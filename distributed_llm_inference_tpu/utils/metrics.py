"""Dependency-free metrics registry with a Prometheus text renderer.

The serving stack's measurement substrate (ISSUE 2): Counter / Gauge /
Histogram families, labeled (`engine` / `route` / `model` / ...), all
thread-safe, rendered two ways from ONE store:

  * `render()` — Prometheus text exposition (served at `GET /metrics`);
  * `snapshot()` — the JSON view (`/stats` sections, bench snapshots).

Both views read the same family objects, so they cannot diverge: every
number in `/stats` that has a Prometheus counterpart is computed from the
same Counter/Gauge/Histogram the exposition renders.

Design notes:
  * No prometheus_client dependency — the container must not grow deps;
    the text format is three line shapes (`# HELP`, `# TYPE`, samples).
  * Histograms use FIXED log-spaced latency buckets (DEFAULT_TIME_BUCKETS)
    so TTFT on a TPU (~ms) and on the CPU fallback (~s) land in resolvable
    buckets from one layout, and bucket layouts never vary per process.
    Each histogram child also keeps a bounded window of raw observations
    (same width as the engine's rolling sample deque) so the JSON view can
    report EXACT p50/p90/p99 over recent traffic while Prometheus gets the
    standard cumulative buckets.
  * Label cardinality is capped per family (default MAX_SERIES): past the
    cap, new label sets collapse into one `"_other_"` series instead of
    growing without bound — an attacker-controlled label (route, model)
    must never be a memory-growth primitive.
  * Registration is get-or-create and idempotent; re-registering a name
    with a different type/labelnames raises (silent reuse would interleave
    two meanings under one exposition family).
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Optional, Sequence

# Log-spaced latency buckets (seconds): sub-ms TPU decode steps through
# multi-minute CPU-fallback requests land in distinct buckets.
DEFAULT_TIME_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)
# Small-integer-count buckets (batch sizes, fleet occupancy).
DEFAULT_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

MAX_SERIES = 64  # label-set cap per family
WINDOW = 256  # raw-observation window per histogram child (matches
# the engine's rolling sample deque, so JSON percentiles line up)

_OTHER = "_other_"  # collapsed label value once a family hits MAX_SERIES


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile, the SAME formula engine.stats() has always
    used — one copy so the JSON and registry views can never disagree."""
    if not values:
        return None
    vals = sorted(values)
    idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
    return round(vals[idx], 4)


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_str(labelnames: tuple, labelvalues: tuple, extra: str = "") -> str:
    parts = [
        f'{n}="{_escape_label(v)}"' for n, v in zip(labelnames, labelvalues)
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Child:
    """One labeled series. All mutation under the family lock."""

    __slots__ = ("_family",)

    def __init__(self, family: "_Family"):
        self._family = family


class CounterChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, family):
        super().__init__(family)
        self._value = 0.0

    def inc(self, n: float = 1.0):
        if n < 0:
            raise ValueError("counters only go up")
        with self._family._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._family._lock:
            return self._value


class GaugeChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, family):
        super().__init__(family)
        self._value = 0.0

    def set(self, v: float):
        with self._family._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0):
        with self._family._lock:
            self._value += n

    def dec(self, n: float = 1.0):
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._family._lock:
            return self._value


class HistogramChild(_Child):
    __slots__ = ("_bucket_counts", "_sum", "_count", "_window",
                 "_exemplars")

    def __init__(self, family):
        super().__init__(family)
        self._bucket_counts = [0] * (len(family.buckets) + 1)  # +Inf last
        self._sum = 0.0
        self._count = 0
        self._window = collections.deque(maxlen=WINDOW)
        # bucket index -> (trace_id, value, ts): the most recent traced
        # observation per bucket, so a p99 bucket links to one concrete
        # inspectable trace (GET /debug/traces/{trace_id}). Bounded by
        # construction (<= len(buckets)+1 entries); exposed in the JSON
        # snapshot, not the text exposition (the 0.0.4 format has no
        # exemplar syntax).
        self._exemplars: dict = {}

    def observe(self, v: float, trace_id: Optional[str] = None):
        v = float(v)
        with self._family._lock:
            i = 0
            buckets = self._family.buckets
            while i < len(buckets) and v > buckets[i]:
                i += 1
            self._bucket_counts[i] += 1
            self._sum += v
            self._count += 1
            self._window.append(v)
            if trace_id is not None:
                self._exemplars[i] = (trace_id, v, time.time())

    def exemplars(self) -> dict:
        """{bucket_le: {trace_id, value, ts}} for buckets that have seen
        a traced observation."""
        with self._family._lock:
            items = dict(self._exemplars)
        les = tuple(self._family.buckets) + (math.inf,)
        return {
            _fmt(les[i]): {
                "trace_id": t, "value": round(v, 6), "ts": round(ts, 3),
            }
            for i, (t, v, ts) in sorted(items.items())
        }

    @property
    def count(self) -> int:
        with self._family._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._family._lock:
            return self._sum

    def window_values(self) -> list:
        with self._family._lock:
            return list(self._window)

    def percentile(self, q: float) -> Optional[float]:
        """Exact nearest-rank percentile over the recent-observation
        window — the number /stats reports for this series."""
        return percentile(self.window_values(), q)


_CHILD_TYPES = {
    "counter": CounterChild,
    "gauge": GaugeChild,
    "histogram": HistogramChild,
}


class _Family:
    """One metric family: a name, a type, and its labeled children."""

    def __init__(self, name: str, mtype: str, help_: str,
                 labelnames: tuple, buckets: Optional[tuple],
                 max_series: int):
        self.name = name
        self.type = mtype
        self.help = help_
        self.labelnames = labelnames
        self.buckets = tuple(float(b) for b in (buckets or ()))
        self.max_series = max_series
        self._lock = threading.Lock()
        self._children: "collections.OrderedDict[tuple, _Child]" = (
            collections.OrderedDict()
        )

    def labels(self, **labelvalues):
        got = tuple(sorted(labelvalues))
        if got != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {got}"
            )
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.max_series:
                    # cardinality cap: collapse into one overflow series
                    key = (_OTHER,) * len(self.labelnames)
                    child = self._children.get(key)
                    if child is None:
                        child = _CHILD_TYPES[self.type](self)
                        self._children[key] = child
                else:
                    child = _CHILD_TYPES[self.type](self)
                    self._children[key] = child
            return child

    def _items(self):
        with self._lock:
            return list(self._children.items())

    # -- rendering -----------------------------------------------------------
    def render_lines(self) -> list:
        out = []
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.type}")
        for key, child in self._items():
            if self.type in ("counter", "gauge"):
                out.append(
                    f"{self.name}{_labels_str(self.labelnames, key)} "
                    f"{_fmt(child.value)}"
                )
                continue
            with self._lock:
                counts = list(child._bucket_counts)
                total, s = child._count, child._sum
            cum = 0
            for b, c in zip(self.buckets + (math.inf,), counts):
                cum += c
                le = f'le="{_fmt(b)}"'
                out.append(
                    f"{self.name}_bucket"
                    f"{_labels_str(self.labelnames, key, le)} {cum}"
                )
            out.append(
                f"{self.name}_sum{_labels_str(self.labelnames, key)} "
                f"{_fmt(s)}"
            )
            out.append(
                f"{self.name}_count{_labels_str(self.labelnames, key)} "
                f"{total}"
            )
        return out

    def snapshot(self) -> dict:
        series = []
        for key, child in self._items():
            entry = {"labels": dict(zip(self.labelnames, key))}
            if self.type in ("counter", "gauge"):
                entry["value"] = child.value
            else:
                entry["count"] = child.count
                entry["sum"] = round(child.sum, 6)
                entry["p50"] = child.percentile(0.5)
                entry["p90"] = child.percentile(0.9)
                entry["p99"] = child.percentile(0.99)
                ex = child.exemplars()
                if ex:
                    entry["exemplars"] = ex
            series.append(entry)
        return {"type": self.type, "help": self.help, "series": series}


class MetricsRegistry:
    """Get-or-create registry of metric families.

    Each serving process typically owns ONE registry reachable from the
    engine (`engine.metrics`); the queue / continuous engine / prefix
    cache / constraint table all register into it so `GET /metrics`
    covers the whole stack in one scrape.
    """

    def __init__(self, max_series: int = MAX_SERIES):
        self._lock = threading.Lock()
        self._families: "collections.OrderedDict[str, _Family]" = (
            collections.OrderedDict()
        )
        self.max_series = max_series

    def _register(self, name: str, mtype: str, help_: str,
                  labelnames: Sequence[str], buckets=None) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != mtype or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.type}{fam.labelnames}, not "
                        f"{mtype}{labelnames}"
                    )
                return fam
            fam = _Family(
                name, mtype, help_, labelnames, buckets, self.max_series
            )
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._register(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._register(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS) -> _Family:
        return self._register(name, "histogram", help, labelnames, buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> list:
        with self._lock:
            return list(self._families.values())

    def render(self) -> str:
        """Prometheus text exposition (format version 0.0.4)."""
        lines = []
        for fam in self.families():
            lines.extend(fam.render_lines())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """The JSON view over the same families the exposition renders."""
        return {f.name: f.snapshot() for f in self.families()}


def latency_summary(registry: MetricsRegistry) -> dict:
    """Compact benchmark-facing summary of the latency histograms
    ({metric: {engine: {p50, p90, p99, count}}}) plus the occupancy
    gauges — the `metrics` section of the bench JSON lines, so BENCH_*
    rounds capture percentile signal, not just aggregate tok/s."""
    out: dict = {}
    for name in (
        "dli_ttft_seconds", "dli_tpot_seconds",
        "dli_request_duration_seconds", "dli_decode_step_seconds",
    ):
        fam = registry.get(name)
        if fam is None:
            continue
        block = {}
        for s in fam.snapshot()["series"]:
            if s["count"]:
                label = s["labels"].get("engine") or "_"
                block[label] = {
                    "p50": s["p50"], "p90": s["p90"], "p99": s["p99"],
                    "count": s["count"],
                }
        if block:
            out[name] = block
    for name in (
        "dli_slots_total", "dli_slots_occupied", "dli_kv_pool_blocks_free",
        "dli_kv_pool_shared_blocks",
    ):
        fam = registry.get(name)
        if fam is not None:
            for s in fam.snapshot()["series"]:
                out[name] = s["value"]
    return out


# Process-global default for callers with no engine in reach (none of the
# serving stack uses it — each engine owns its registry — but library
# users get a working default).
REGISTRY = MetricsRegistry()
