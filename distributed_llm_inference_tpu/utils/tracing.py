"""Per-request stage tracing: request ids + host-side span breakdowns.

Every request gets a `Trace` carrying a `request_id` (client-supplied via
the `X-Request-Id` header, or generated) and an ordered set of stage
spans — queue_wait, constraint_compile, admission, prefill, decode,
detokenize — recorded as HOST-side timestamps only. Nothing here crosses
into traced XLA code: a checkpoint is a `time.perf_counter()` read around
an already-host-blocking boundary (block_until_ready, a queue pop), so
the no-host-callback discipline of the compiled decode loops is untouched.

The span model is CONTIGUOUS: `checkpoint(name)` attributes the time
since the previous checkpoint (or trace creation) to `name`, so the spans
sum to ≈ the end-to-end latency by construction — the property that makes
a `timings` breakdown trustworthy for "where did this slow request spend
its time". Repeated checkpoints under one name accumulate (a chunked
decode records one growing `decode` span, not N).

The breakdown is returned in each response's `timings` field and logged
as one structured `request_done` event (utils/logging.py attaches the
request_id to every record logged inside `request_id_context`).
"""

from __future__ import annotations

import collections
import re
import threading
import time
import uuid
from typing import Optional

_SAFE_ID = re.compile(r"^[A-Za-z0-9_\-\.:]{1,128}$")


def new_request_id() -> str:
    return "req-" + uuid.uuid4().hex[:20]


def sanitize_request_id(raw) -> Optional[str]:
    """A client-supplied id, or None if absent/unusable. Constrained to a
    safe charset + length: the id is echoed into headers, logs, and
    metrics-adjacent output — it must never be an injection vector."""
    if not isinstance(raw, str):
        return None
    raw = raw.strip()
    return raw if _SAFE_ID.match(raw) else None


class Trace:
    """Ordered, contiguous stage spans for one request."""

    __slots__ = ("request_id", "_t0", "_last", "_spans", "_lock")

    def __init__(self, request_id: Optional[str] = None):
        self.request_id = request_id or new_request_id()
        now = time.perf_counter()
        self._t0 = now
        self._last = now
        self._spans: "collections.OrderedDict[str, float]" = (
            collections.OrderedDict()
        )
        # a deadline-abandoned generation keeps checkpointing from its
        # daemon thread while the caller reads timings(): cheap lock
        self._lock = threading.Lock()

    def checkpoint(self, name: str) -> float:
        """Attribute time since the last checkpoint to span `name`."""
        now = time.perf_counter()
        with self._lock:
            dur = now - self._last
            self._last = now
            self._spans[name] = self._spans.get(name, 0.0) + dur
        return dur

    def add(self, name: str, seconds: float):
        """Record an externally-measured span (e.g. a queue wait measured
        by the dispatcher on another thread)."""
        with self._lock:
            self._spans[name] = self._spans.get(name, 0.0) + float(seconds)

    def spans(self) -> dict:
        with self._lock:
            return dict(self._spans)

    def timings(self) -> dict:
        """`{"<span>_s": dur, ..., "total_s": wall}` in chronological span
        order. Spans sum to ≈ total_s (the unspanned tail is whatever ran
        after the last checkpoint — response assembly, envelope fill)."""
        now = time.perf_counter()
        with self._lock:
            out = {f"{k}_s": round(v, 6) for k, v in self._spans.items()}
            out["total_s"] = round(now - self._t0, 6)
        return out
