"""Per-request stage tracing: request ids + host-side span breakdowns.

Every request gets a `Trace` carrying a `request_id` (client-supplied via
the `X-Request-Id` header, or generated) and an ordered set of stage
spans — queue_wait, constraint_compile, admission, prefill, decode,
detokenize — recorded as HOST-side timestamps only. Nothing here crosses
into traced XLA code: a checkpoint is a `time.perf_counter()` read around
an already-host-blocking boundary (block_until_ready, a queue pop), so
the no-host-callback discipline of the compiled decode loops is untouched.

The span model is CONTIGUOUS: `checkpoint(name)` attributes the time
since the previous checkpoint (or trace creation) to `name`, so the spans
sum to ≈ the end-to-end latency by construction — the property that makes
a `timings` breakdown trustworthy for "where did this slow request spend
its time". Repeated checkpoints under one name accumulate (a chunked
decode records one growing `decode` span, not N).

The breakdown is returned in each response's `timings` field and logged
as one structured `request_done` event (utils/logging.py attaches the
request_id to every record logged inside `request_id_context`).

Fleet-wide tracing (ISSUE 17) grows this module from stage timer to span
tree: W3C-style `traceparent` ids (`SpanContext`, parse/format helpers)
propagate across every inter-process hop — client → router dispatch /
failover attempts → replica → KV-fabric pulls → prefill→decode handoff —
and each process records spans into its bounded in-memory store
(serving/trace_store.TraceStore). The `Trace` stage timer now also keeps
absolute-timestamped segments so a finished request's contiguous stage
breakdown can be exported as child spans of the replica's request span
with real wall-clock bounds. A `FlightRecorder` (bounded ring of
control-plane events) lives here too: engine-side code records
admissions, scheduler plans, preemptions, fabric fetches and restarts
into it; the supervisor dumps it into crash reports and
`GET /debug/flight` serves it live.

Everything here stays strictly host-side: nothing crosses into traced
XLA code, and the launch-level attribution the continuous engine records
under `engine_cfg.trace_sample_rate` is host timestamps keyed by launch
seq — never an extra device sync.
"""

from __future__ import annotations

import collections
import re
import threading
import time
import uuid
from typing import Optional

_SAFE_ID = re.compile(r"^[A-Za-z0-9_\-\.:]{1,128}$")

# W3C traceparent: version "00", 32-hex trace id, 16-hex parent span id,
# 2-hex flags (bit 0 = sampled). The all-zero ids are invalid per spec.
_TRACEPARENT = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def new_request_id() -> str:
    return "req-" + uuid.uuid4().hex[:20]


def sanitize_request_id(raw) -> Optional[str]:
    """A client-supplied id, or None if absent/unusable. Constrained to a
    safe charset + length: the id is echoed into headers, logs, and
    metrics-adjacent output — it must never be an injection vector."""
    if not isinstance(raw, str):
        return None
    raw = raw.strip()
    return raw if _SAFE_ID.match(raw) else None


# -- W3C-style trace context -------------------------------------------------
def new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex chars


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class SpanContext:
    """One hop's trace context: the trace id, the CURRENT span id (the
    parent of anything started under this context), and the sampled flag.
    Immutable by convention; `child()` derives the next hop's context."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)

    @classmethod
    def new_root(cls, sampled: bool = True) -> "SpanContext":
        return cls(new_trace_id(), new_span_id(), sampled)

    def child(self, span_id: Optional[str] = None) -> "SpanContext":
        return SpanContext(
            self.trace_id, span_id or new_span_id(), self.sampled
        )

    def header(self) -> str:
        """The `traceparent` header value for the NEXT hop (this
        context's span id is the downstream parent)."""
        return (
            f"00-{self.trace_id}-{self.span_id}-"
            f"{'01' if self.sampled else '00'}"
        )

    def __repr__(self):  # debug output only
        return f"SpanContext({self.header()})"


def parse_traceparent(raw) -> Optional[SpanContext]:
    """Parse an inbound `traceparent` header; None on absent/malformed
    (the hop then starts a fresh root — propagation degrades, never
    errors). Only version 00 is accepted; all-zero ids are invalid."""
    if not isinstance(raw, str):
        return None
    m = _TRACEPARENT.match(raw.strip().lower())
    if not m:
        return None
    trace_id, span_id, flags = m.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id, bool(int(flags, 16) & 1))


def sample_decision(trace_id: str, rate: float) -> bool:
    """Deterministic per-trace sampling for launch-level profiling
    (engine_cfg.trace_sample_rate): a pure function of the trace id — no
    RNG on the hot path, and every process agrees on the decision.
    rate <= 0 never samples; rate >= 1 always does."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return int(trace_id[:8], 16) / float(0x100000000) < rate


_MAX_SEGMENTS = 256  # bounded per-request segment log (span-tree export)


class Trace:
    """Ordered, contiguous stage spans for one request."""

    __slots__ = ("request_id", "_t0", "_wall0", "_last", "_spans",
                 "_segments", "_lock")

    def __init__(self, request_id: Optional[str] = None):
        self.request_id = request_id or new_request_id()
        now = time.perf_counter()
        self._t0 = now
        # wall-clock anchor for absolute span export: abs(t) =
        # _wall0 + (t - _t0). One pair read at construction so the
        # perf_counter deltas (monotonic, the timing source of record)
        # map onto a wall timeline consistent across processes to within
        # clock skew.
        self._wall0 = time.time()
        self._last = now
        self._spans: "collections.OrderedDict[str, float]" = (
            collections.OrderedDict()
        )
        # absolute-timestamped (name, start, end) segments, bounded — the
        # span-tree export reads these; the contiguous accumulator above
        # stays the `timings` source so the two views cannot diverge on
        # totals
        self._segments: collections.deque = collections.deque(
            maxlen=_MAX_SEGMENTS
        )
        # a deadline-abandoned generation keeps checkpointing from its
        # daemon thread while the caller reads timings(): cheap lock
        self._lock = threading.Lock()

    def checkpoint(self, name: str) -> float:
        """Attribute time since the last checkpoint to span `name`."""
        now = time.perf_counter()
        with self._lock:
            dur = now - self._last
            self._segments.append((name, self._last, now))
            self._last = now
            self._spans[name] = self._spans.get(name, 0.0) + dur
        return dur

    def add(self, name: str, seconds: float):
        """Record an externally-measured span (e.g. a queue wait measured
        by the dispatcher on another thread)."""
        with self._lock:
            self._spans[name] = self._spans.get(name, 0.0) + float(seconds)

    def spans(self) -> dict:
        with self._lock:
            return dict(self._spans)

    def segments(self) -> list:
        """[(name, start_wall, end_wall)] — the absolute-timestamped
        stage segments, chronological. The span-tree export turns these
        into child spans of the process's request span."""
        with self._lock:
            off = self._wall0 - self._t0
            return [(n, a + off, b + off) for n, a, b in self._segments]

    @property
    def start_wall(self) -> float:
        return self._wall0

    def timings(self) -> dict:
        """`{"<span>_s": dur, ..., "total_s": wall}` in chronological span
        order. Spans sum to ≈ total_s (the unspanned tail is whatever ran
        after the last checkpoint — response assembly, envelope fill)."""
        now = time.perf_counter()
        with self._lock:
            out = {f"{k}_s": round(v, 6) for k, v in self._spans.items()}
            out["total_s"] = round(now - self._t0, 6)
        return out


class FlightRecorder:
    """Bounded ring of recent control-plane events for one engine.

    The crash-forensics companion of the span store: admissions,
    scheduler plans (budget splits), preemptions, fabric fetches,
    quarantines and restarts append here as cheap host-side dicts; the
    ring is dumped into the supervisor's crash report, served live at
    `GET /debug/flight`, and persisted next to `--restore-dir` on a
    crash — so a poison-quarantine or restart-loop episode is
    reconstructable after the fact. Strictly host-side control-plane
    code; never called from anywhere decode-launch-adjacent except
    behind the existing per-event seams (admission, plan, preempt,
    fetch, restart), all of which already do host work."""

    __slots__ = ("_events", "_lock", "_seq", "capacity")

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity
        )
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, kind: str, **fields):
        """Append one event. `fields` must already be JSON-safe scalars
        (the dump is json.dumps'd into crash reports verbatim)."""
        with self._lock:
            self._seq += 1
            ev = {"seq": self._seq, "ts": round(time.time(), 6),
                  "kind": kind}
            if fields:
                ev.update(fields)
            self._events.append(ev)

    def events(self, limit: Optional[int] = None) -> list:
        with self._lock:
            out = list(self._events)
        return out[-limit:] if limit else out

    def dump(self) -> dict:
        """The /debug/flight + crash-report payload."""
        events = self.events()
        return {
            "capacity": self.capacity,
            "recorded_total": self._seq,
            "events": events,
        }
