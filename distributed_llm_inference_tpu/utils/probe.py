"""Device liveness probing for the /workers health sweep.

The reference's /workers actually polls each worker's /health over HTTP
with a 5 s timeout and reports online / offline / error
(/root/reference/orchestration.py:306-329). A mesh stage is an in-process
device slice, so the equivalent probe is a tiny timed device op: round-trip
one scalar through the device and report how long it took. A wedged device
(hung transfer queue, dead tunnel) is reported "offline" after the timeout
instead of hanging the health endpoint.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp


def probe_device(dev, timeout_s: float = 5.0, _op=None) -> dict:
    """One device's liveness: {"status": online|offline|error, ...}.

    online  -> includes probe_ms (scalar round-trip time)
    error   -> the op raised; includes the error string
    offline -> the op did not complete within timeout_s (probe thread is
               abandoned — it cannot be killed, but it is daemonic)
    """
    result: dict = {}

    def run():
        try:
            t0 = time.perf_counter()
            if _op is not None:
                _op()
            else:
                x = jax.device_put(jnp.int32(1), dev)
                jax.block_until_ready(x + 1)
            result.update(
                status="online",
                probe_ms=round((time.perf_counter() - t0) * 1e3, 2),
            )
        except Exception as e:  # noqa: BLE001 - health must not raise
            result.update(status="error", error=str(e)[:300])

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    if not result:
        return {
            "status": "offline",
            "error": f"device probe timed out after {timeout_s:.1f}s",
        }
    return result
