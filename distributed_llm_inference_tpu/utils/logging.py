"""Structured JSON-lines logging.

The reference logs with emoji print() banners throughout
(/root/reference/orchestration.py:74-76, Worker1.py:84-87 — SURVEY.md §5
metrics/logging). Here every log record is one JSON object on stderr
(machine-parseable, greppable), with arbitrary structured fields:

    log = get_logger("engine")
    log.info("request", model="tinyllama-1.1b", tokens=20, ttft_s=0.01)

Stdout stays clean for tool output (bench.py's single JSON line, the
client CLI).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import sys
import time
from typing import Any, Optional

_CONFIGURED = False

# Current request id (utils/tracing.Trace): set around a request's
# processing so every record logged inside — engine internals included,
# with no plumbing — carries the id for cross-service correlation.
_REQUEST_ID: contextvars.ContextVar = contextvars.ContextVar(
    "request_id", default=None
)
# Current W3C trace id (utils/tracing.SpanContext): same contract as the
# request id, set by the serving edges (router POST handling, replica
# request handling, fabric code paths) so router- and fabric-side log
# records carry the fleet-wide trace id too — not just the engine side.
_TRACE_ID: contextvars.ContextVar = contextvars.ContextVar(
    "trace_id", default=None
)


def set_request_id(rid: Optional[str]):
    """Set (rid) or clear (None) the context's request id; returns the
    token for contextvars reset."""
    return _REQUEST_ID.set(rid)


def get_request_id() -> Optional[str]:
    return _REQUEST_ID.get()


def get_trace_id() -> Optional[str]:
    return _TRACE_ID.get()


@contextlib.contextmanager
def request_id_context(rid: Optional[str], trace_id: Optional[str] = None):
    token = _REQUEST_ID.set(rid)
    t_token = _TRACE_ID.set(trace_id) if trace_id is not None else None
    try:
        yield
    finally:
        if t_token is not None:
            _TRACE_ID.reset(t_token)
        _REQUEST_ID.reset(token)


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "event": record.getMessage(),
        }
        rid = _REQUEST_ID.get()
        if rid is not None:
            out["request_id"] = rid
        tid = _TRACE_ID.get()
        if tid is not None:
            out["trace_id"] = tid
        fields = getattr(record, "fields", None)
        if fields:
            out.update(fields)  # an explicit request_id field wins
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class StructuredLogger:
    """Thin wrapper adding **fields kwargs to the stdlib logger."""

    def __init__(self, logger: logging.Logger):
        self._logger = logger

    def _log(self, level: int, event: str, exc_info=None, **fields: Any):
        if self._logger.isEnabledFor(level):
            self._logger.log(level, event, extra={"fields": fields}, exc_info=exc_info)

    def debug(self, event: str, **fields):
        self._log(logging.DEBUG, event, **fields)

    def info(self, event: str, **fields):
        self._log(logging.INFO, event, **fields)

    def warning(self, event: str, **fields):
        self._log(logging.WARNING, event, **fields)

    def error(self, event: str, exc_info=None, **fields):
        self._log(logging.ERROR, event, exc_info=exc_info, **fields)


def configure(level: int = logging.INFO, stream=None) -> None:
    """Install the JSON handler on the package root logger.

    The handler is installed exactly once, but the LEVEL applies on every
    call: a repeat `configure(logging.DEBUG)` (an operator turning on
    verbosity at runtime) updates the root level instead of being
    silently ignored.
    """
    global _CONFIGURED
    root = logging.getLogger("distributed_llm_inference_tpu")
    root.setLevel(level)
    if _CONFIGURED:
        return
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(_JsonFormatter())
    root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str) -> StructuredLogger:
    """Library-safe: does NOT install handlers — records propagate to the
    host application's logging config by default. Entry points (the server
    CLI) call configure() to get the JSON-lines handler."""
    return StructuredLogger(
        logging.getLogger(f"distributed_llm_inference_tpu.{name}")
    )
