"""Tokenizers.

The reference holds an `AutoTokenizer` on the orchestrator
(/root/reference/orchestration.py:34) and requires hub access at boot. Here
the HF tokenizer is optional (used when a local checkpoint/cache exists) and
a dependency-free byte-level tokenizer is the offline fallback, so the whole
serving stack runs with zero network egress (tests, CI, air-gapped TPU pods).
"""

from __future__ import annotations

from typing import Optional, Sequence


class ByteTokenizer:
    """Reversible byte-level tokenizer: id = byte + 3; 0/1/2 = pad/bos/eos.

    Vocab of 259 fits any model config with vocab_size >= 259; for tiny test
    configs it simply never emits ids above 258.
    """

    OFFSET = 3

    def __init__(self, pad_id: int = 0, bos_id: int = 1, eos_id: int = 2):
        self.pad_token_id = pad_id
        self.bos_token_id = bos_id
        self.eos_token_id = eos_id

    @property
    def vocab_size(self) -> int:
        return 256 + self.OFFSET

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [b + self.OFFSET for b in text.encode("utf-8")]
        return [self.bos_token_id] + ids if add_bos else ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        data = bytes(
            i - self.OFFSET for i in ids if i >= self.OFFSET and i < 256 + self.OFFSET
        )
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Thin wrapper over a transformers tokenizer (local files only)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name_or_path)
        self.pad_token_id = (
            self._tok.pad_token_id
            if self._tok.pad_token_id is not None
            else self._tok.eos_token_id
        )
        self.bos_token_id = self._tok.bos_token_id
        self.eos_token_id = self._tok.eos_token_id

    @property
    def vocab_size(self) -> int:
        return self._tok.vocab_size

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    @property
    def has_chat_template(self) -> bool:
        return bool(getattr(self._tok, "chat_template", None))

    def apply_chat_template(self, messages: list) -> str:
        """Render [{role, content}, ...] through the tokenizer's own jinja
        chat template (the one the checkpoint shipped with), ending with
        the assistant generation header."""
        return self._tok.apply_chat_template(
            messages, tokenize=False, add_generation_prompt=True
        )


def load_tokenizer(
    name_or_path: Optional[str] = None,
    *,
    pad_id=0,
    bos_id=1,
    eos_id=2,
    strict: bool = False,
):
    """HF tokenizer when a local path/cache resolves; byte fallback otherwise.

    strict=True re-raises on a failed explicit path instead of silently
    degrading to bytes (serving with the wrong tokenizer produces garbled
    output with status 'success' — a deployment should fail loudly).
    """
    if name_or_path:
        try:
            return HFTokenizer(name_or_path)
        except Exception as e:
            if strict:
                raise
            import logging

            logging.getLogger(__name__).warning(
                "tokenizer '%s' failed to load (%s); falling back to ByteTokenizer",
                name_or_path,
                e,
            )
    return ByteTokenizer(pad_id=pad_id, bos_id=bos_id, eos_id=eos_id)
