"""Shared HTTP retry/backoff policy for every upstream caller.

One copy of the discipline the serving edge's clients must agree on —
the interactive client (client.py) and the router tier's upstream calls
(serving/router.py) used to need identical Retry-After parsing and
jittered exponential backoff, and duplicated logic is how the two ends
of a retry loop drift apart:

  * 429 (shed load) and 503 (draining replica / deadline / restarting
    scheduler) are the two RETRYABLE statuses the serving edge hands
    out — anything else (400, 500 incl. poison) is the caller's bug or
    a server fault that every retry would hit again.
  * A parseable Retry-After header is SERVER-DIRECTED delay and always
    wins over local backoff: the server knows its own drain/overload
    horizon, the client does not.
  * Local backoff is exponential with FULL JITTER on the upper half, so
    a herd of retrying clients decorrelates instead of re-stampeding.
"""

from __future__ import annotations

import random
from typing import Optional

# the two retryable statuses the serving edge emits (see
# serving/server.py's envelope -> status mapping)
RETRY_STATUSES = (429, 503)

# ceiling on any locally computed delay (seconds)
BACKOFF_CAP_S = 8.0


# jaxlint: decode-unreachable -- client-side policy helper; no in-package caller
def is_retryable(status: int) -> bool:
    """True for the statuses a well-behaved caller may retry blindly."""
    return status in RETRY_STATUSES


def parse_retry_after(value) -> Optional[float]:
    """Seconds from a Retry-After header value, or None when absent or
    unparseable (the HTTP-date form and junk both fall back to local
    backoff — guessing at a malformed server hint is worse than jitter).
    Negative values clamp to 0 (retry immediately)."""
    if value is None:
        return None
    try:
        return max(0.0, float(value))
    except (TypeError, ValueError):
        return None


def backoff_delay(attempt: int, base_s: float = 0.5,
                  cap_s: float = BACKOFF_CAP_S, rng=None) -> float:
    """Jittered exponential delay for the `attempt`-th retry (0-based):
    uniformly drawn from the upper half of min(cap, base * 2^attempt)."""
    upper = min(cap_s, base_s * (2 ** attempt))
    r = (rng or random).random()
    return upper * (0.5 + r / 2)


def retry_delay(attempt: int, retry_after=None, base_s: float = 0.5,
                cap_s: float = BACKOFF_CAP_S, rng=None) -> float:
    """The delay before the `attempt`-th retry: the server-directed
    Retry-After when it parses, else jittered exponential backoff."""
    ra = parse_retry_after(retry_after)
    if ra is not None:
        return ra
    return backoff_delay(attempt, base_s=base_s, cap_s=cap_s, rng=rng)


def overload_retry_after(depth: int, per_cycle: int = 1,
                         cap_s: float = BACKOFF_CAP_S) -> int:
    """Queue-depth-derived Retry-After hint (whole seconds, >= 1) for a
    shed-load rejection: roughly one second per dispatch cycle the
    backlog needs to clear (`depth / per_cycle`), bounded by `cap_s`.
    Deliberately coarse — the point is that a deeper backlog tells
    clients to stay away LONGER, so their backoff is server-directed
    instead of uniformly hammering an overloaded queue."""
    cycles = depth // max(1, int(per_cycle)) + 1
    return int(min(cap_s, float(cycles)))
