"""Deterministic host-side fault injection for the serving stack.

The reference repo's only failure story is a 30s hop timeout and a
re-run of the notebook; our continuous scheduler now survives crashes
(engine/continuous.py supervisor), but a recovery path that is never
exercised is a recovery path that does not work. This module plants
NAMED injection points through the scheduler's host loop so every
containment path runs in CI, deterministically:

    admission      _admit_one entry, before any resource grant
    alloc          the paged-pool block grant, before the shared-head
                   incref (a raise here must not leak references)
    prefill        just inside the admission try block, before the
                   scratch prefill / chunked ingest (resources granted;
                   the BaseException handler must release them)
    decode_launch  before a decode chunk launch
    fetch          before a chunk's device->host fetch
    shadow_copy    the warm-recovery shadow store (engine/shadow.py):
                   before a filled-block device->host capture is
                   dispatched (tag = the request's prompt) and before a
                   rebuilt pool restores shadowed blocks (tag
                   "restore" — the crash-during-restore double-fault
                   drill)
    solo           the solo engine's generation path, inside the
                   deadline wrapper (engine._generate_locked) — the
                   wedge drill for /ready-driven router ejection: a
                   wedge_s > deadline rule leaves an abandoned device
                   call in engine._wedged until the sleep drains
    preempt        SLO-aware KV preemption (engine/continuous.
                   _preempt_for): after the victim is selected, before
                   any of its state is touched (tag = the victim's
                   prompt) — the crash-during-preempt chaos drill
    stage_send     the MPMD stage transport (serving/stage_runtime.py):
                   before a cross-process activation/token hand-off is
                   shipped to the next stage (tag =
                   "{request_id}:{phase}:stage{i}") — drop/delay/wedge
                   the inter-stage wire deterministically
    stage_recv     the receiving side of the same hand-off: inside the
                   stage server's /stage/step handler before compute,
                   and inside the heartbeat handler (tag
                   "heartbeat:stage{i}" — a wedge rule here is the
                   heartbeat-timeout → unready drill)

Design rules:
  * Zero overhead disarmed: check() is one module-global None test.
    Production never pays for the harness.
  * Deterministic: triggers are per-point CALL COUNTERS (fail on the
    Nth call, then every Mth, at most `times` firings), never wall
    clock; the optional probabilistic mode draws from a seeded
    random.Random so a chaos run replays identically under
    pytest-randomly or a CI retry.
  * Strictly host-side: nothing here is referenced from any jit root —
    tests/test_analysis.py pins that with a callgraph fixture, so the
    compiled-decode invariants (analysis/) cannot regress through the
    harness. The wedge sleep below is exactly the kind of host sync the
    hot-path lint exists to catch; it stays legal only because these
    hooks live in the scheduler's host loop.

Arming: tests call arm([FaultRule(...), ...]); operators use the server
`--faults SPEC` flag or the DLI_FAULTS env var (server.main calls
arm_from_env()). SPEC grammar, semicolon-separated rules:

    point:kind[:k=v[,k=v...]]
    e.g.  decode_launch:transient:on=3
          prefill:fatal:match=POISON,times=0
          fetch:transient:on=2,every=4,times=3,wedge=0.5
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

POINTS = (
    "admission", "prefill", "decode_launch", "fetch", "alloc",
    "shadow_copy", "solo", "preempt", "stage_send", "stage_recv",
)


class FaultError(RuntimeError):
    """Base class for injected faults (never raised by real code)."""


class TransientFault(FaultError):
    """Simulated transient device/runtime error (RESOURCE_EXHAUSTED-like):
    the operation would succeed if retried after a restart."""


class FatalFault(FaultError):
    """Simulated hard failure: every retry fails too (the supervisor's
    restart budget is what bounds the damage)."""


@dataclass
class FaultRule:
    """One armed trigger at one injection point.

    Fires on the `on_call`-th MATCHING call (1-based), then every
    `every`-th call after that (0 = only the on_call firing window), at
    most `times` total firings (0 = unlimited). `match` restricts the
    rule to calls whose tag contains the substring — the poison-request
    targeting hook (the scheduler tags admission/prefill checks with the
    request's prompt). `wedge_s` sleeps before raising, simulating a
    call that wedges the runtime before dying. `p` < 1.0 fires
    probabilistically from a random.Random(seed) stream (deterministic
    per rule instance).
    """

    point: str
    kind: str = "transient"  # "transient" | "fatal"
    on_call: int = 1
    every: int = 0
    times: int = 1
    wedge_s: float = 0.0
    match: str = ""
    p: float = 1.0
    seed: int = 0
    calls: int = field(default=0, repr=False)
    fired: int = field(default=0, repr=False)
    _rng: Optional[random.Random] = field(default=None, repr=False)

    def __post_init__(self):
        if self.point not in POINTS:
            raise ValueError(
                f"unknown fault point {self.point!r}; known: {POINTS}"
            )
        if self.kind not in ("transient", "fatal"):
            raise ValueError(
                f"fault kind must be 'transient' or 'fatal', got {self.kind!r}"
            )
        if self.on_call < 1:
            raise ValueError("on_call is 1-based (first matching call = 1)")
        if self.p < 1.0:
            self._rng = random.Random(self.seed)

    def should_fire(self, tag: str) -> bool:
        """Count this call; True when the rule fires on it."""
        if self.match and self.match not in tag:
            return False
        self.calls += 1
        if self.times and self.fired >= self.times:
            return False
        n = self.calls
        due = n == self.on_call or (
            self.every > 0 and n > self.on_call
            and (n - self.on_call) % self.every == 0
        )
        if not due:
            return False
        if self._rng is not None and self._rng.random() >= self.p:
            return False
        self.fired += 1
        return True

    def raise_fault(self):
        if self.wedge_s > 0:
            time.sleep(self.wedge_s)
        cls = FatalFault if self.kind == "fatal" else TransientFault
        detail = "simulated fatal fault" if self.kind == "fatal" else \
            "RESOURCE_EXHAUSTED: simulated transient fault"
        raise cls(f"{detail} at {self.point!r} (call {self.calls})")


class FaultPlan:
    """A set of armed rules + thread-safe counters (the scheduler worker,
    test threads, and HTTP handler threads may all hit check())."""

    def __init__(self, rules):
        self._lock = threading.Lock()
        self.rules = list(rules)
        self._by_point: dict = {}
        for r in self.rules:
            self._by_point.setdefault(r.point, []).append(r)

    def check(self, point: str, tag: str = ""):
        rules = self._by_point.get(point)
        if not rules:
            return
        with self._lock:
            due = [r for r in rules if r.should_fire(tag)]
        if due:
            due[0].raise_fault()

    def fired(self, point: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                r.fired for r in self.rules
                if point is None or r.point == point
            )


_PLAN: Optional[FaultPlan] = None


def arm(rules) -> FaultPlan:
    """Arm a plan from FaultRule instances or a SPEC string (see module
    docstring). Replaces any existing plan; returns it (tests read
    plan.fired())."""
    global _PLAN
    if isinstance(rules, str):
        rules = parse_spec(rules)
    _PLAN = FaultPlan(rules)
    return _PLAN


# jaxlint: decode-unreachable -- test-harness surface: only conftest/tests call it
def disarm():
    global _PLAN
    _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


def check(point: str, tag: str = ""):
    """The injection point. ONE global None test when disarmed — the
    only cost production code ever pays."""
    plan = _PLAN
    if plan is None:
        return
    plan.check(point, tag)


_FLOAT_KEYS = ("wedge", "p")
_INT_KEYS = ("on", "every", "times", "seed")
_KEY_MAP = {
    "on": "on_call", "every": "every", "times": "times",
    "wedge": "wedge_s", "match": "match", "p": "p", "seed": "seed",
}


def parse_spec(spec: str) -> list:
    """'point:kind[:k=v,...];...' -> [FaultRule, ...]. Raises ValueError
    with the offending fragment on malformed input (server startup should
    fail loudly, not arm a half-parsed plan)."""
    rules = []
    for frag in spec.split(";"):
        frag = frag.strip()
        if not frag:
            continue
        parts = frag.split(":", 2)
        if len(parts) < 2:
            raise ValueError(f"fault spec {frag!r}: need point:kind[:opts]")
        kw: dict = {"point": parts[0].strip(), "kind": parts[1].strip()}
        if len(parts) == 3 and parts[2].strip():
            for opt in parts[2].split(","):
                if "=" not in opt:
                    raise ValueError(f"fault spec option {opt!r}: need k=v")
                k, v = (s.strip() for s in opt.split("=", 1))
                if k not in _KEY_MAP:
                    raise ValueError(
                        f"fault spec option {k!r}; known: {sorted(_KEY_MAP)}"
                    )
                if k in _FLOAT_KEYS:
                    kw[_KEY_MAP[k]] = float(v)
                elif k in _INT_KEYS:
                    kw[_KEY_MAP[k]] = int(v)
                else:
                    kw[_KEY_MAP[k]] = v
        rules.append(FaultRule(**kw))
    if not rules:
        raise ValueError(f"fault spec {spec!r} contains no rules")
    return rules


def arm_from_env(env=None) -> Optional[FaultPlan]:
    """Arm from DLI_FAULTS when set (server startup hook); None if unset."""
    spec = (env or os.environ).get("DLI_FAULTS")
    if not spec:
        return None
    return arm(spec)
