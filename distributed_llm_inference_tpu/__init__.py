"""distributed_llm_inference_tpu — TPU-native pipeline-parallel LLM inference.

A from-scratch JAX/XLA framework with the capability surface of
Tulsi027/distributed-llm-inference (see SURVEY.md): layer-sharded
multi-device pipeline inference of HF causal LMs with a sampling decode
loop, chat templating, an HTTP serving API, an interactive client, and
per-request perf stats — redesigned TPU-first (jit-compiled stage
functions, ppermute over ICI, HBM KV cache, scan-based decode).
"""

__version__ = "0.1.0"

from .config import EngineConfig, MeshConfig, ModelConfig, SamplingConfig, stage_layer_range
from .models.registry import get_model_config, list_models
from .runtime import create_backend, create_engine
