"""Top-level factory: model name + mesh shape -> ready InferenceEngine.

The single entry point the serving layer / bench / client tooling use —
the reference needed three hand-edited scripts and manual URL wiring to
assemble the same topology (SURVEY.md §2 C10).
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from .config import EngineConfig, MeshConfig, ModelConfig
from .engine.engine import InferenceEngine, SingleDeviceBackend
from .models import api as M
from .models.registry import get_model_config
from .parallel.mesh import build_mesh
from .parallel.pipeline import PipelineBackend


def create_engine(
    model: str | ModelConfig = "tinyllama-1.1b",
    *,
    mesh_cfg: MeshConfig = MeshConfig(),
    engine_cfg: EngineConfig = EngineConfig(),
    params: Any = None,
    dtype: Optional[str] = None,
    tokenizer: Any = None,
    seed: int = 0,
) -> InferenceEngine:
    """Build an engine; pp>1 selects the SPMD pipeline backend.

    params=None random-initializes (offline bring-up / benchmarks);
    pass a converted HF pytree (models/convert.py) for real weights.
    """
    cfg = get_model_config(model) if isinstance(model, str) else model
    if dtype is not None:
        cfg = cfg.replace(dtype=dtype)
    if mesh_cfg.dp > 1:
        # the serving engine decodes batch=1, which cannot shard over dp;
        # batched dp decode is a backend-level capability (PipelineBackend
        # with batch % dp == 0 — used by the bench harness). Rejected before
        # params init — the expensive step — so a bad mesh fails instantly.
        raise NotImplementedError(
            "dp>1 is not available through the batch-1 serving engine; "
            "use PipelineBackend directly for dp-sharded batched decode"
        )
    if params is None:
        params = M.init_params(cfg, jax.random.PRNGKey(seed))
    if mesh_cfg.pp > 1 or mesh_cfg.tp > 1:
        mesh = build_mesh(mesh_cfg)
        backend = PipelineBackend(cfg, params, mesh)
    else:
        backend = SingleDeviceBackend(cfg, params)
    return InferenceEngine(
        cfg, backend=backend, tokenizer=tokenizer, engine_cfg=engine_cfg, seed=seed
    )
