"""Top-level factory: model name + mesh shape -> ready InferenceEngine.

The single entry point the serving layer / bench / client tooling use —
the reference needed three hand-edited scripts and manual URL wiring to
assemble the same topology (SURVEY.md §2 C10).
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from .config import EngineConfig, MeshConfig, ModelConfig
from .engine.engine import InferenceEngine, SingleDeviceBackend
from .models import api as M
from .models.registry import get_model_config
from .parallel.context import ContextParallelBackend
from .parallel.mesh import build_mesh
from .parallel.pipeline import PipelineBackend
from .parallel.schedule import MicrobatchPipelineBackend


def create_backend(
    model: str | ModelConfig = "tinyllama-1.1b",
    *,
    mesh_cfg: MeshConfig = MeshConfig(),
    microbatches: int = 1,
    params: Any = None,
    dtype: Optional[str] = None,
    quant: Optional[str] = None,
    kv_quant: Optional[str] = None,
    attn_impl: Optional[str] = None,
    seed: int = 0,
    sp_strategy: str = "ring",
    lora: Optional[str] = None,
    wire_quant: Optional[str] = None,
    adapter_slots: int = 0,
    adapter_rank: int = 8,
):
    """Build a compute backend alone (no engine/tokenizer around it).

    Selection: single device when the mesh is trivial; the SPMD pipeline
    for pp/tp meshes; the microbatched zero-bubble schedule
    (parallel/schedule.py, BASELINE config 5) when microbatches > 1.
    Batched workloads (bench harness, dryrun, batch-serving callers) use
    the backend interface directly: batch % (dp * microbatches) == 0.
    wire_quant (EngineConfig.pp_wire_quant through create_engine):
    "int8" quantizes every inter-stage activation hand-off on the SPMD
    backends (ops/wire_quant.py); ignored on the single device — there
    is no wire. Returns (cfg, backend).
    """
    cfg = get_model_config(model) if isinstance(model, str) else model
    if dtype is not None:
        cfg = cfg.replace(dtype=dtype)
    if quant is not None:
        cfg = cfg.replace(quant=quant)
    if kv_quant is not None:
        cfg = cfg.replace(kv_quant=kv_quant)
    # kv_quant composes with EVERY topology now: single device, pp/tp/dp
    # pipeline, 1F1B (per-leaf cache specs + tree-aware row slicing), and
    # sp (the ring/cp hooks quantize on write and dequantize their local
    # slot sets — parallel/context.py).
    if attn_impl is not None:
        from .config import resolve_attn_impl

        cfg = resolve_attn_impl(cfg, attn_impl)
    if sp_strategy != "ring" and mesh_cfg.sp <= 1:
        # fail loudly BEFORE any backend branch (including microbatches):
        # --sp-strategy ulysses without --sp > 1 would otherwise silently
        # run with no sequence parallelism at all
        raise ValueError(
            f"sp_strategy={sp_strategy!r} needs a context-parallel mesh "
            f"(sp > 1); got sp={mesh_cfg.sp}"
        )
    if mesh_cfg.sp > 1 and (microbatches > 1 or mesh_cfg.ep > 1):
        # checked before params init (the expensive step) and before the
        # microbatch branch, which would otherwise claim the sp-wide mesh
        # and silently replicate all work across it. sp x pp composes
        # since round 5 (the context backend runs the gated microstep
        # ring over pp with the sequence still sharded over sp).
        raise ValueError(
            "sp (context parallel) does not compose with microbatching/"
            "ep yet: the 1F1B schedule and expert dispatch assume "
            "whole-sequence activations per stage"
        )
    # weight quantization covers both families now (gpt2 projections go
    # through the quant-aware mm — ops/quant._QUANT_KEYS); an unknown arch
    # rejects inside quantize_params below — AFTER params init, since the
    # registry only carries the two supported arches anyway
    if params is None:
        params = M.init_params(cfg, jax.random.PRNGKey(seed))
    if lora is not None:
        # merge BEFORE quantization: the low-rank delta lands in the
        # dense weights, then every downstream path (quant/sharding/
        # speculation) sees one ordinary checkpoint
        from .models.lora import merge_lora

        params = merge_lora(cfg, params, lora)
    if cfg.quant is not None:
        from .ops.quant import quantize_params

        params = quantize_params(cfg, params)
    if adapter_slots:
        if microbatches > 1 or mesh_cfg.sp > 1:
            raise ValueError(
                "adapter_slots > 0 (runtime LoRA serving) rides the "
                "single-device and pp/tp pipeline backends; the 1F1B "
                "and context-parallel backends carry no adapter pages"
            )
        # install AFTER quantization (the paged lora leaves stay dense)
        # and BEFORE backend construction, so pp/tp meshes shard them
        # through the ordinary parallel/partition specs
        from .engine.adapters import install_adapter_leaves

        params = install_adapter_leaves(cfg, params, adapter_slots,
                                        adapter_rank)
    if microbatches > 1:
        if mesh_cfg.pp < 2:
            raise ValueError(
                "microbatches > 1 needs a pipeline (pp >= 2): with one "
                "stage there is no bubble to fill and the round-robin "
                "schedule would only serialize the batch"
            )
        if cfg.arch != "llama":
            # the serving path for microbatched fleets is the ragged
            # (left-padded) batch path, which needs shift-invariant
            # positions — reject at build time, not at warmup/request time
            raise NotImplementedError(
                f"microbatches > 1 serves ragged llama-family fleets only; "
                f"got arch={cfg.arch!r}"
            )
        mesh = build_mesh(mesh_cfg)
        return cfg, MicrobatchPipelineBackend(
            cfg, params, mesh, n_microbatches=microbatches,
            wire_quant=wire_quant,
        )
    if mesh_cfg.sp > 1:
        mesh = build_mesh(mesh_cfg)
        return cfg, ContextParallelBackend(
            cfg, params, mesh, sp_strategy=sp_strategy,
            wire_quant=wire_quant,
        )
    if not mesh_cfg.is_trivial:
        # sp > 1 already returned above, so a non-trivial mesh here means
        # dp/pp/tp/ep — the SPMD pipeline's axes
        mesh = build_mesh(mesh_cfg)
        return cfg, PipelineBackend(cfg, params, mesh, wire_quant=wire_quant)
    return cfg, SingleDeviceBackend(cfg, params)


def create_engine(
    model: str | ModelConfig = "tinyllama-1.1b",
    *,
    mesh_cfg: MeshConfig = MeshConfig(),
    engine_cfg: EngineConfig = EngineConfig(),
    microbatches: int = 1,
    params: Any = None,
    dtype: Optional[str] = None,
    quant: Optional[str] = None,
    kv_quant: Optional[str] = None,
    attn_impl: Optional[str] = None,
    tokenizer: Any = None,
    seed: int = 0,
    sp_strategy: str = "ring",
    draft_model: Optional[str | ModelConfig] = None,
    draft_params: Any = None,
    lora: Optional[str] = None,
) -> InferenceEngine:
    """Build an engine; pp>1 selects the SPMD pipeline backend.

    params=None random-initializes (offline bring-up / benchmarks);
    pass a converted HF pytree (models/convert.py) for real weights.
    draft_model attaches a smaller same-tokenizer model for two-model
    speculative decoding ("speculative": true greedy requests verify the
    draft's proposals instead of prompt-lookup n-grams).
    microbatches=M > 1 serves the zero-bubble 1F1B schedule (BASELINE
    config 5) through the engine: fleets decode M microbatches chasing
    each other around the pp ring, batched requests pad to a multiple of
    M, and solo requests ride the batched path.
    engine_cfg.adapter_slots > 0 installs the paged runtime LoRA leaves
    (engine/adapters.py) and hangs an AdapterPool off engine.adapters:
    requests carrying `adapter` select a page inside the one compiled
    mixed program, with `--lora` merge-at-load staying the
    single-adapter fast path (the same adapter cannot be served both
    ways).
    """
    if mesh_cfg.dp > 1:
        # the serving engine decodes batch=1, which cannot shard over dp
        # (nor split into microbatches); batched dp / microbatched decode is
        # a backend-level capability — see create_backend. Rejected before
        # params init — the expensive step — so a bad mesh fails instantly.
        raise NotImplementedError(
            "dp>1 is not available through the batch-1 serving engine; "
            "use create_backend() for dp-sharded / microbatched batched decode"
        )
    cfg, backend = create_backend(
        model, mesh_cfg=mesh_cfg, microbatches=microbatches, params=params,
        dtype=dtype, quant=quant, kv_quant=kv_quant, attn_impl=attn_impl,
        seed=seed, sp_strategy=sp_strategy, lora=lora,
        wire_quant=engine_cfg.pp_wire_quant,
        adapter_slots=engine_cfg.adapter_slots,
        adapter_rank=engine_cfg.adapter_rank,
    )
    engine = InferenceEngine(
        cfg, backend=backend, tokenizer=tokenizer, engine_cfg=engine_cfg, seed=seed
    )
    if engine_cfg.adapter_slots:
        from .engine.adapters import AdapterPool

        # merged_source records the --lora merge-at-load path so a later
        # register() of the SAME adapter (which would apply its delta on
        # top of the already-merged weights) fails loudly
        engine.adapters = AdapterPool(
            cfg, backend, engine_cfg.adapter_slots, engine_cfg.adapter_rank,
            registry=engine.metrics, merged_source=lora,
        )
    if draft_model is not None:
        dcfg = (
            get_model_config(draft_model)
            if isinstance(draft_model, str) else draft_model
        )
        if dtype is not None:
            dcfg = dcfg.replace(dtype=dtype)
        engine.set_draft(dcfg, draft_params, seed=seed + 1)
    return engine
