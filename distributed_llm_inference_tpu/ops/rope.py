"""Rotary position embeddings (Llama semantics).

The reference burns ~25 lines on transformers-version compat fallbacks just to
get cos/sin tables out of HF (/root/reference/Worker1.py:98-120) and rebuilds
position ids 0..seq-1 on every call (/root/reference/Worker1.py:93-94). Here
RoPE is a pure function of (positions, head_dim, theta) with pinned HF
"rotate_half" semantics: inv_freq over even indices, angles tiled twice, and
rotation by concat(-x2, x1) — matching transformers' LlamaRotaryEmbedding so
converter parity tests hold exactly.
"""

from __future__ import annotations

import jax.numpy as jnp


def llama3_scaled_inv_freq(
    inv_freq: jnp.ndarray,
    factor: float,
    low_freq_factor: float,
    high_freq_factor: float,
    original_max_len: int,
) -> jnp.ndarray:
    """Llama-3.1/3.2 "llama3" rope_scaling applied to the inverse frequencies.

    Matches transformers' `_compute_llama3_parameters`: wavelengths longer
    than original_max_len/low_freq_factor are slowed by `factor`, wavelengths
    shorter than original_max_len/high_freq_factor are kept, and the band in
    between interpolates smoothly. HF applies this unconditionally (not only
    past the original context), so parity requires it at every position.
    """
    wavelen = 2.0 * jnp.pi / inv_freq
    low_freq_wavelen = original_max_len / low_freq_factor
    high_freq_wavelen = original_max_len / high_freq_factor
    smooth = (original_max_len / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    smoothed = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    scaled = jnp.where(wavelen > low_freq_wavelen, inv_freq / factor, smoothed)
    return jnp.where(wavelen < high_freq_wavelen, inv_freq, scaled)


def rope_cos_sin(
    positions: jnp.ndarray,
    head_dim: int,
    theta: float = 10000.0,
    *,
    scaling: str | None = None,
    scaling_factor: float = 8.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_len: int = 8192,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for integer positions.

    positions: [...] int array. Returns (cos, sin), each [..., head_dim],
    computed in float32 (HF computes RoPE tables in fp32 even for bf16 models).
    scaling="llama3" reproduces Llama-3.1/3.2 frequency scaling.
    """
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if scaling == "llama3":
        inv_freq = llama3_scaled_inv_freq(
            inv_freq, scaling_factor, low_freq_factor, high_freq_factor,
            original_max_len,
        )
    elif scaling == "linear":
        # HF "linear" rope_scaling (Gemma-3 global layers): every
        # frequency divides by the factor at every position
        inv_freq = inv_freq / scaling_factor
    elif scaling is not None:
        raise ValueError(f"unsupported rope scaling {scaling!r}")
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., head_dim/2]
    angles = jnp.concatenate([angles, angles], axis=-1)  # [..., head_dim]
    return jnp.cos(angles), jnp.sin(angles)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(
    q: jnp.ndarray,
    k: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Apply rotary embedding to q [B,T,H,Dh] and k [B,T,KV,Dh].

    cos/sin: [T, Dh] or [B, T, Dh]; broadcast over the head axis.
    """
    if cos.ndim == 2:  # [T, Dh] -> [1, T, 1, Dh]
        cos_b = cos[None, :, None, :]
        sin_b = sin[None, :, None, :]
    else:  # [B, T, Dh] -> [B, T, 1, Dh]
        cos_b = cos[:, :, None, :]
        sin_b = sin[:, :, None, :]
    orig = q.dtype
    qf, kf = q.astype(jnp.float32), k.astype(jnp.float32)
    q_out = qf * cos_b + _rotate_half(qf) * sin_b
    k_out = kf * cos_b + _rotate_half(kf) * sin_b
    return q_out.astype(orig), k_out.astype(orig)
