"""Weight-only int8 quantization for the decode hot path.

Batch-1 decode is HBM-bandwidth-bound: every step streams every weight
byte from HBM once (bench.py measures ~75% of the v5e roofline in bf16).
Halving the bytes halves the floor — so the matmul weights are stored as
**int8 with per-output-channel symmetric scales** and dequantized on-chip:

    y = (x @ q.astype(x.dtype)) * s        # scale applied to the OUTPUT

The `astype` is a convert feeding a dot, which XLA fuses into the
operand read (the int8 tensor is what crosses HBM). Applying the scale
after the matmul keeps the inner loop integer-clean and needs one
multiply per output element.

QTensor is a registered pytree, so it composes with everything that maps
over params: `lax.scan` over stacked layers slices q [L, in, out] and
s [L, out] together, `device_put`/`NamedSharding` shard both leaves, and
donation just works. Per-output-channel scales ride with their columns
under tensor parallelism (column-sharded weights shard s; row-sharded
weights replicate s).

Embeddings stay unquantized: the embed lookup is a gather (no matmul to
fuse into) and its bytes are negligible per token; norms/biases are tiny.

No reference analogue — the reference serves fp32 torch on CPU
(/root/reference/Worker1.py:64, orchestration.py:41); this is a
beyond-parity TPU-performance feature.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import ModelConfig

# llama-family stacked matmul weights eligible for quantization, and
# whether their OUTPUT channels are the last axis (always true here:
# weights are stored [L, in, out] / [in, out])
_LLAMA_QUANT_KEYS = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
)


@jax.tree_util.register_pytree_node_class
class QTensor:
    """int8 weight + per-output-channel scale; shapes q [..., in, out],
    s [..., out]."""

    __slots__ = ("q", "s")

    def __init__(self, q, s):
        self.q = q
        self.s = s

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def size(self):
        return self.q.size + self.s.size

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QTensor(q={self.q.shape}@{self.q.dtype}, s={self.s.shape})"


def quantize_tensor(w: jnp.ndarray) -> QTensor:
    """Symmetric per-output-channel int8 quantization of w [..., in, out]."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)  # [..., 1, out]
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QTensor(q, scale[..., 0, :])


def dequantize_tensor(t: QTensor, dtype=jnp.float32) -> jnp.ndarray:
    return (t.q.astype(jnp.float32) * t.s[..., None, :].astype(jnp.float32)).astype(dtype)


def matmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """x @ w for a plain array or a QTensor (dequant fused into the dot)."""
    if isinstance(w, QTensor):
        return (x @ w.q.astype(x.dtype)) * w.s.astype(x.dtype)
    return x @ w


def quantize_params(cfg: ModelConfig, params: dict) -> dict:
    """Quantize the llama-family matmul weights of a params pytree.

    Quantizes the stacked per-layer projections and (when untied) the LM
    head; leaves embed / norms / biases untouched. Idempotent on already-
    quantized leaves.
    """
    if cfg.arch != "llama":
        raise NotImplementedError(
            f"weight-only quantization is wired for the llama family; "
            f"got arch={cfg.arch!r}"
        )
    out = dict(params)
    layers = dict(params["layers"])
    for k in _LLAMA_QUANT_KEYS:
        # MoE expert banks ([L, E, in, out], 4-D) stay dense for now —
        # the moe_ffn einsum path has no QTensor seam; attention weights
        # still quantize on MoE models (partial quant is valid)
        if (
            k in layers
            and not isinstance(layers[k], QTensor)
            and layers[k].ndim == 3
        ):
            layers[k] = quantize_tensor(layers[k])
    out["layers"] = layers
    if "lm_head" in params and not isinstance(params["lm_head"], QTensor):
        out["lm_head"] = quantize_tensor(params["lm_head"])
    return out
