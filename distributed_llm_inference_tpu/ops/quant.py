"""Weight-only int8 quantization for the decode hot path.

Batch-1 decode is HBM-bandwidth-bound: every step streams every weight
byte from HBM once (bench.py measures ~75% of the v5e roofline in bf16).
Halving the bytes halves the floor — so the matmul weights are stored as
**int8 with per-output-channel symmetric scales** and dequantized on-chip:

    y = (x @ q.astype(x.dtype)) * s        # scale applied to the OUTPUT

The `astype` is a convert feeding a dot, which XLA fuses into the
operand read (the int8 tensor is what crosses HBM). Applying the scale
after the matmul keeps the inner loop integer-clean and needs one
multiply per output element.

QTensor is a registered pytree, so it composes with everything that maps
over params: `lax.scan` over stacked layers slices q [L, in, out] and
s [L, out] together, `device_put`/`NamedSharding` shard both leaves, and
donation just works. Per-output-channel scales ride with their columns
under tensor parallelism (column-sharded weights shard s; row-sharded
weights replicate s).

Embeddings stay unquantized: the embed lookup is a gather (no matmul to
fuse into) and its bytes are negligible per token; norms/biases are tiny.

No reference analogue — the reference serves fp32 torch on CPU
(/root/reference/Worker1.py:64, orchestration.py:41); this is a
beyond-parity TPU-performance feature.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..config import ModelConfig

# stacked matmul weights eligible for quantization, per family; OUTPUT
# channels are the last axis for every one (weights are stored
# [L, in, out] / [in, out]). Biases, norms, and embeddings stay dense.
_QUANT_KEYS = {
    "llama": ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"),
    "gpt2": ("wq", "wk", "wv", "wo", "w_fc", "w_proj"),
}


@jax.tree_util.register_pytree_node_class
class QTensor:
    """int8 weight + per-output-channel scale; shapes q [..., in, out],
    s [..., out]."""

    __slots__ = ("q", "s")

    def __init__(self, q, s):
        self.q = q
        self.s = s

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def size(self):
        return self.q.size + self.s.size

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QTensor(q={self.q.shape}@{self.q.dtype}, s={self.s.shape})"


def quantize_tensor(w: jnp.ndarray) -> QTensor:
    """Symmetric per-output-channel int8 quantization of w [..., in, out]."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)  # [..., 1, out]
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QTensor(q, scale[..., 0, :])


@jax.tree_util.register_pytree_node_class
class Q4Tensor:
    """Packed int4 weight + per-(group, output-channel) scale.

    q: int8 [..., G, g//2, out] — two signed 4-bit values per byte along
    the group-row axis (group row i in the LOW nibble, row i + g/2 in the
    HIGH — halves, not interleaved pairs, so unpacking is a concatenate:
    Mosaic compiles a concat along the sublane axis where an interleaving
    reshape is an "unsupported shape cast");
    s: [..., G, out]. Each group of `g` contraction rows shares a scale
    (group-wise quantization: 4-bit needs finer scale granularity than
    int8's whole-column scales to keep reconstruction error useful).
    The group size rides as static pytree aux data so spec trees built
    for sharding keep the same treedef.
    """

    __slots__ = ("q", "s", "g")

    def __init__(self, q, s, g: int):
        self.q = q
        self.s = s
        self.g = int(g)

    @property
    def shape(self):  # logical [..., in, out]
        lead = self.q.shape[:-3]
        G, half, out = self.q.shape[-3:]
        return (*lead, G * self.g, out)

    @property
    def ndim(self):
        return self.q.ndim - 1

    @property
    def size(self):
        return self.q.size + self.s.size

    def tree_flatten(self):
        return (self.q, self.s), self.g

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    def __repr__(self):
        return (f"Q4Tensor(q={self.q.shape}@{self.q.dtype}, "
                f"s={self.s.shape}, g={self.g})")


def _unpack_int4(p: jnp.ndarray) -> jnp.ndarray:
    """int8 [..., n, out] of packed nibble halves -> int8 [..., 2n, out].

    Arithmetic shifts on int8 sign-extend, so the low nibble comes out
    via (p << 4) >> 4. Low nibbles hold rows [0, n), high nibbles rows
    [n, 2n) — a concatenate, never an interleave.
    """
    low = jnp.right_shift(jnp.left_shift(p, 4), 4)
    high = jnp.right_shift(p, 4)
    return jnp.concatenate([low, high], axis=-2)


def quantize_tensor4(w: jnp.ndarray, group: int = 64) -> Q4Tensor:
    """Symmetric group-wise int4 quantization of w [..., in, out]."""
    *lead, d_in, d_out = w.shape
    g = min(group, d_in)
    if d_in % g:
        g = d_in  # fall back to one group rather than reject odd shapes
    if g % 2:
        raise ValueError(f"int4 packing needs an even group size, got {g}")
    G = d_in // g
    w32 = w.astype(jnp.float32).reshape(*lead, G, g, d_out)
    absmax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)  # [..., G, 1, out]
    scale = jnp.maximum(absmax / 7.0, 1e-12)
    q = jnp.clip(jnp.round(w32 / scale), -7, 7).astype(jnp.int8)
    half = g // 2
    packed = jnp.bitwise_or(
        jnp.left_shift(q[..., half:, :], 4),
        jnp.bitwise_and(q[..., :half, :], jnp.int8(15)),
    )
    return Q4Tensor(packed, scale[..., 0, :], g)


def dequantize_tensor4(t: Q4Tensor, dtype=jnp.float32) -> jnp.ndarray:
    q = _unpack_int4(t.q).astype(jnp.float32)  # [..., G, g, out]
    w = q * t.s[..., None, :].astype(jnp.float32)
    lead = w.shape[:-3]
    return w.reshape(*lead, w.shape[-3] * w.shape[-2], w.shape[-1]).astype(dtype)


def dequantize_tensor(t: QTensor, dtype=jnp.float32) -> jnp.ndarray:
    return (t.q.astype(jnp.float32) * t.s[..., None, :].astype(jnp.float32)).astype(dtype)


def _q4_rows_kernel(x_ref, q_ref, s_ref, o_ref):
    """One (out-tile, group-block) step of y = x @ dequant(q4): unpack
    the PACKED block in VMEM (the whole point — only int4 bytes ever
    cross HBM), two plain 2-D dots per group (nibble halves — the
    packing is halves, not interleaved, precisely so no reshape is
    needed here), scale, accumulate into the out tile across the
    group-reduction grid dim. Plain dots only: a G-batched dot_general
    compiles pathologically in Mosaic (>7 min, never finished). Shapes:
    x [GB, R, g] f32 block, q [GB, g/2, ob] int8, s [GB, ob] f32,
    o [R, ob] f32 (revisited across the reduction)."""
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(1) == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    GB, half, ob = q_ref.shape
    acc = jnp.zeros_like(o_ref)
    for i in range(GB):  # static unroll over the small group block
        p = q_ref[i].astype(jnp.int32)
        low = jnp.right_shift(jnp.left_shift(p, 28), 28)   # rows [0, g/2)
        high = jnp.right_shift(jnp.left_shift(p, 24), 28)  # rows [g/2, g)
        x = x_ref[i].astype(jnp.float32)  # [R, g]
        part = jnp.dot(
            x[:, :half], low.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) + jnp.dot(
            x[:, half:], high.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        acc = acc + part * s_ref[i][None, :]
    o_ref[...] += acc


# groups per grid step: amortizes grid/DMA overhead over 8·g·ob packed
# bytes while keeping the kernel's static unroll small
_Q4_GROUP_BLOCK = 8


def q4_matmul_rows(x2d: jnp.ndarray, w: Q4Tensor, interpret: bool = None):
    """Pallas path for y = x2d @ dequant(w), x2d [R, in].

    The XLA einsum formulation of the same algebra materializes the
    unpacked int8 tensor in HBM (measured far slower on v5e; plain
    dequant-then-dot lands ~62 tok/s end to end), so the decode hot path
    unpacks in VMEM instead. Honest accounting (chained-call timing,
    bench.py): int4 decode lands ~330-350 tok/s vs int8's ~450-480 —
    the R=1 matvec shapes leave the kernel overhead-bound, so int4 is
    the CAPACITY lever (half int8's weight HBM: 13B-class fits a single
    v5e) while int8 stays the single-stream speed pick. Caller
    guarantees the tiling gates."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    R, d_in = x2d.shape
    G, half, d_out = w.q.shape
    g = 2 * half
    gb = _Q4_GROUP_BLOCK if G % _Q4_GROUP_BLOCK == 0 else 1
    # [R, in] -> [G, R, g] in XLA-land (tiny tensor; Mosaic rejects the
    # lane-splitting reshape in-kernel)
    xg = jnp.swapaxes(x2d.reshape(R, G, g), 0, 1).astype(jnp.float32)
    ob = next(b for b in (512, 256, 128) if d_out % b == 0)
    out = pl.pallas_call(
        _q4_rows_kernel,
        grid=(d_out // ob, G // gb),
        in_specs=[
            pl.BlockSpec((gb, R, g), lambda j, i: (i, 0, 0)),
            pl.BlockSpec((gb, half, ob), lambda j, i: (i, 0, j)),
            pl.BlockSpec((gb, ob), lambda j, i: (i, j)),
        ],
        out_specs=pl.BlockSpec((R, ob), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((R, d_out), jnp.float32),
        interpret=interpret,
    )(xg, w.q, w.s.astype(jnp.float32))
    return out


def _q4_kernel_ok(R: int, w: Q4Tensor) -> bool:
    """Gates for the Pallas path: few rows (decode/verify/slots — prefill
    keeps the XLA formulation, it amortizes dequant over T), int8-tile-
    friendly packed block (half % 32, out % 128), single stacked slice."""
    if w.q.ndim != 3 or R > 32:
        return False
    _, half, d_out = w.q.shape
    return half % 32 == 0 and d_out % 128 == 0


def matmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """x @ w for a plain array, QTensor, or Q4Tensor (dequant fused into
    the dot; for int4 the per-group partial products are scaled then
    summed — algebraically x @ dequant(w))."""
    if isinstance(w, QTensor):
        return (x @ w.q.astype(x.dtype)) * w.s.astype(x.dtype)
    if isinstance(w, Q4Tensor):
        lead = x.shape[:-1]
        R = 1
        for d in lead:
            R *= d
        if _q4_kernel_ok(R, w):
            y = q4_matmul_rows(x.reshape(R, x.shape[-1]), w)
            return y.reshape(*lead, y.shape[-1]).astype(x.dtype)
        q = _unpack_int4(w.q).astype(x.dtype)  # [G, g, out]
        G, g = q.shape[-3], q.shape[-2]
        xr = x.reshape(*x.shape[:-1], G, g)
        partial = jnp.einsum("...gi,gio->...go", xr, q)
        return (partial * w.s.astype(x.dtype)).sum(axis=-2)
    return x @ w


def expert_einsum(spec: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """einsum over an expert bank for a dense array or an int8 QTensor.

    Works for any spec whose OUTPUT keeps the scale axes — the per-
    (expert, out-channel) scale s [..., E, out] multiplies the result
    elementwise, which commutes with the contraction:
      'btd,edf->btef' (gate/up: out [b,t,e,f] * s[e,f])
      'btef,efd->bted' (down:   out [b,t,e,d] * s[e,d])
    """
    if isinstance(w, QTensor):
        return jnp.einsum(spec, x, w.q.astype(x.dtype)) * w.s.astype(x.dtype)
    return jnp.einsum(spec, x, w)


def quantize_params(cfg: ModelConfig, params: dict, mode: str = None,
                    group: int = 64) -> dict:
    """Quantize the matmul weights of a params pytree (both families —
    gpt2's projections go through the same quant-aware `mm`).

    mode: "int8" (per-output-channel scales) or "int4" (packed nibbles,
    group-wise scales — half the HBM bytes of int8 again); defaults to
    cfg.quant, then "int8". Quantizes the stacked per-layer projections
    and (when untied) the LM head; leaves embed / norms / biases
    untouched. Idempotent on already-quantized leaves.
    """
    if cfg.arch not in _QUANT_KEYS:
        raise NotImplementedError(
            f"weight-only quantization is wired for "
            f"{sorted(_QUANT_KEYS)}; got arch={cfg.arch!r}"
        )
    mode = mode or cfg.quant or "int8"
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown quantization mode {mode!r}")
    if mode == "int8":
        qfn = quantize_tensor
    else:
        # int4 row-sharding (tp) shards the GROUP axis, so a tp mesh
        # needs n_groups % tp == 0 — `group` tunes that (and fidelity)
        qfn = functools.partial(quantize_tensor4, group=group)
    out = dict(params)
    layers = dict(params["layers"])
    for k in _QUANT_KEYS[cfg.arch]:
        if k not in layers or isinstance(layers[k], (QTensor, Q4Tensor)):
            continue
        if layers[k].ndim == 3:
            layers[k] = qfn(layers[k])
        elif layers[k].ndim == 4 and mode == "int8":
            # MoE expert bank [L, E, in, out]: per-(expert, out-channel)
            # int8 scales ride the moe_ffn einsums (ops/quant.expert_einsum
            # — the elementwise scale commutes with the contraction).
            # int4 experts stay dense: the grouped-contraction layout has
            # no einsum seam yet.
            layers[k] = quantize_tensor(layers[k])
    out["layers"] = layers
    if "lm_head" in params and not isinstance(
        params["lm_head"], (QTensor, Q4Tensor)
    ):
        out["lm_head"] = qfn(params["lm_head"])
    return out
