"""Normalization ops.

TPU-native replacements for the torch modules the reference leans on
(`model.norm` RMSNorm at /root/reference/orchestration.py:46,140 and the
per-layer input/post-attention norms inside the HF decoder layers run at
/root/reference/Worker1.py:128-137). Accumulation is in float32 regardless
of activation dtype, matching HF LlamaRMSNorm semantics so logits-parity
tests hold in bfloat16.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5,
    unit_offset: bool = False,
) -> jnp.ndarray:
    """RMSNorm: x / rms(x) * weight, variance in fp32.

    unit_offset=True multiplies by (1 + weight) instead (HF GemmaRMSNorm —
    the checkpoint stores w with neutral value 0, not 1)."""
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    xf = xf * jax.lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if unit_offset:
        w = 1.0 + w
    return (xf * w).astype(orig_dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5
) -> jnp.ndarray:
    """LayerNorm with affine params (GPT-2 family), fp32 accumulation."""
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
    xf = (xf - mean) * (var + eps) ** -0.5
    out = xf * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(orig_dtype)
