"""Token sampling: temperature / top-k / top-p / greedy.

Behavioral spec is the reference's inline sampling stack
(/root/reference/orchestration.py:144-169): divide logits by temperature,
top-k filter, top-p nucleus filter with the keep-first-over-threshold shift,
then a categorical draw — rebuilt as pure jittable functions over
`jax.random` keys instead of torch in-place mutation, so the whole sampler
lives inside the decode `lax.scan`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = jnp.finfo(jnp.float32).min


def apply_temperature(logits: jnp.ndarray, temperature: jnp.ndarray) -> jnp.ndarray:
    """logits / temperature (reference orchestration.py:147). Guard t>0."""
    t = jnp.maximum(jnp.asarray(temperature, dtype=logits.dtype), 1e-6)
    return logits / t


def top_k_filter(logits: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Keep the k highest logits, set the rest to -inf.

    Matches reference orchestration.py:150-152 (threshold = k-th largest
    value; ties at the threshold are kept, identical to the torch topk
    comparison). k is a traced scalar; k <= 0 disables filtering.
    """
    vocab = logits.shape[-1]
    k_eff = jnp.clip(k, 1, vocab)
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
    idx = jnp.broadcast_to(jnp.asarray(k_eff - 1), logits.shape[:-1] + (1,))
    threshold = jnp.take_along_axis(sorted_logits, idx, axis=-1)
    filtered = jnp.where(logits < threshold, NEG_INF, logits)
    return jnp.where(k <= 0, logits, filtered)


def top_p_filter(logits: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus filtering (reference orchestration.py:155-165).

    Sort descending, softmax, cumulative sum; remove tokens whose cumulative
    probability exceeds p — shifted right one slot so the first token over
    the threshold is kept (`sorted_indices_to_remove[..., 0] = False` in the
    reference). p >= 1 disables filtering.
    """
    sort_idx = jnp.argsort(logits, axis=-1)[..., ::-1]
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    remove = cum > p
    remove = jnp.concatenate(
        [jnp.zeros_like(remove[..., :1]), remove[..., :-1]], axis=-1
    )
    sorted_filtered = jnp.where(remove, NEG_INF, sorted_logits)
    # Scatter back to vocab order.
    inv = jnp.argsort(sort_idx, axis=-1)
    filtered = jnp.take_along_axis(sorted_filtered, inv, axis=-1)
    return jnp.where(p >= 1.0, logits, filtered)


def apply_repetition_penalty(
    logits: jnp.ndarray, presence: jnp.ndarray, penalty: jnp.ndarray
) -> jnp.ndarray:
    """HF RepetitionPenaltyLogitsProcessor semantics: for every token
    already present in the context (prompt + generated so far), positive
    logits divide by the penalty and negative logits multiply by it.
    penalty <= 0 or == 1 disables; presence: [..., V] bool."""
    p = jnp.asarray(penalty, logits.dtype)
    penalized = jnp.where(logits > 0, logits / p, logits * p)
    out = jnp.where(presence, penalized, logits)
    return jnp.where((p <= 0) | (p == 1.0), logits, out)


def apply_oai_penalties(
    logits: jnp.ndarray,
    counts: jnp.ndarray,
    freq_penalty: jnp.ndarray,
    pres_penalty: jnp.ndarray,
) -> jnp.ndarray:
    """OpenAI frequency/presence penalties over GENERATED-token counts:

        logits -= freq_penalty * count + pres_penalty * (count > 0)

    (the OpenAI API reference's published formula; counts cover sampled
    tokens only, not the prompt — the same only-the-output convention the
    major open-source OpenAI-compatible servers use, vs the HF repetition
    penalty's prompt+output membership set). 0.0 disables either term;
    counts: [..., V] int32."""
    f = jnp.asarray(freq_penalty, jnp.float32)
    pr = jnp.asarray(pres_penalty, jnp.float32)
    c = counts.astype(jnp.float32)
    out = logits - f * c - pr * (c > 0).astype(jnp.float32)
    return jnp.where((f == 0.0) & (pr == 0.0), logits, out)


def min_p_filter(logits: jnp.ndarray, min_p: jnp.ndarray) -> jnp.ndarray:
    """HF MinPLogitsWarper: drop tokens whose probability is below
    min_p * max_prob (a dynamic floor that adapts to the model's
    confidence). min_p <= 0 disables. Applied AFTER temperature, like HF's
    warper ordering."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    floor = min_p * jnp.max(probs, axis=-1, keepdims=True)
    filtered = jnp.where(probs < floor, NEG_INF, logits)
    return jnp.where(min_p <= 0.0, logits, filtered)


def sample_token(
    key: jax.Array,
    logits: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    greedy: jnp.ndarray,
    min_p: jnp.ndarray = None,
    rep_penalty: jnp.ndarray = None,
    freq_penalty: jnp.ndarray = None,
    pres_penalty: jnp.ndarray = None,
    presence: jnp.ndarray = None,
    counts: jnp.ndarray = None,
    bias: jnp.ndarray = None,
    allowed: jnp.ndarray = None,
) -> jnp.ndarray:
    """Full sampling stack -> int32 token ids, shape logits.shape[:-1].

    greedy is a traced bool: argmax bypass (the BASELINE configs use greedy
    decode; the reference always samples). Greedy applies the repetition
    penalty BEFORE the argmax (HF processor ordering) but ignores the
    warpers (temperature/top-k/top-p/min-p), matching HF do_sample=False.

    min_p / rep_penalty+presence are optional HF-parity extensions
    (MinPLogitsWarper / RepetitionPenaltyLogitsProcessor); None or their
    disabled values (0 / 1.0) reproduce the reference's exact stack.
    freq_penalty / pres_penalty + counts are the OpenAI penalties
    (apply_oai_penalties; 0.0 disables). The positional parameter order
    through pres_penalty matches engine.generate.SamplingParams, so
    `sample_token(key, logits, *sampling, ...)` stays the universal call;
    presence/counts/bias are state, passed by keyword.
    allowed ([..., V] bool, None = unconstrained) is the grammar-
    constraint mask (constrain/): False tokens are -inf'd after
    bias/penalties, before the warpers — greedy and sampled draws alike
    can never emit a disallowed token.

    Hot-path note: this runs inside the decode `lax.scan` every token, so
    top-k and top-p share ONE descending sort (the standalone filters above
    are the unfused behavioral spec used by tests); the draw happens in
    sorted order and maps back through the sort permutation — equivalent to
    top_p_filter(top_k_filter(.)) + categorical, with 1 sort instead of 3.
    min-p piggybacks on the same sorted probs (max prob = rank-0 prob).
    """
    logits = logits.astype(jnp.float32)
    if bias is not None:
        # OpenAI logit_bias semantics: added to the RAW logits before any
        # warper; -100/+100 effectively ban/force a token. Applies to the
        # greedy argmax too (the ban must hold under temperature 0).
        logits = logits + bias.astype(jnp.float32)
    if rep_penalty is not None and presence is not None:
        logits = apply_repetition_penalty(logits, presence, rep_penalty)
    if counts is not None and freq_penalty is not None:
        # OpenAI penalties ride the same pre-warper slot as the HF
        # repetition penalty (and apply to the greedy argmax too)
        logits = apply_oai_penalties(logits, counts, freq_penalty, pres_penalty)
    if allowed is not None:
        # grammar-constraint mask (constrain/): disallowed tokens drop to
        # -inf AFTER bias/penalties and BEFORE the warpers, so a +100
        # logit_bias can never resurrect a token the grammar forbids and
        # the greedy argmax obeys the mask too. The table compiler
        # guarantees every row keeps >= 1 allowed token (EOS at worst),
        # so the masked row can never go all -inf.
        logits = jnp.where(allowed, logits, NEG_INF)

    use_min_p = min_p is not None
    mp = jnp.float32(0.0) if min_p is None else min_p
    greedy = jnp.asarray(greedy)
    # greedy uses a true argmax (first index on ties, like torch/np), NOT
    # sort_idx[..., 0]: the reversed stable ascending argsort would break
    # ties toward the LAST index. Argmax of the PENALIZED logits: HF
    # applies processors (repetition penalty) in greedy mode too.
    all_greedy = greedy if greedy.ndim == 0 else jnp.all(greedy)

    def _argmax_only(k, lg, t, tk, tp, mp_):
        return jnp.argmax(lg, axis=-1).astype(jnp.int32)

    def _fused(k, lg, t, tk, tp, mp_):
        sampled = _sample_warped(use_min_p, k, lg, t, tk, tp, mp_)
        if greedy.ndim == 0:
            # only reachable with scalar greedy False (the True case took
            # the argmax branch above/below) — sampled IS the answer
            return sampled
        # per-row fleet flags: mixed fleets resolve row-wise
        return jnp.where(greedy, jnp.argmax(lg, axis=-1), sampled).astype(
            jnp.int32
        )

    operands = (key, logits, temperature, top_k, top_p, mp)
    if isinstance(all_greedy, jax.core.Tracer):
        # Inside jit/scan (every decode hot loop): the warper pipeline
        # costs a full-vocab argsort + softmax + cumsum per step, and a
        # where(greedy, ...) would keep it live even when every step is
        # an argmax. lax.cond runs only the taken branch — greedy decode
        # skips the sampler entirely (279 -> 321 tok/s solo on v5e; the
        # slot fleet takes it whenever ALL rows are greedy). The sampled
        # branch is bit-identical to the fused path.
        return jax.lax.cond(all_greedy, _argmax_only, _fused, *operands)
    # Eager call (tests / one-off prefills outside jit): an eager cond
    # re-traces fresh branch closures every call and XLA recompiles the
    # whole computation each time (measured 10x test-suite blowup) — a
    # concrete flag needs a plain Python branch instead.
    # jaxlint: disable=host-sync -- eager-only branch: the Tracer case returned via lax.cond above; a concrete flag costs nothing to read
    if bool(all_greedy):
        return _argmax_only(*operands)
    return _fused(*operands)


def _sample_warped(use_min_p: bool, key, logits, temperature, top_k, top_p,
                   min_p):
    """The warper pipeline + categorical draw (the non-greedy half of
    sample_token, shared by its fused and lax.cond forms)."""
    scaled = apply_temperature(logits, temperature)
    vocab = scaled.shape[-1]

    sort_idx = jnp.argsort(scaled, axis=-1)[..., ::-1]
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    rank = jnp.arange(vocab, dtype=jnp.int32)
    # top-k: keep ranks < k (rank ordering matches the threshold semantics
    # of top_k_filter up to ties at the threshold). k <= 0 disables.
    keep_k = jnp.where(top_k <= 0, True, rank < jnp.clip(top_k, 1, vocab))
    # top-p: shifted cumulative-probability removal, first token always kept.
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    over = cum > top_p
    keep_p = ~jnp.concatenate([jnp.zeros_like(over[..., :1]), over[..., :-1]], axis=-1)
    keep_p = jnp.where(top_p >= 1.0, True, keep_p)
    keep = keep_k & keep_p
    if use_min_p:
        # sorted descending: rank 0 holds max prob. HF's warper order is
        # temperature -> top_k -> top_p -> min_p (transformers 4.57
        # _get_logits_processor); intersecting the keep-masks here is
        # token-identical because min_p's ratio test is invariant under
        # the earlier filters' renormalization and its keep set is a
        # prefix of the sorted ranks
        keep_m = probs >= min_p * probs[..., :1]
        keep &= jnp.where(min_p <= 0.0, True, keep_m)

    sorted_filtered = jnp.where(keep, sorted_logits, NEG_INF)
    draw = jax.random.categorical(key, sorted_filtered, axis=-1)  # rank index
    sampled = jnp.take_along_axis(sort_idx, draw[..., None], axis=-1)[..., 0]
    return sampled.astype(jnp.int32)


def top_n_probs(logits: jnp.ndarray, n: int = 5):
    """Top-n (prob, token) pairs for debug observability — the reference
    prints top-5 next-token predictions for the first 3 steps
    (/root/reference/orchestration.py:172-178)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_probs, top_ids = jax.lax.top_k(probs, n)
    return top_probs, top_ids
