"""int8 KV-cache quantization (per-token, per-head symmetric scales).

The KV cache is the HBM budget that scales with context and slot count —
at Llama-2-7B/4096 a single bf16 KV row is ~2 GB, and the continuous
fleet multiplies that by n_slots. Storing K/V as int8 with one fp32 scale
per (token, kv-head) halves the cache's HBM footprint (int8 data +
1/head_dim scale overhead), which buys 2x the slots / context at the
same budget; on read the dequantize (int8 -> f32 multiply) fuses into
the attention matmuls the same way the weight-only path's does
(ops/quant.py — measured 1.6x on-chip for weights, same producer-fusion
shape here).

Why per-(token, head) granularity: K/V activation outliers are
token-local (a single position can spike), so one scale per token row
keeps the quantization error independent of sequence content elsewhere —
the standard KV-quant recipe (vs per-tensor, which a single outlier
token would poison).

`KVQuant` is a registered pytree whose leaves (q int8, s fp32) flow
through every cache-shaped tree.map in the engine unchanged: slot
splices and beam reorders index the batch axis, which sits at the same
position in both leaves ([L, B, KV, S, Dh] and [L, B, KV, S]). The
dense hook (models/llama.default_attn_hook) dispatches on the leaf type;
everything else — scan-over-layers, donation, while_loop carries —
treats the cache as an opaque pytree.

Scope: llama-family, EVERY topology — single device, the slot fleet
(dense OR block-paged pool), pp/tp/dp/1F1B pipeline meshes, and sp
(the ring/cp hooks quantize on write and dequantize their local slot
sets — parallel/context.py); the prefix snapshot store composes too,
its slices carry the scale leaves. The Pallas flash PREFILL kernel and
the fused paged DECODE kernel both dequantize int8 tiles/blocks in
their prologues (ops/flash_attention.py, ops/paged_attention.py — half
the cache HBM bytes); only the dense fleet kernel (flash_attend_slots,
which the hook never selects anyway) still reads raw dtypes. The
reference has no KV cache at all (/root/reference/Worker1.py:132-134);
this is north-star serving scope.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .wire_quant import quantize_rows


@jax.tree_util.register_pytree_node_class
class KVQuant:
    """int8 cache leaf: q [..., S, Dh] int8, s [..., S] fp32 scales."""

    __slots__ = ("q", "s")

    def __init__(self, q, s):
        self.q = q
        self.s = s

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"KVQuant(q={self.q.shape}@{self.q.dtype}, s={self.s.shape})"


def init_quant_cache(
    n_layers: int, batch: int, n_kv: int, max_seq: int, head_dim: int
) -> dict:
    """Zeroed int8 cache, same dict shape as the raw one ({"k", "v"})."""
    q = (n_layers, batch, n_kv, max_seq, head_dim)
    s = (n_layers, batch, n_kv, max_seq)
    return {
        "k": KVQuant(jnp.zeros(q, jnp.int8), jnp.zeros(s, jnp.float32)),
        "v": KVQuant(jnp.zeros(q, jnp.int8), jnp.zeros(s, jnp.float32)),
    }


def quantize_chunk(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 over the head_dim axis: x [B, T, KV, Dh] ->
    (q [B, T, KV, Dh] int8, s [B, T, KV] fp32). The symmetric per-row
    primitive is shared with the pp wire format (ops/wire_quant.py), so
    cache and wire quantization cannot drift numerically."""
    return quantize_rows(x)


def dequantize(leaf: KVQuant) -> jnp.ndarray:
    """[..., S, Dh] fp32 view — feeds attention's fp32 softmax path
    directly, so the int8 load + scale multiply is the producer XLA fuses
    into the score/value matmuls."""
    return leaf.q.astype(jnp.float32) * leaf.s[..., None]


def update_cache(
    leaf: KVQuant,
    x_new: jnp.ndarray,
    pos: jnp.ndarray,
    gate: Optional[jnp.ndarray] = None,
) -> KVQuant:
    """Quantize-and-write a chunk at scalar offset `pos` (prefill / shared
    decode). Mirrors ops/attention.update_kv_cache: same transposes, same
    clamp caveat, same gated read-modify-write of the written slice only."""
    zero = jnp.int32(0)
    qn, sn = quantize_chunk(x_new)
    qn = qn.transpose(0, 2, 1, 3)  # [B, KV, T, Dh]
    sn = sn.transpose(0, 2, 1)  # [B, KV, T]
    start_q = (zero, zero, pos, zero)
    start_s = (zero, zero, pos)
    if gate is not None:
        old_q = jax.lax.dynamic_slice(leaf.q, start_q, qn.shape)
        old_s = jax.lax.dynamic_slice(leaf.s, start_s, sn.shape)
        qn = jnp.where(gate, qn, old_q)
        sn = jnp.where(gate, sn, old_s)
    return KVQuant(
        jax.lax.dynamic_update_slice(leaf.q, qn, start_q),
        jax.lax.dynamic_update_slice(leaf.s, sn, start_s),
    )


def update_cache_slots(
    leaf: KVQuant,
    x_new: jnp.ndarray,
    pos: jnp.ndarray,
    gate: Optional[jnp.ndarray] = None,
) -> KVQuant:
    """Per-row quantize-and-write at per-row offsets pos [B] (continuous
    batching). Mirrors ops/attention.update_kv_cache_slots."""
    qn, sn = quantize_chunk(x_new)
    qn = qn.transpose(0, 2, 1, 3)  # [B, KV, T, Dh]
    sn = sn.transpose(0, 2, 1)  # [B, KV, T]

    def row_q(cq, kn, p):
        start = (jnp.int32(0), p, jnp.int32(0))
        if gate is not None:
            old = jax.lax.dynamic_slice(cq, start, kn.shape)
            kn = jnp.where(gate, kn, old)
        return jax.lax.dynamic_update_slice(cq, kn, start)

    def row_s(cs, sn_, p):
        start = (jnp.int32(0), p)
        if gate is not None:
            old = jax.lax.dynamic_slice(cs, start, sn_.shape)
            sn_ = jnp.where(gate, sn_, old)
        return jax.lax.dynamic_update_slice(cs, sn_, start)

    return KVQuant(
        jax.vmap(row_q)(leaf.q, qn, pos),
        jax.vmap(row_s)(leaf.s, sn, pos),
    )
