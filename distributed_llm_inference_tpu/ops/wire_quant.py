"""int8 wire format for inter-stage activation hand-offs.

Activations crossing pp stage boundaries are full-precision by default,
and on real TPU slices the ICI bytes of those hops — not stage compute —
are the binding constraint for deeper pipelines and larger microbatch
counts (EQuARX, PAPERS.md: quantizing XLA collectives wins 2-4x at
negligible quality cost). This module is the ONE implementation of the
symmetric per-token-row int8 quantize/dequantize both wire consumers
share:

  * the KV cache (ops/kv_quant.py) — `quantize_chunk` delegates to
    `quantize_rows` here, so cache quantization and wire quantization can
    never drift numerically;
  * the pp/sp wire (EngineConfig.pp_wire_quant = "int8") — every
    activation hand-off family quantizes immediately before the
    collective and dequantizes on landing:
      1. the gated microstep ring (parallel/pipeline._microstep_loop),
      2. the 1F1B schedule's two ppermute sites (parallel/schedule.py),
      3. the sp ring/ulysses K-V chunk hops (parallel/ring.py — int8
         caches already rotate scales; raw-dtype activations adopt the
         same recipe via the `wire` flag),
      4. the masked `psum` broadcasts of the final-stage [B, 1, D]
         window — quantize the masked operand so the all-reduce ships
         int8 data + fp32 scales, EQuARX-style (exactly one participant
         is nonzero, so the int8 sum cannot overflow).

Data + scale travel as a `WireQuant` pytree through `ppermute`/`psum`
exactly like `KVQuant` leaves do on the sp ring. Everything stays fully
traced — zero host syncs, one compiled program per topology — and the
`wire-dtype` HLO rule family (analysis/hlo.py) machine-checks that the
lowered collective-permutes really carry si8 when the knob is on.

Exactness contract: quant off (the default) is bit-identical to the
unquantized collectives — `wire_ppermute(..., quant=False)` IS
`lax.ppermute` and `masked_psum(..., quant=False)` IS the masked-psum
idiom the call sites used verbatim. Quant on is toleranced: each wire
crossing is one symmetric-int8 round trip (`wire_roundtrip`), gated by
the greedy token-match-rate tests in tests/test_wire_quant.py.
"""

from __future__ import annotations

import functools as _functools

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class WireQuant:
    """int8 wire leaf: q [..., D] int8 data + s [...] fp32 per-row scales.

    A registered pytree, so a single `ppermute`/`psum` call ships data
    and scales together (two collectives in the lowered program — one
    si8, one small f32) and the loop-carry/type discipline of the
    surrounding `fori_loop`/`while_loop` is untouched.
    """

    __slots__ = ("q", "s")

    def __init__(self, q, s):
        self.q = q
        self.s = s

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"WireQuant(q={self.q.shape}@{self.q.dtype}, s={self.s.shape})"


def quantize_rows(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 over the LAST axis, one fp32 scale per leading row:
    x [..., D] -> (q [..., D] int8, s [...] fp32).

    Per-row granularity keeps the quantization error independent of
    content elsewhere in the batch/sequence — a single outlier token
    poisons only its own row, never the whole tensor (the same argument
    as the KV cache's per-(token, head) scales, which are this exact
    function applied to [B, T, KV, Dh] chunks)."""
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1)
    s = jnp.maximum(absmax / 127.0, 1e-12)  # all-zero rows stay zero
    q = jnp.clip(jnp.round(x32 / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def wire_encode(x: jnp.ndarray) -> WireQuant:
    """Quantize an activation for the wire."""
    return WireQuant(*quantize_rows(x))


def wire_decode(w: WireQuant, dtype) -> jnp.ndarray:
    """Dequantize on landing, restoring the sender's dtype (loop carries
    stay type-stable across the hop)."""
    return (w.q.astype(jnp.float32) * w.s[..., None]).astype(dtype)


def wire_roundtrip(x: jnp.ndarray) -> jnp.ndarray:
    """The numerics of ONE wire crossing without the collective — what a
    receiving stage sees of `x`. The CPU-proxy bench leg and the
    tolerance tests replay the mesh's error profile with this."""
    return wire_decode(wire_encode(x), x.dtype)


def wire_ppermute(x: jnp.ndarray, axis_name, perm, *, quant: bool):
    """Ring hand-off: `quant=False` IS `lax.ppermute` (bit-identical —
    the off-path contract); True ships int8 data + fp32 scales as one
    WireQuant pytree and dequantizes on landing."""
    if not quant:
        return jax.lax.ppermute(x, axis_name, perm)
    w = jax.lax.ppermute(wire_encode(x), axis_name, perm)
    return wire_decode(w, x.dtype)


def masked_psum(x: jnp.ndarray, sel, axis_name, *, quant: bool):
    """Masked single-owner broadcast: psum of a one-hot-masked operand
    (the final-stage [B, .., D] window hand-off every pp program ends
    with). `quant=False` is the exact masked-psum idiom the call sites
    inlined before this helper existed; True quantizes the masked
    operand so the all-reduce ships int8 data + fp32 scales — exactly
    one participant is nonzero, so the int8 sum cannot overflow."""
    if not quant:
        return jax.lax.psum(
            jnp.where(sel, x, jnp.zeros((), x.dtype)), axis_name
        )
    w = wire_encode(x)
    q = jax.lax.psum(jnp.where(sel, w.q, jnp.zeros((), w.q.dtype)), axis_name)
    s = jax.lax.psum(jnp.where(sel, w.s, jnp.zeros((), w.s.dtype)), axis_name)
    return wire_decode(WireQuant(q, s), x.dtype)


def proxy_stage_generate(cfg, params, prompt_ids, max_new: int,
                         n_stages: int, *, quant: bool = True):
    """CPU proxy of the pp ring's WIRE NUMERICS on one device.

    Greedy prefill + decode where the activation passes one
    `wire_roundtrip` after each of `n_stages` stage applications (the S
    ring hand-offs of one microstep loop) plus one more for the masked
    psum broadcast of the sampled window — the exact per-token error
    profile of the quantized mesh programs, with no mesh. The round trip
    is ROW-local (one scale per (b, t) row), so round-tripping the whole
    buffer and slicing the sampled window is identical to slicing first.

    quant=False runs the same stage-sliced forward with no round trips —
    bit-identical to the single-device greedy path (asserted in
    tests/test_wire_quant.py), so the proxy's match rate isolates
    exactly the wire quantization.

    Used by the `bench.py wire_quant` leg and the greedy
    token-match-rate gates; environments without jax.shard_map (the CPU
    CI) calibrate the mesh tests' tolerance against this.
    """
    ranges, fwd = _proxy_fwd(cfg, n_stages, quant)

    T = len(prompt_ids)
    from ..models import api as M

    caches = tuple(
        jax.tree.map(
            lambda a, lo=l0, hi=l1: a[lo:hi],
            M.init_kv_cache(cfg, 1, max_seq=T + max_new),
        )
        for (l0, l1) in ranges
    )
    tokens = jnp.asarray([prompt_ids], jnp.int32)
    logits, caches = fwd(params, tokens, jnp.int32(0), caches, T=T)
    tok = int(jnp.argmax(logits[0, T - 1]))
    out = [tok]
    for i in range(max_new - 1):
        logits, caches = fwd(
            params, jnp.asarray([[tok]], jnp.int32), jnp.int32(T + i),
            caches, T=1,
        )
        tok = int(jnp.argmax(logits[0, -1]))
        out.append(tok)
    return out


@_functools.lru_cache(maxsize=8)
def _proxy_fwd(cfg, n_stages: int, quant: bool):
    """Memoized stage-sliced forward for the proxy (cfg is a frozen
    dataclass — hashable), so repeated proxy calls reuse one jit cache
    and the bench leg times compute, not recompiles."""
    from ..config import stage_layer_range

    ranges = tuple(
        stage_layer_range(cfg.n_layers, n_stages, s)
        for s in range(n_stages)
    )

    @_functools.partial(jax.jit, static_argnames=("T",))
    def fwd(params, tokens, pos, caches, *, T):
        from ..models import api as M

        x = M.embed(cfg, params, tokens, pos)
        out = []
        for s, (l0, l1) in enumerate(ranges):
            layers_s = jax.tree.map(
                lambda a, lo=l0, hi=l1: a[lo:hi], params["layers"]
            )
            x, c = M.forward_layers(cfg, layers_s, x, caches[s], pos)
            out.append(c)
            if quant:
                x = wire_roundtrip(x)  # the inter-stage ppermute hop
        if quant:
            x = wire_roundtrip(x)  # the masked-psum broadcast
        return M.unembed(cfg, params, x), tuple(out)

    return ranges, fwd


def proxy_stage_match(cfg, params, prompt_ids, max_new: int,
                      n_stages: int) -> float:
    """Teacher-forced greedy match rate of the wire-quantized forward
    against the exact one: generate `max_new` tokens exactly (no wire
    error), then re-run the QUANTIZED stage forward over the same
    history and count the positions whose argmax agrees. Per-DECISION
    agreement — one early flip does not cascade through the rest of the
    sequence the way a free-running comparison would — which is the
    quantity the quality gate should bound (it is also what a user of a
    real checkpoint experiences per step)."""
    from ..config import stage_layer_range
    from ..models import api as M

    exact = proxy_stage_generate(
        cfg, params, prompt_ids, max_new, n_stages, quant=False
    )
    T = len(prompt_ids)
    full = list(prompt_ids) + exact
    ranges = [
        stage_layer_range(cfg.n_layers, n_stages, s)
        for s in range(n_stages)
    ]
    caches = tuple(
        jax.tree.map(
            lambda a, lo=l0, hi=l1: a[lo:hi],
            M.init_kv_cache(cfg, 1, max_seq=len(full)),
        )
        for (l0, l1) in ranges
    )
    x = M.embed(cfg, params, jnp.asarray([full], jnp.int32), jnp.int32(0))
    for s, (l0, l1) in enumerate(ranges):
        layers_s = jax.tree.map(
            lambda a, lo=l0, hi=l1: a[lo:hi], params["layers"]
        )
        x, _ = M.forward_layers(cfg, layers_s, x, caches[s], jnp.int32(0))
        x = wire_roundtrip(x)
    x = wire_roundtrip(x)
    logits = M.unembed(cfg, params, x)
    pred = jnp.argmax(logits[0], axis=-1)
    hits = sum(
        int(pred[T - 1 + i]) == exact[i] for i in range(max_new)
    )
    return hits / max_new


def wire_bytes(shape, itemsize: int, hops: int, *, quant: bool) -> int:
    """Host-side static wire accounting (no tracing cost): bytes one
    activation of `shape` costs crossing `hops` hand-offs. The formula
    itself lives in analysis/comms.wire_link_bytes — the ONE
    implementation the dli_pp_wire_bytes_total counters, the symbolic
    link table, and the bench leg's bytes/token headline all evaluate."""
    from ..analysis.comms import wire_link_bytes

    return wire_link_bytes(shape, itemsize, hops, quant=quant)
