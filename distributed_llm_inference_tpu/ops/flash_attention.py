"""Pallas TPU flash attention over the static-shape KV cache.

Drop-in replacement for `ops.attention.attend` (the XLA einsum path): same
GQA semantics, same [B,KV,S,Dh] cache layout, causal by absolute position.
One kernel covers both phases:

  * prefill — query chunk of length T at offset `pos`,
  * decode  — T=1 query at offset `pos`,

with an online-softmax (flash) loop over KV tiles, so the full [T,S] score
matrix is never materialized. The reference has no analogue — its
attention is HF eager attention recomputed over the whole sequence with no
cache at all (/root/reference/Worker1.py:125-154); this kernel is the
TPU-native hot path that makes decode O(prefix) per token.

Kernel layout decisions (see /opt/skills/guides/pallas_guide.md):
  * grid = (B, KV-heads, T-tiles, KV-tiles) under a
    `PrefetchScalarGridSpec`: `pos` is a scalar-prefetch argument, so the
    K/V BlockSpec index maps can CLAMP the KV-tile index to the live
    prefix — tiles past ceil((pos+T)/block_k) map to the same block as
    their predecessor, Pallas skips the redundant DMA, and HBM traffic is
    one pass over the live prefix, not max_seq. VMEM holds one
    [block_k, Dh] tile per operand, so max_seq is unbounded by VMEM.
  * GQA is folded into the query-row dimension: a tile holds
    block_t x group rows (row r = t*group + g), so one kernel serves MHA
    (group=1) and GQA alike and the MXU sees tall skinny matmuls instead
    of per-head vector products.
  * (m, l, acc) live in VMEM scratch, which persists across the
    sequentially-iterated KV-tile grid dimension (standard Pallas flash
    pattern); the output block is written once, on the last KV tile.
  * scores/accumulator in fp32 (preferred_element_type), output cast back
    to the query dtype.

On non-TPU backends the kernel runs in interpret mode, which is what the
CPU test suite exercises; numerics match `attend` to fp32 tolerance.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)  # mask fill; avoids inf-inf NaNs


def resolve_interpret(interpret: bool | None) -> bool:
    """Resolve a kernel's interpret mode: an explicit argument wins, then
    the DLI_PALLAS_INTERPRET env switch ("1"/"0" — tests/conftest.py pins
    it to 1 so tier-1 exercises every Pallas kernel bit-for-bit on CPU),
    then the backend default (interpret anywhere but a real TPU). ONE
    resolver for all kernels (flash / paged / ragged), so the test-suite
    switch cannot miss one."""
    if interpret is not None:
        return interpret
    env = os.environ.get("DLI_PALLAS_INTERPRET", "")
    if env != "":
        return env not in ("0", "false", "no")
    return jax.default_backend() != "tpu"


def _needed_tiles(pos, qi, *, T: int, block_t: int, block_k: int):
    """KV tiles live for query tile qi: keys up to its last valid query
    position pos + min((qi+1)*block_t, T) - 1."""
    t_hi = jnp.minimum((qi + 1) * block_t, T)
    return pl.cdiv(pos + t_hi, block_k)


def _first_tile(pos, qi, *, block_t: int, block_k: int, win):
    """First KV tile any query in tile qi can see: with sliding-window
    attention the tile's EARLIEST query (pos + qi*block_t) bounds it at
    q_pos - win + 1; full causal starts at 0. `win` is a TRACED scalar
    (the 3rd scalar-prefetch operand): <= 0 means full causal — per-layer
    window patterns (Gemma-2/3) feed a per-layer value from the scan, so
    ONE compiled kernel serves windowed and full layers."""
    lo = pos + qi * block_t - win + 1
    return jnp.where(win > 0, jnp.maximum(lo, 0) // block_k, 0)


def _flash_kernel(
    pos_ref,  # scalar-prefetch [1] int32
    vs_ref,  # scalar-prefetch [B] int32: per-row first valid slot
    win_ref,  # scalar-prefetch [1] int32: sliding window (<= 0 = full)
    q_ref,  # [1, block_t, 1, group, Dh] VMEM
    k_ref,  # [1, 1, block_k, Dh] VMEM
    v_ref,  # [1, 1, block_k, Dh] VMEM
    *rest,  # quant: (ks_ref, vs_scale_ref, o_ref, scratch...) else (o_ref, ...)
    T: int,
    S: int,
    block_t: int,
    block_k: int,
    group: int,
    scale: float,
    softcap: float | None,
    quant: bool = False,
):
    if quant:
        # int8 cache (ops/kv_quant): per-(token, head) fp32 scales ride
        # as two extra [1, 1, block_k] operands; dequant happens in the
        # tile prologue below — the kernel streams HALF the cache bytes
        # from HBM and the MXU still sees fp32 tiles.
        ks_ref, vscale_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vscale_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    pos = pos_ref[0]
    valid_from = vs_ref[pl.program_id(0)]
    win = win_ref[0]
    qi = pl.program_id(2)
    j = pl.program_id(3)
    n_j = pl.num_programs(3)
    rows = block_t * group
    Dh = q_ref.shape[-1]

    needed = _needed_tiles(pos, qi, T=T, block_t=block_t, block_k=block_k)
    first_live = _first_tile(pos, qi, block_t=block_t, block_k=block_k, win=win)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full((rows, 1), _NEG, jnp.float32)
        l_ref[:] = jnp.zeros((rows, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((rows, Dh), jnp.float32)

    @pl.when((j >= first_live) & (j < needed))
    def _():
        q = q_ref[0].reshape(rows, Dh).astype(jnp.float32) * scale
        # Row r of the tile is query (t_local = r // group, head g = r % group);
        # its absolute position is pos + qi*block_t + t_local.
        r_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 0)
        t_global = qi * block_t + r_ids // group
        q_pos = pos + t_global
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 1)

        ks = k_ref[0, 0].astype(jnp.float32)  # [block_k, Dh]
        if quant:
            ks = ks * ks_ref[0, 0][:, None]  # dequant prologue
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [rows, block_k]
        if softcap is not None:  # Gemma-2 logit capping, pre-mask (HF order)
            s = softcap * jnp.tanh(s / softcap)
        kv_pos = j * block_k + col_ids
        mask = (t_global < T) & (kv_pos <= q_pos) & (kv_pos < S)
        mask &= kv_pos >= valid_from  # left-pad slots (ragged batches)
        # sliding-window attention (win <= 0 = full causal; per-layer
        # patterns pass this layer's width)
        mask &= (win <= 0) | (kv_pos > q_pos - win)
        s = jnp.where(mask, s, _NEG)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)  # first tile: exp(_NEG - _NEG) == 1
        alpha = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        vs = v_ref[0, 0].astype(jnp.float32)
        if quant:
            vs = vs * vscale_ref[0, 0][:, None]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == n_j - 1)
    def _():
        l = l_ref[:]
        l = jnp.where(l == 0.0, 1.0, l)  # padding rows (t >= T) are all-masked
        o_ref[0] = (acc_ref[:] / l).reshape(block_t, 1, group, Dh).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_t", "block_k", "interpret", "window", "scale",
                     "softcap"),
)
def flash_attend(
    q: jnp.ndarray,
    cache_k,
    cache_v,
    pos: jnp.ndarray,
    valid_start: jnp.ndarray | None = None,
    window_dyn: jnp.ndarray | None = None,
    *,
    block_t: int = 0,
    block_k: int = 0,
    interpret: bool | None = None,
    window: int | None = None,
    scale: float | None = None,
    softcap: float | None = None,
) -> jnp.ndarray:
    """Causal GQA flash attention over the (already updated) cache.

    q [B,T,H,Dh], cache_k/v [B,KV,S,Dh] — or ops/kv_quant.KVQuant leaves
    (int8 data + per-(token, head) fp32 scales [B,KV,S]), dequantized in
    the kernel's tile prologue so the int8 cache streams half the HBM
    bytes. pos scalar int32 (chunk offset).
    valid_start: optional [B] int32 — first real slot per row (ragged
    LEFT-padded batches; earlier slots are never attended). window:
    static sliding-window width (None = full causal); window_dyn: TRACED
    scalar override (<= 0 = full causal) — the window rides the kernel as
    a scalar-prefetch operand, so per-layer patterns (Gemma-2/3
    alternating layers) feed each scan step's width through ONE compiled
    kernel. scale: score scale override (Gemma query scaling, Granite
    attention_multiplier; None = head_dim**-0.5). softcap: Gemma-2 logit
    capping. Returns [B,T,H,Dh] in q.dtype. Same contract as
    `attention.attend` with the mask derived from `pos` (and
    `valid_start`/window) instead of passed in.
    """
    from .kv_quant import KVQuant

    quant = isinstance(cache_k, KVQuant)
    if quant:
        cache_k, k_scale = cache_k.q, cache_k.s
        cache_v, v_scale = cache_v.q, cache_v.s
    B, T, H, Dh = q.shape
    KV, S = cache_k.shape[1], cache_k.shape[2]
    group = H // KV

    interpret = resolve_interpret(interpret)
    if block_t <= 0:
        # ~<=1024 query rows per tile keeps q + fp32 acc well inside VMEM.
        block_t = max(1, min(T, 1024 // group))
    if block_k <= 0:
        block_k = min(S, 256)

    # Heads of one KV group are contiguous in H (h = kv*group + g), so a
    # [*, block_t, 1, group, Dh] block at KV-index kv covers exactly that
    # group's queries.
    q5 = q.reshape(B, T, KV, group, Dh)
    pos_arr = jnp.reshape(pos.astype(jnp.int32), (1,))
    if valid_start is None:
        valid_start = jnp.zeros((B,), jnp.int32)
    valid_start = valid_start.astype(jnp.int32)
    if window_dyn is None:
        win_arr = jnp.full((1,), window if window is not None else -1, jnp.int32)
    else:
        win_arr = jnp.reshape(window_dyn.astype(jnp.int32), (1,))

    nt = _needed_tiles  # close over static tile params in the index maps

    def kv_index(b, kv, qi, j, pos_ref, vs_ref, win_ref):
        # Clamp dead tiles (past the causal frontier, or — with a sliding
        # window — before the window) to the nearest live one: the block
        # index repeats, so Pallas skips the DMA and dead grid steps cost
        # nothing. The kernel's pl.when gate skips their compute too.
        needed = nt(pos_ref[0], qi, T=T, block_t=block_t, block_k=block_k)
        first = _first_tile(
            pos_ref[0], qi, block_t=block_t, block_k=block_k, win=win_ref[0]
        )
        return (b, kv, jnp.clip(j, first, needed - 1), 0)

    def kv_index_3(b, kv, qi, j, pos_ref, vs_ref, win_ref):
        # the quant-scale operands [B, KV, S]: same clamped tile walk,
        # one rank down
        return kv_index(b, kv, qi, j, pos_ref, vs_ref, win_ref)[:3]

    kernel = functools.partial(
        _flash_kernel,
        T=T,
        S=S,
        block_t=block_t,
        block_k=block_k,
        group=group,
        scale=scale if scale is not None else Dh**-0.5,
        softcap=softcap,
        quant=quant,
    )
    rows = block_t * group
    in_specs = [
        pl.BlockSpec(
            (1, block_t, 1, group, Dh),
            lambda b, kv, qi, j, pos_ref, vs_ref, win_ref: (b, qi, kv, 0, 0),
        ),
        pl.BlockSpec((1, 1, block_k, Dh), kv_index),
        pl.BlockSpec((1, 1, block_k, Dh), kv_index),
    ]
    operands = [q5, cache_k, cache_v]
    if quant:
        # scale rows [B, KV, S] tile with the SAME clamped kv index map,
        # one [block_k] strip per tile
        in_specs += [
            pl.BlockSpec((1, 1, block_k), kv_index_3),
            pl.BlockSpec((1, 1, block_k), kv_index_3),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, KV, pl.cdiv(T, block_t), pl.cdiv(S, block_k)),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, block_t, 1, group, Dh),
            lambda b, kv, qi, j, pos_ref, vs_ref, win_ref: (b, qi, kv, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, Dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, T, KV, group, Dh), q.dtype),
        interpret=interpret,
    )(pos_arr, valid_start, win_arr, *operands)
    return out.reshape(B, T, H, Dh)
