"""Pallas TPU paged-attention decode kernel over the block pool.

Fused replacement for the gather-then-attend path in `engine/paged.py`:
the XLA path materializes each slot's block table into a contiguous
[B, KV, MB*bs, Dh] view (one extra HBM write + read of the whole logical
window per layer per step) and then runs the masked einsum attention over
it. Here the kernel walks the block table directly — each grid step DMAs
ONE physical pool block [bs, Dh] into VMEM and folds it into an
online-softmax (flash) accumulator, so

  * HBM traffic is one read of the slot's LIVE blocks (dead tail blocks
    and — with a sliding window — dead head blocks repeat their
    neighbour's index, so Pallas skips the DMA entirely), with no
    contiguous-view materialization at all;
  * the pool is never reshaped/transposed: the kernel reads the same
    [N, KV, bs, Dh] layout the scatter writes.

Contract (matches `engine/paged.make_paged_hook`'s gather path):
  * decode only — T=1 queries at per-row positions `pos` [B];
  * mask is derived IN-KERNEL from `pos` and the window — static, or a
    TRACED per-layer width via the `window_dyn` scalar-prefetch operand
    (Gemma-2/3 alternating patterns): row b attends logical positions
    max(0, pos_b-win+1) .. pos_b inclusive. Score-scale overrides and
    Gemma-2 softcapping are static kernel params, so the full attention
    variant surface runs fused (round 5 — the kernel previously fell
    back to the gather path for these).
  * GQA is folded into the query-row dimension exactly like
    ops/flash_attention.py: the score matmul is [group, Dh] x [Dh, bs].

The reference has no analogue at any level — it has no KV cache at all
(/root/reference/Worker1.py:132-134); block-paged KV + this kernel are
north-star serving scope (vLLM-class HBM discipline, re-designed for
XLA's static shapes: the table is a plain traced input, admission never
recompiles).

On non-TPU backends the kernel runs in interpret mode (CPU test suite);
numerics match the gather path to fp32 tolerance.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)  # mask fill; avoids inf-inf NaNs


def _live_range(pos_b, *, bs: int, MB: int, win):
    """(first, needed) logical-block bounds for a row at position pos_b:
    blocks [first, needed) hold at least one attendable position. `win`
    is a TRACED scalar or a static int (None / <= 0 = full causal) —
    per-layer window patterns (Gemma-2/3) feed each scan step's width
    through one compiled kernel, same contract as
    ops/flash_attention._first_tile."""
    if win is None:
        win = -1
    needed = jnp.minimum(pl.cdiv(pos_b + 1, bs), MB)
    needed = jnp.maximum(needed, 1)  # pos < 0 never happens; keep clip sane
    first = jnp.where(
        win > 0,
        jnp.minimum(jnp.maximum(pos_b - win + 1, 0) // bs, needed - 1),
        0,
    )
    return first, needed


def _paged_kernel(
    table_ref,  # scalar-prefetch [B, MB] int32
    pos_ref,  # scalar-prefetch [B] int32
    win_ref,  # scalar-prefetch [1] int32: sliding window (<= 0 = full)
    q_ref,  # [1, 1, 1, group, Dh] VMEM
    k_ref,  # [1, 1, bs, Dh] VMEM (one physical pool block)
    v_ref,  # [1, 1, bs, Dh] VMEM
    *rest,  # quant: (ks_ref, vscale_ref, o_ref, scratch...) else (o_ref, ...)
    bs: int,
    MB: int,
    group: int,
    scale: float,
    softcap: float | None,
    quant: bool = False,
):
    del table_ref  # physical placement is the index maps' concern
    if quant:
        # int8 pool (ops/kv_quant): per-(token, head) fp32 scales ride as
        # two extra [1, 1, bs] operands walking the same table; dequant in
        # the block prologue — the table walk streams the int8 bytes, the
        # MXU sees fp32
        ks_ref, vscale_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vscale_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(2)
    n_j = pl.num_programs(2)
    pos_b = pos_ref[b]
    win = win_ref[0]
    Dh = q_ref.shape[-1]
    first, needed = _live_range(pos_b, bs=bs, MB=MB, win=win)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full((group, 1), _NEG, jnp.float32)
        l_ref[:] = jnp.zeros((group, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((group, Dh), jnp.float32)

    @pl.when((j >= first) & (j < needed))
    def _():
        q = q_ref[0, 0, 0].astype(jnp.float32) * scale  # [group, Dh]
        ks = k_ref[0, 0].astype(jnp.float32)  # [bs, Dh]
        vs = v_ref[0, 0].astype(jnp.float32)
        if quant:
            ks = ks * ks_ref[0, 0][:, None]
            vs = vs * vscale_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [group, bs]
        if softcap is not None:  # Gemma-2 logit capping, pre-mask (HF order)
            s = softcap * jnp.tanh(s / softcap)
        kv_pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (group, bs), 1)
        mask = kv_pos <= pos_b
        mask &= (win <= 0) | (kv_pos > pos_b - win)
        s = jnp.where(mask, s, _NEG)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)  # first block: exp(_NEG - _NEG) == 1
        alpha = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == n_j - 1)
    def _():
        l = l_ref[:]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked row (never in serving)
        o_ref[0, 0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("interpret", "window", "scale", "softcap")
)
def paged_flash_attend(
    q: jnp.ndarray,
    pool_k,
    pool_v,
    table: jnp.ndarray,
    pos: jnp.ndarray,
    window_dyn: jnp.ndarray | None = None,
    *,
    window: int | None = None,
    scale: float | None = None,
    softcap: float | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Paged GQA decode attention over the (already updated) block pool.

    q [B,1,H,Dh]; pool_k/v [N,KV,bs,Dh] (one layer's pool slice) — or
    ops/kv_quant.KVQuant leaves (int8 blocks + per-(token, head) fp32
    scales [N,KV,bs]), dequantized in the block prologue so the table
    walk streams HALF the bytes per live block; table [B,MB] int32
    physical block ids; pos [B] int32 per-row positions.
    window: static sliding-window width (None = full causal);
    window_dyn: TRACED scalar override (<= 0 = full) riding as a
    scalar-prefetch operand — per-layer patterns (Gemma-2/3) feed each
    scan step's width through ONE compiled kernel. scale: score-scale
    override (None = head_dim**-0.5); softcap: Gemma-2 logit capping.
    Returns [B,1,H,Dh] in q.dtype — same contract as the gather path in
    engine/paged.make_paged_hook with the mask derived from pos/window.
    """
    from .flash_attention import resolve_interpret
    from .kv_quant import KVQuant

    quant = isinstance(pool_k, KVQuant)
    if quant:
        pool_k, k_scale = pool_k.q, pool_k.s
        pool_v, v_scale = pool_v.q, pool_v.s
    B, T, H, Dh = q.shape
    assert T == 1, "paged kernel serves decode steps (T=1) only"
    KV, bs = pool_k.shape[1], pool_k.shape[2]
    MB = table.shape[1]
    group = H // KV

    interpret = resolve_interpret(interpret)

    q5 = q.reshape(B, 1, KV, group, Dh)
    table = table.astype(jnp.int32)
    pos = pos.astype(jnp.int32)
    if window_dyn is None:
        win_arr = jnp.full((1,), window if window is not None else -1, jnp.int32)
    else:
        win_arr = jnp.reshape(window_dyn.astype(jnp.int32), (1,))

    def kv_index(b, kv, j, table_ref, pos_ref, win_ref):
        # Clamp dead logical blocks (past the causal frontier, or before
        # a sliding window) to the nearest live one: the PHYSICAL index
        # then repeats across consecutive dead steps, Pallas skips the
        # DMA, and the kernel's pl.when gate skips their compute.
        first, needed = _live_range(
            pos_ref[b], bs=bs, MB=MB, win=win_ref[0]
        )
        return (table_ref[b, jnp.clip(j, first, needed - 1)], kv, 0, 0)

    def kv_index_3(b, kv, j, table_ref, pos_ref, win_ref):
        # the quant-scale operands [N, KV, bs]: same table walk, one rank
        # down
        return kv_index(b, kv, j, table_ref, pos_ref, win_ref)[:3]

    kernel = functools.partial(
        _paged_kernel,
        bs=bs,
        MB=MB,
        group=group,
        scale=scale if scale is not None else Dh**-0.5,
        softcap=softcap,
        quant=quant,
    )
    in_specs = [
        pl.BlockSpec(
            (1, 1, 1, group, Dh),
            lambda b, kv, j, table_ref, pos_ref, win_ref: (b, 0, kv, 0, 0),
        ),
        pl.BlockSpec((1, 1, bs, Dh), kv_index),
        pl.BlockSpec((1, 1, bs, Dh), kv_index),
    ]
    operands = [q5, pool_k, pool_v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, bs), kv_index_3),
            pl.BlockSpec((1, 1, bs), kv_index_3),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, KV, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, 1, group, Dh),
            lambda b, kv, j, table_ref, pos_ref, win_ref: (b, 0, kv, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, Dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, 1, KV, group, Dh), q.dtype),
        interpret=interpret,
    )(table, pos, win_arr, *operands)
    return out.reshape(B, 1, H, Dh)


def _slots_kernel(
    pos_ref,  # scalar-prefetch [B] int32
    q_ref,  # [1, 1, KV, group, Dh] VMEM
    k_ref,  # [1, KV, bk, Dh] VMEM (all kv heads, one seq tile)
    v_ref,  # [1, KV, bk, Dh] VMEM
    o_ref,  # [1, 1, KV, group, Dh] VMEM
    m_ref,  # scratch [H, 1] fp32
    l_ref,  # scratch [H, 1] fp32
    acc_ref,  # scratch [H, Dh] fp32
    *,
    bk: int,
    KV: int,
    group: int,
    S: int,
    scale: float,
    window: int | None,
):
    """One (batch row, seq tile) step: ALL kv heads in one MXU matmul.

    The per-(b, kv) variant (`_paged_kernel`) issues KV x S/bk programs of
    [group, bk] work each; this tile folds every kv head — scores are one
    [H, KV*bk] matmul (rows = all query heads, columns = every kv head's
    tile) and a block-diagonal mask kills the cross-head terms: 4x the
    multiplies on paper, but they ride an MXU that was idling, and the
    program count drops by KV x.

    Measured on v5e (TinyLlama, 8 x 8192 fleet cache at pos 1024):
    ~1.08 ms/call vs the XLA einsum's ~1.00 ms at the attention level
    (bench.py's fleet leg re-measures both every round), and 382 vs 395
    tok/s inside the full end-to-end fleet decode step — the live-prefix
    DMA savings do not yet overcome Mosaic pipelining overhead against
    XLA's fused masked einsum. That is why the serving hook never
    selects this kernel: decode stays on the XLA path regardless of
    attn_impl, and this kernel is the baseline future work (splash-style
    multi-tile pipelining) has to beat.
    """
    b = pl.program_id(0)
    j = pl.program_id(1)
    n_j = pl.num_programs(1)
    pos_b = pos_ref[b]
    Dh = q_ref.shape[-1]
    H = KV * group
    C = KV * bk
    first, needed = _live_range(pos_b, bs=bk, MB=n_j, win=window)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full((H, 1), _NEG, jnp.float32)
        l_ref[:] = jnp.zeros((H, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((H, Dh), jnp.float32)

    @pl.when((j >= first) & (j < needed))
    def _():
        q = q_ref[0, 0].reshape(H, Dh).astype(jnp.float32) * scale
        ks = k_ref[0].reshape(C, Dh).astype(jnp.float32)
        vs = v_ref[0].reshape(C, Dh).astype(jnp.float32)
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [H, C]
        row = jax.lax.broadcasted_iota(jnp.int32, (H, C), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (H, C), 1)
        kv_pos = j * bk + col % bk
        # block-diagonal: row h (kv head h // group) only sees columns of
        # its own kv head's tile (col // bk)
        mask = (row // group == col // bk) & (kv_pos <= pos_b)
        if S % bk != 0:
            mask &= kv_pos < S
            vs = jnp.where(
                j * bk + jax.lax.broadcasted_iota(jnp.int32, (C, Dh), 0) % bk
                < S,
                vs, 0.0,
            )  # BlockSpec pad garbage would ride 0 * NaN into acc
        if window is not None:
            mask &= kv_pos > pos_b - window
        s = jnp.where(mask, s, _NEG)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == n_j - 1)
    def _():
        l = l_ref[:]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (
            (acc_ref[:] / l).reshape(KV, group, Dh).astype(o_ref.dtype)
        )


@functools.partial(
    jax.jit, static_argnames=("block_k", "interpret", "window")
)
def flash_attend_slots(
    q: jnp.ndarray,
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    pos: jnp.ndarray,
    *,
    block_k: int = 0,
    window: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Per-row-position flash decode over the DENSE slot-fleet cache.

    The same online-softmax walk as `paged_flash_attend` with the identity
    layout: the fleet cache is [B, KV, S, Dh] and row b's live prefix is
    positions 0..pos[b] (ops/attention.slot_causal_mask semantics, the
    continuous fleet's decode mask). Tiles past each row's causal frontier
    — or, with a sliding window, before it — clamp to the nearest live
    tile, so Pallas skips their DMA: HBM traffic per step is each row's
    LIVE prefix, where the XLA path reads all B*S slots of the fleet
    cache regardless of occupancy. ops/flash_attention.flash_attend is
    the shared-scalar-position counterpart (its grid offsets assume one
    frontier for the whole batch; this kernel's are per-row).

    Not reachable from the serving hook: see `_slots_kernel` — on v5e
    at serving sizes the XLA einsum still edges it out end to end;
    bench.py's fleet leg tracks the attention-level gap each round.

    q [B,1,H,Dh] (decode, T=1); cache_k/v [B,KV,S,Dh]; pos [B] int32.
    Returns [B,1,H,Dh] in q.dtype.
    """
    from .flash_attention import resolve_interpret

    B, T, H, Dh = q.shape
    assert T == 1, "slots kernel serves decode steps (T=1) only"
    KV, S = cache_k.shape[1], cache_k.shape[2]
    group = H // KV

    interpret = resolve_interpret(interpret)
    if block_k <= 0:
        block_k = min(S, 512)
    MB = pl.cdiv(S, block_k)

    q5 = q.reshape(B, 1, KV, group, Dh)
    pos = pos.astype(jnp.int32)

    def kv_index(b, j, pos_ref):
        first, needed = _live_range(pos_ref[b], bs=block_k, MB=MB, win=window)
        return (b, 0, jnp.clip(j, first, needed - 1), 0)

    kernel = functools.partial(
        _slots_kernel,
        bk=block_k,
        KV=KV,
        group=group,
        S=S,
        scale=Dh**-0.5,
        window=window,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, MB),
        in_specs=[
            pl.BlockSpec(
                (1, 1, KV, group, Dh), lambda b, j, pos_ref: (b, 0, 0, 0, 0)
            ),
            pl.BlockSpec((1, KV, block_k, Dh), kv_index),
            pl.BlockSpec((1, KV, block_k, Dh), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, KV, group, Dh), lambda b, j, pos_ref: (b, 0, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, Dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, 1, KV, group, Dh), q.dtype),
        interpret=interpret,
    )(pos, q5, cache_k, cache_v)
    return out.reshape(B, 1, H, Dh)


# -- ragged paged attention: mixed prefill + decode rows, one launch ----------
#
# The decode kernel above serves exactly one query per row; prefill still
# climbs a bucket ladder of chunked fills over a contiguous scratch cache
# that is then scattered into the pool. This kernel collapses both phases
# into ONE grid: the flat query axis holds every row's tokens back to back
# (a prefill row contributes its chunk, a decode row contributes one
# token), a per-tile metadata array carries (row, start, length, kind),
# and the KV walk reads each tile's placement straight from the block
# table. Dead tiles (launch padding, or KV blocks past a tile's causal
# frontier) repeat their neighbour's physical index, so Pallas skips the
# DMA — padding costs control flow, not HBM bandwidth. The TPU "Ragged
# Paged Attention" kernel (PAPERS.md) is the design source; the flash
# accumulation discipline is shared with ops/flash_attention.py.

RAGGED_PREFILL = 0  # metadata `kind`: a prompt-chunk row (length >= 1)
RAGGED_DECODE = 1  # metadata `kind`: a single-token decode row


def _ragged_live_range(q_start, q_len, *, bs: int, MB: int, win):
    """(first, needed) logical-block bounds for a query tile starting at
    absolute position q_start with q_len valid queries. Dead tiles
    (q_len == 0 launch padding) evaluate with an effective length of 1 so
    their range — and therefore their clamped physical index — equals
    their predecessor's, which is what lets Pallas skip the DMA
    entirely (the builder copies the predecessor's row/start into pad
    tiles). `win` is a TRACED scalar (<= 0 = full causal)."""
    last = q_start + jnp.maximum(q_len, 1) - 1
    needed = jnp.clip(pl.cdiv(last + 1, bs), 1, MB)
    first = jnp.where(
        win > 0,
        jnp.minimum(jnp.maximum(q_start - win + 1, 0) // bs, needed - 1),
        0,
    )
    return first, needed


def _ragged_kernel(
    meta_ref,  # scalar-prefetch [G, 4] int32: (row, q_start, q_len, kind)
    table_ref,  # scalar-prefetch [R, MB] int32
    win_ref,  # scalar-prefetch [1] int32: sliding window (<= 0 = full)
    q_ref,  # [1, tq, 1, group, Dh] VMEM (one query tile, one kv head)
    k_ref,  # [1, 1, bs, Dh] VMEM (one physical pool block)
    v_ref,  # [1, 1, bs, Dh] VMEM
    *rest,  # quant: (ks_ref, vscale_ref, o_ref, scratch...) else (o_ref, ...)
    bs: int,
    MB: int,
    tq: int,
    group: int,
    scale: float,
    softcap: float | None,
    quant: bool = False,
):
    del table_ref  # physical placement is the index maps' concern
    if quant:
        ks_ref, vscale_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vscale_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    g = pl.program_id(0)
    j = pl.program_id(2)
    n_j = pl.num_programs(2)
    q_start = meta_ref[g, 1]
    q_len = meta_ref[g, 2]  # 0 = dead (launch-padding) tile
    win = win_ref[0]
    rows = tq * group
    Dh = q_ref.shape[-1]
    first, needed = _ragged_live_range(q_start, q_len, bs=bs, MB=MB, win=win)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full((rows, 1), _NEG, jnp.float32)
        l_ref[:] = jnp.zeros((rows, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((rows, Dh), jnp.float32)

    @pl.when((q_len > 0) & (j >= first) & (j < needed))
    def _():
        # Row r of the tile is (local query t = r // group, head g = r %
        # group); its absolute position is q_start + t — the SAME GQA
        # row-folding as the decode kernel, with tq queries per tile
        # instead of one.
        q = q_ref[0].reshape(rows, Dh).astype(jnp.float32) * scale
        ks = k_ref[0, 0].astype(jnp.float32)  # [bs, Dh]
        vs = v_ref[0, 0].astype(jnp.float32)
        if quant:
            ks = ks * ks_ref[0, 0][:, None]
            vs = vs * vscale_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [rows, bs]
        if softcap is not None:  # Gemma-2 logit capping, pre-mask (HF order)
            s = softcap * jnp.tanh(s / softcap)
        t_local = jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 0) // group
        q_pos = q_start + t_local
        kv_pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
        mask = (t_local < q_len) & (kv_pos <= q_pos)
        mask &= (win <= 0) | (kv_pos > q_pos - win)
        s = jnp.where(mask, s, _NEG)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)  # first block: exp(_NEG - _NEG) == 1
        alpha = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == n_j - 1)
    def _():
        l = l_ref[:]
        l = jnp.where(l == 0.0, 1.0, l)  # padding rows are fully masked
        o_ref[0] = (
            (acc_ref[:] / l).reshape(tq, 1, group, Dh).astype(o_ref.dtype)
        )


@functools.partial(
    jax.jit, static_argnames=("interpret", "window", "scale", "softcap")
)
def ragged_paged_attend(
    q: jnp.ndarray,
    pool_k,
    pool_v,
    table: jnp.ndarray,
    meta: jnp.ndarray,
    window_dyn: jnp.ndarray | None = None,
    *,
    window: int | None = None,
    scale: float | None = None,
    softcap: float | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Mixed prefill + decode GQA attention over the (already updated)
    block pool — one launch for rows of ARBITRARY per-row length.

    q [W, H, Dh]: the flat query-token axis — every row's tokens laid out
    back to back at query-tile granularity (tq = W // meta.shape[0]); a
    prefill row contributes its chunk, a decode row one token.
    pool_k/v [N, KV, bs, Dh] (one layer's pool slice) — or
    ops/kv_quant.KVQuant leaves (int8 blocks + per-(token, head) fp32
    scales), dequantized in the block prologue.
    table [R, MB] int32 physical block ids, one row per fleet row.
    meta [G, 4] int32 per-tile metadata (row, q_start, q_len, kind), the
    host-built launch plan (engine/paged.build_ragged_meta): q_start is
    the tile's first ABSOLUTE position, q_len its valid queries (0 =
    launch-padding tile — its row/q_start repeat the predecessor's so the
    clamped KV index repeats and Pallas skips the DMA), kind is
    RAGGED_PREFILL / RAGGED_DECODE (launch accounting; the math is
    uniform — a decode row is simply q_len == 1 at its own position).
    window / window_dyn / scale / softcap: as `paged_flash_attend`.
    Returns [W, H, Dh] in q.dtype: each query token's attention output
    over its row's KV prefix (positions 0..q_pos through the block
    table), which is exactly the bucketed scratch prefill's per-token
    contract — so one compiled program replaces the whole bucket ladder.
    """
    from .flash_attention import resolve_interpret
    from .kv_quant import KVQuant

    quant = isinstance(pool_k, KVQuant)
    if quant:
        pool_k, k_scale = pool_k.q, pool_k.s
        pool_v, v_scale = pool_v.q, pool_v.s
    W, H, Dh = q.shape
    G = meta.shape[0]
    tq = W // G
    assert tq * G == W, "flat query axis must be a whole number of tiles"
    KV, bs = pool_k.shape[1], pool_k.shape[2]
    MB = table.shape[1]
    group = H // KV

    interpret = resolve_interpret(interpret)

    q5 = q.reshape(G, tq, KV, group, Dh)
    table = table.astype(jnp.int32)
    meta = meta.astype(jnp.int32)
    if window_dyn is None:
        win_arr = jnp.full((1,), window if window is not None else -1, jnp.int32)
    else:
        win_arr = jnp.reshape(window_dyn.astype(jnp.int32), (1,))

    def kv_index(g, kv, j, meta_ref, table_ref, win_ref):
        # Clamp dead logical blocks to the tile's live range; pad tiles
        # (q_len == 0) share their predecessor's (row, q_start), so their
        # whole walk repeats the previous tile's physical indices and
        # Pallas skips every DMA. The kernel's pl.when gate skips the
        # compute either way.
        first, needed = _ragged_live_range(
            meta_ref[g, 1], meta_ref[g, 2], bs=bs, MB=MB, win=win_ref[0]
        )
        row = jnp.maximum(meta_ref[g, 0], 0)
        return (table_ref[row, jnp.clip(j, first, needed - 1)], kv, 0, 0)

    def kv_index_3(g, kv, j, meta_ref, table_ref, win_ref):
        # the quant-scale operands [N, KV, bs]: same table walk, one rank
        # down
        return kv_index(g, kv, j, meta_ref, table_ref, win_ref)[:3]

    kernel = functools.partial(
        _ragged_kernel,
        bs=bs,
        MB=MB,
        tq=tq,
        group=group,
        scale=scale if scale is not None else Dh**-0.5,
        softcap=softcap,
        quant=quant,
    )
    rows = tq * group
    in_specs = [
        pl.BlockSpec(
            (1, tq, 1, group, Dh),
            lambda g, kv, j, meta_ref, table_ref, win_ref: (g, 0, kv, 0, 0),
        ),
        pl.BlockSpec((1, 1, bs, Dh), kv_index),
        pl.BlockSpec((1, 1, bs, Dh), kv_index),
    ]
    operands = [q5, pool_k, pool_v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, bs), kv_index_3),
            pl.BlockSpec((1, 1, bs), kv_index_3),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(G, KV, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, tq, 1, group, Dh),
            lambda g, kv, j, meta_ref, table_ref, win_ref: (g, 0, kv, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, Dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((G, tq, KV, group, Dh), q.dtype),
        interpret=interpret,
    )(meta, table, win_arr, *operands)
    return out.reshape(W, H, Dh)
