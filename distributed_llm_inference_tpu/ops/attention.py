"""Causal attention with GQA and a static-shape KV cache.

Replaces the reference's per-layer HF `LlamaAttention` calls, which it runs
with `attention_mask=None, past_key_value=None, use_cache=False`
(/root/reference/Worker1.py:125-154) — i.e. full-sequence recompute per
decoded token. Here the KV cache is a static-shape HBM buffer written with
`lax.dynamic_update_slice`, so one compiled program covers both prefill
(chunk of length T at offset 0) and decode (T=1 at offset `pos`), and the
decode cost per token is O(seq) attention instead of O(seq²) recompute.

Shapes (B=batch, T=chunk len, S=max_seq, H=q heads, KV=kv heads, Dh=head_dim):
  q          [B, T, H, Dh]
  k_new/v_new[B, T, KV, Dh]
  cache_k/v  [B, KV, S, Dh]

The cache keeps the head axis OUTSIDE the sequence axis so each head's
[S, Dh] slab is contiguous — dense per-head reads for the Pallas flash
kernel (whose BlockSpec tiles the trailing [S, Dh] dims; Pallas TPU
requires the last two block dims be full-size or (8,128)-aligned) and for
XLA's attention matmuls alike.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def update_kv_cache(
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
    gate=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write the new K/V chunk at offset `pos` (scalar int32). Static shapes.

    Caller contract: pos + T must be <= max_seq. `dynamic_update_slice`
    CLAMPS out-of-range starts instead of erroring, which would silently
    misplace K/V relative to `causal_mask`'s absolute positions — the decode
    engine enforces the bound (engine/generate.py caps max_new_tokens by the
    cache capacity) so this never triggers in serving.

    gate: optional traced bool — when False the write is a no-op. Used by
    the pipeline runtime, where a stage executes speculatively on
    microsteps when it holds no valid microbatch. Gating selects over the
    written SLICE only (read-modify-write of [B,KV,T,Dh]), not the whole
    cache — a whole-cache `where` would copy max_seq slots per layer per
    microstep.
    """
    zero = jnp.int32(0)
    # [B, T, KV, Dh] chunk -> [B, KV, T, Dh] to match the cache layout.
    k_new = k_new.transpose(0, 2, 1, 3)
    v_new = v_new.transpose(0, 2, 1, 3)
    start = (zero, zero, pos, zero)
    if gate is not None:
        old_k = jax.lax.dynamic_slice(cache_k, start, k_new.shape)
        old_v = jax.lax.dynamic_slice(cache_v, start, v_new.shape)
        k_new = jnp.where(gate, k_new, old_k)
        v_new = jnp.where(gate, v_new, old_v)
    cache_k = jax.lax.dynamic_update_slice(cache_k, k_new, start)
    cache_v = jax.lax.dynamic_update_slice(cache_v, v_new, start)
    return cache_k, cache_v


def causal_mask(
    pos: jnp.ndarray, chunk_len: int, max_seq: int, window=None
) -> jnp.ndarray:
    """[T, S] boolean mask: query at absolute position pos+t may attend to
    cache slots 0..pos+t inclusive (earlier prompt + itself). With
    `window` (sliding-window attention, Mistral-style) only the last
    `window` positions qualify: q_pos - window < kv_pos <= q_pos."""
    q_pos = pos + jnp.arange(chunk_len, dtype=jnp.int32)  # [T]
    kv_pos = jnp.arange(max_seq, dtype=jnp.int32)  # [S]
    mask = kv_pos[None, :] <= q_pos[:, None]
    if window is not None:
        mask &= kv_pos[None, :] > q_pos[:, None] - window
    return mask


def slot_causal_mask(
    pos: jnp.ndarray, chunk_len: int, max_seq: int, window=None
) -> jnp.ndarray:
    """[B, T, S] mask for PER-ROW query offsets (continuous batching).

    Each slot row b decodes at its own absolute position pos[b]+t — slots
    admitted at different times have different lengths, so there is no
    shared position frame to left-pad into. Row b's query at pos[b]+t may
    attend cache slots 0..pos[b]+t; stale K/V beyond a slot's position
    (from a longer previous tenant) sits strictly above it and is never
    attended before decode overwrites it — the same argument as padded
    prefill.
    """
    q_pos = pos[:, None] + jnp.arange(chunk_len, dtype=jnp.int32)[None, :]  # [B, T]
    kv_pos = jnp.arange(max_seq, dtype=jnp.int32)  # [S]
    mask = kv_pos[None, None, :] <= q_pos[:, :, None]
    if window is not None:
        mask &= kv_pos[None, None, :] > q_pos[:, :, None] - window
    return mask


def update_kv_cache_slots(
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
    gate=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row cache write at per-row offsets pos [B] (continuous batching:
    every slot is at its own sequence position). vmapped
    `dynamic_update_slice` over the batch axis — same clamp caveat as
    `update_kv_cache`, enforced per slot by the continuous engine.

    gate: optional traced bool (shared across rows) — when False the write
    is a no-op, selected over the written slices only. The pipeline slots
    program needs it: stages execute speculatively on microsteps where
    they don't own the fleet's buffer."""
    k_new = k_new.transpose(0, 2, 1, 3)  # [B, KV, T, Dh]
    v_new = v_new.transpose(0, 2, 1, 3)

    def row(ck, kn, p):
        if gate is not None:
            old = jax.lax.dynamic_slice(ck, (jnp.int32(0), p, jnp.int32(0)), kn.shape)
            kn = jnp.where(gate, kn, old)
        return jax.lax.dynamic_update_slice(ck, kn, (jnp.int32(0), p, jnp.int32(0)))

    cache_k = jax.vmap(row)(cache_k, k_new, pos)
    cache_v = jax.vmap(row)(cache_v, v_new, pos)
    return cache_k, cache_v


def ragged_causal_mask(
    pos: jnp.ndarray, chunk_len: int, max_seq: int, valid_start: jnp.ndarray,
    window=None,
) -> jnp.ndarray:
    """[B, T, S] mask for LEFT-padded batches: causal AND slot >= the row's
    first real slot. Left-padding aligns ragged prompts to one shared
    position frame (RoPE is relative, so a per-row uniform shift is
    harmless); the pad slots in front must simply never be attended."""
    causal = causal_mask(pos, chunk_len, max_seq, window)  # [T, S]
    kv_pos = jnp.arange(max_seq, dtype=jnp.int32)
    valid = kv_pos[None, None, :] >= valid_start[:, None, None]  # [B, 1, S]
    return causal[None, :, :] & valid


def attend(
    q: jnp.ndarray,
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    mask: jnp.ndarray,
    scale=None,
    softcap=None,
) -> jnp.ndarray:
    """Grouped-query attention over the (already updated) cache.

    mask: [T, S] (shared) or [B, T, S] (per-row, ragged left-padded batch).
    Softmax in fp32; output cast back to q.dtype. Returns [B, T, H, Dh].
    scale: score scale (None = head_dim**-0.5; Gemma-2 overrides).
    softcap: Gemma-2 attention logit softcapping, cap*tanh(scores/cap),
    applied BEFORE masking (HF Gemma2Attention order).
    """
    B, T, H, Dh = q.shape
    KV = cache_k.shape[1]
    group = H // KV
    # [B, T, KV, group, Dh] so each kv head serves its query group without
    # materializing repeated K/V (XLA keeps this as a batched matmul).
    qg = q.reshape(B, T, KV, group, Dh)
    if scale is None:
        scale = Dh ** -0.5
    scores = jnp.einsum(
        "btkgd,bksd->bkgts", qg.astype(jnp.float32), cache_k.astype(jnp.float32)
    ) * scale  # [B, KV, group, T, S]
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    neg = jnp.finfo(jnp.float32).min
    bmask = mask[:, None, None, :, :] if mask.ndim == 3 else mask[None, None, None, :, :]
    scores = jnp.where(bmask, scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bksd->btkgd", probs, cache_v.astype(jnp.float32))
    return out.reshape(B, T, H, Dh).astype(q.dtype)
