"""Grammar-constrained structured-output decoding.

Host-side compiler that turns a constraint spec — a regex, a choice list,
or a JSON schema subset — into a DFA over the tokenizer vocabulary:

  * `regex.py`   — regex subset -> byte-level DFA (Thompson NFA + subset
    construction; full-match semantics);
  * `schema.py`  — JSON-schema subset / generic-JSON grammar -> regex;
  * `vocab.py`   — token id -> byte string extraction (byte fallback,
    HF BPE byte-decoder, sentencepiece);
  * `tables.py`  — DFA x vocab trie -> dense `(num_states, vocab)`
    allowed-mask + transition tables (the arrays shipped to device);
  * `fleet.py`   — per-fleet combined table registry for the continuous
    engine (admission acquires by constraint hash, release frees).

The device side is deliberately tiny: the sampler masks logits with
`mask[state]` and advances `state = trans[state, token]` — two gathers
inside the compiled decode `while_loop`, zero host work per token
(ops/sampling.py, engine/generate.py). EOS is only ever allowed in DFA
accept states, and an accept state with no live continuation allows ONLY
EOS — so "force EOS when the grammar is complete" falls out of the table
construction rather than any special-case device code.
"""

from .regex import RegexError, compile_regex, escape_literal
from .schema import SchemaError, constraint_to_regex
from .tables import (
    CompiledConstraint,
    ConstraintError,
    compile_constraint,
    constraint_key,
    parse_constraint_spec,
)
from .vocab import TokenVocab
from .fleet import FleetConstraintTable

__all__ = [
    "CompiledConstraint",
    "ConstraintError",
    "FleetConstraintTable",
    "RegexError",
    "SchemaError",
    "TokenVocab",
    "compile_constraint",
    "compile_regex",
    "constraint_key",
    "constraint_to_regex",
    "escape_literal",
    "parse_constraint_spec",
]
