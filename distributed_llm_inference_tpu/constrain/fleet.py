"""Per-fleet combined constraint tables for the continuous engine.

The slot fleet decodes in lock-step with ONE pair of (mask, transition)
tables shared by every row, so slots running DIFFERENT constraints need
their states to index one combined table. Row 0 is the FREE state (every
token allowed, self-loop): unconstrained slots simply sit at state 0 and
the constrained decode program is a uniform two-gather no-op for them.
Each resident constraint's artifact occupies rows [offset, offset + S) with
its transitions rebased by +offset; a slot's absolute FSM state is
offset + local state.

Residency is refcounted by constraint hash: admission `acquire`s (reusing
a resident entry or appending its rows), release `release`s. Appending
never moves resident rows — active slots hold absolute indices on device —
so zero-ref entries are reclaimed lazily: the next acquire that finds NO
active references resets the whole table. `acquire` returns None when the
capacity cannot take the artifact right now (same backpressure contract as
the paged block pool: requeue, retry after a release).

Table capacity is padded up a bucket ladder so the decode program only
recompiles when the fleet crosses a bucket, not on every admission.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .tables import CompiledConstraint

STATE_BUCKETS = (32, 64, 128, 256, 512, 1024)


class FleetConstraintTable:
    def __init__(self, vocab_size: int, max_states: int = STATE_BUCKETS[-1],
                 registry=None):
        self.vocab_size = int(vocab_size)
        self.max_states = int(max_states)
        self._entries: dict = {}  # key -> {"art", "offset", "refs"}
        self._total = 1  # row 0 = the free state
        self._np: Optional[tuple] = None  # (mask, trans) padded to bucket
        self._dev: Optional[tuple] = None
        # /metrics residency + backpressure (utils/metrics.py): gauges
        # track resident artifacts / occupied state rows, the counter
        # counts acquire() refusals (the requeue-and-retry backpressure
        # events the paged pool also reports)
        self._m_resident = self._m_states = self._m_backpressure = None
        if registry is not None:
            self._m_resident = registry.gauge(
                "dli_constraint_entries_resident",
                "constraint artifacts resident in the fleet table",
            ).labels()
            self._m_states = registry.gauge(
                "dli_constraint_states_resident",
                "fleet-table state rows occupied (row 0 = free state)",
            ).labels()
            self._m_states.set(self._total)
            self._m_backpressure = registry.counter(
                "dli_constraint_backpressure_total",
                "admissions refused because the fleet table was full",
            ).labels()

    def _update_gauges(self):
        if self._m_resident is not None:
            self._m_resident.set(len(self._entries))
            self._m_states.set(self._total)

    @property
    def any_active(self) -> bool:
        return any(e["refs"] > 0 for e in self._entries.values())

    def fits(self, art: CompiledConstraint) -> bool:
        """Could `art` EVER be admitted (even into an empty table)? False
        means route the request to the solo engine instead of queueing it
        behind a release that will never help."""
        return 1 + art.num_states <= self.max_states

    def acquire(self, art: CompiledConstraint) -> Optional[int]:
        """Resident offset for `art` (refcount bumped), or None when the
        table is full right now (backpressure: retry after a release)."""
        e = self._entries.get(art.key)
        if e is not None:
            e["refs"] += 1
            return e["offset"]
        if not self.any_active and self._entries:
            # no slot references any resident rows: safe to compact
            self._entries.clear()
            self._total = 1
            self._np = self._dev = None
        if self._total + art.num_states > self.max_states:
            self._update_gauges()
            if self._m_backpressure is not None:
                self._m_backpressure.inc()
            return None
        offset = self._total
        self._entries[art.key] = {"art": art, "offset": offset, "refs": 1}
        self._total += art.num_states
        self._np = self._dev = None
        self._update_gauges()
        return offset

    def release(self, key: str):
        e = self._entries.get(key)
        if e is not None and e["refs"] > 0:
            e["refs"] -= 1

    def _bucket(self) -> int:
        for b in STATE_BUCKETS:
            if self._total <= b <= self.max_states:
                return b
        return self.max_states

    def numpy_tables(self) -> tuple:
        """(mask [B, V] bool, trans [B, V] int32) padded to the bucket.
        Padding rows are free rows — unreachable, but a garbage gather
        through one must never produce NaN logits."""
        if self._np is None:
            B = self._bucket()
            mask = np.ones((B, self.vocab_size), bool)
            trans = np.zeros((B, self.vocab_size), np.int32)
            for e in self._entries.values():
                art, off = e["art"], e["offset"]
                S = art.num_states
                mask[off: off + S] = art.mask
                trans[off: off + S] = art.next_state + off
                # EOS self-loops were absolute-local; rebase is uniform +off
            self._np = (mask, trans)
        return self._np

    def device_tables(self) -> tuple:
        if self._dev is None:
            import jax.numpy as jnp

            mask, trans = self.numpy_tables()
            self._dev = (jnp.asarray(mask), jnp.asarray(trans))
        return self._dev

    def stats(self) -> dict:
        return {
            "resident": len(self._entries),
            "active": sum(e["refs"] > 0 for e in self._entries.values()),
            "states": self._total,
            "bucket": self._bucket(),
            "max_states": self.max_states,
        }
