"""DFA x vocab -> the dense device tables, plus constraint-spec plumbing.

`compile_constraint` is the one host-side entry: a normalized spec
(parse_constraint_spec) compiles through schema.py -> regex.py into a
byte DFA, then a byte-level TRIE over the token vocabulary is walked once
per live DFA state to produce

  * mask [num_states, vocab] bool — token allowed in state s iff its whole
    byte string stays inside LIVE DFA states (an accept state stays
    reachable), plus EOS exactly in accept states;
  * next_state [num_states, vocab] int32 — where the token's bytes land
    (0 where disallowed — unreachable by construction, the mask bans it).

The trie shares prefix walks across the vocab (one DFS per state, dead
byte prunes the whole subtree) — compile cost is O(states x trie nodes)
instead of O(states x vocab x token_len).

EOS forcing needs no special case: an accept state with no live outgoing
byte has an all-False row except EOS, so the masked sampler can only end
the generation there. A non-accepting state whose row comes out all-False
(possible when no single token covers a required byte sequence) gets EOS
as a documented escape hatch — strictly better than the NaN an all -inf
logits row would produce.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional

import numpy as np

from .regex import compile_regex
from .schema import constraint_to_regex
from .vocab import TokenVocab


class ConstraintError(ValueError):
    """Malformed constraint spec (serving edge answers 400)."""


def parse_constraint_spec(raw) -> dict:
    """Validate a wire-format constraint into {"kind": ..., ...}.

    Wire format (the /generate "constraint" field): an object with exactly
    one of `regex` (string), `choices` (non-empty list of non-empty
    strings), `json_schema` (object), or `json_object` (true). The OpenAI
    `response_format` translator produces the same normalized dict.
    """
    if not isinstance(raw, dict):
        raise ConstraintError(
            f"constraint must be an object, got {type(raw).__name__}"
        )
    keys = [k for k in ("regex", "choices", "json_schema", "json_object")
            if raw.get(k) is not None]
    unknown = set(raw) - {"regex", "choices", "json_schema", "json_object"}
    if unknown:
        raise ConstraintError(
            f"unknown constraint fields {sorted(unknown)}"
        )
    if len(keys) != 1:
        raise ConstraintError(
            "constraint needs exactly one of 'regex', 'choices', "
            "'json_schema', 'json_object'"
        )
    kind = keys[0]
    if kind == "regex":
        pat = raw["regex"]
        if not isinstance(pat, str) or not pat:
            raise ConstraintError("constraint regex must be a non-empty string")
        return {"kind": "regex", "pattern": pat}
    if kind == "choices":
        ch = raw["choices"]
        if not (isinstance(ch, list) and ch
                and all(isinstance(c, str) and c for c in ch)):
            raise ConstraintError(
                "constraint choices must be a non-empty list of non-empty "
                "strings"
            )
        return {"kind": "choices", "choices": list(ch)}
    if kind == "json_schema":
        sch = raw["json_schema"]
        if not isinstance(sch, dict):
            raise ConstraintError("json_schema must be a schema object")
        return {"kind": "json_schema", "schema": sch}
    if raw["json_object"] is not True:
        raise ConstraintError("json_object must be true")
    return {"kind": "json_object"}


def constraint_key(spec: dict) -> str:
    """Canonical hash of a normalized spec — the compiled-artifact cache
    key (engine LRU + the continuous fleet's residency registry)."""
    blob = json.dumps(spec, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclasses.dataclass
class CompiledConstraint:
    """The device-ready artifact. State 0 is the DFA start state."""

    mask: np.ndarray  # [S, V] bool
    next_state: np.ndarray  # [S, V] int32
    start: int
    key: str
    spec: dict
    _dev: Optional[tuple] = dataclasses.field(default=None, repr=False)

    @property
    def num_states(self) -> int:
        return self.mask.shape[0]

    def device_tables(self):
        """(mask, next_state) as device arrays, uploaded once per artifact
        (the engine's artifact cache keeps them warm across requests)."""
        if self._dev is None:
            import jax.numpy as jnp

            self._dev = (jnp.asarray(self.mask), jnp.asarray(self.next_state))
        return self._dev

    def state_bias(self, state: int) -> np.ndarray:
        """[V] f32 added to the PREFILL logits when the FSM sits at
        `state`: 0 where the state allows the token, a -1e9 floor
        otherwise — rides the existing logit_bias operand, so constrained
        prefill reuses the already-compiled bias program variants. The
        scheduler's crash-recovery continuation prefill samples from a
        mid-constraint state (the DFA advanced over the salvaged tokens),
        hence the state parameter."""
        return np.where(self.mask[state], 0.0, -1e9).astype(np.float32)

    def start_bias(self) -> np.ndarray:
        """state_bias at the DFA start state (the cold-admission case:
        the first token is sampled by prefill, before any decode-loop
        state exists)."""
        return self.state_bias(self.start)

    def advance(self, state: int, token_id: int) -> int:
        """Host-side single-step advance (admission / chunked-stop paths)."""
        return int(self.next_state[state, token_id])


class _Trie:
    __slots__ = ("children", "token_ids")

    def __init__(self):
        self.children: dict = {}
        self.token_ids: list = []


def _build_trie(vocab: TokenVocab) -> _Trie:
    root = _Trie()
    for tid, bs in enumerate(vocab.tokens):
        if not bs:
            continue
        node = root
        for b in bs:
            nxt = node.children.get(b)
            if nxt is None:
                nxt = node.children[b] = _Trie()
            node = nxt
        node.token_ids.append(tid)
    return root


def compile_constraint(raw_or_spec: dict, vocab: TokenVocab,
                       trie: Optional[_Trie] = None) -> CompiledConstraint:
    """Wire-format or normalized spec -> CompiledConstraint.

    Raises ConstraintError (bad spec), SchemaError (unsupported schema),
    or RegexError (unsupported/oversized pattern) — all ValueError
    subclasses, so the engine's invalid_request envelope covers them.
    """
    spec = (
        raw_or_spec if "kind" in raw_or_spec
        else parse_constraint_spec(raw_or_spec)
    )
    dfa = compile_regex(constraint_to_regex(spec))
    if trie is None:
        trie = _build_trie(vocab)
    S, V = dfa.n_states, vocab.vocab_size
    mask = np.zeros((S, V), bool)
    nxt = np.zeros((S, V), np.int32)
    live_states = np.flatnonzero(dfa.live)
    trans = dfa.trans
    live = dfa.live

    for s in live_states:
        # iterative DFS over (trie node, dfa state) — dead bytes prune
        # whole subtrees, shared prefixes walk once
        stack = [(trie, int(s))]
        while stack:
            node, st = stack.pop()
            for tid in node.token_ids:
                mask[s, tid] = True
                nxt[s, tid] = st
            for b, child in node.children.items():
                t = int(trans[st, b])
                if t >= 0 and live[t]:
                    stack.append((child, t))

    for e in vocab.eos_ids:
        if 0 <= e < V:
            mask[np.flatnonzero(dfa.accept), e] = True
            nxt[:, e] = np.arange(S, dtype=np.int32)
    # escape hatch: a live non-accept state no token can serve would hand
    # the sampler an all -inf row (NaN); allow EOS there instead
    stuck = ~mask.any(axis=1)
    if stuck.any() and vocab.eos_ids:
        mask[stuck, vocab.eos_ids[0]] = True

    return CompiledConstraint(
        mask=mask, next_state=nxt, start=int(dfa.start),
        key=constraint_key(spec), spec=spec,
    )
