"""Token id -> byte string extraction for the constraint compiler.

The DFA runs over UTF-8 bytes, so every sampleable token id needs its exact
byte string. Three extraction paths, matching the tokenizers the stack
serves with (utils/tokenizer.py):

  * ByteTokenizer — the offline fallback: id = byte + OFFSET, exact by
    construction;
  * HF fast/BPE tokenizers — GPT-2-style byte-to-unicode vocabularies
    decode through the standard `bytes_to_unicode` inverse map;
    sentencepiece vocabularies map `▁` to space and `<0xNN>` byte
    tokens to their byte;
  * anything else — per-id `decode([id])`, rejected (token unusable under
    constraints) when the round-trip is lossy (U+FFFD).

Tokens that map to None (special tokens, lossy ids, ids past the
tokenizer's range in a padded model vocab) are simply never allowed by any
constraint mask.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


def _gpt2_unicode_to_bytes() -> dict:
    """Inverse of the GPT-2 `bytes_to_unicode` table (the printable-char
    embedding every byte-level BPE vocab uses)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


_U2B = None


def _token_str_to_bytes(s: str) -> Optional[bytes]:
    """One HF vocab token string -> bytes, or None when unmappable."""
    global _U2B
    if s.startswith("<0x") and s.endswith(">") and len(s) == 6:
        try:
            return bytes([int(s[3:5], 16)])  # sentencepiece byte token
        except ValueError:
            return None
    if "▁" in s:  # sentencepiece word-start marker
        return s.replace("▁", " ").encode("utf-8")
    if _U2B is None:
        _U2B = _gpt2_unicode_to_bytes()
    if all(c in _U2B for c in s):
        return bytes(_U2B[c] for c in s)
    return s.encode("utf-8")


@dataclasses.dataclass
class TokenVocab:
    """Per-id byte strings + the stop/special bookkeeping tables.py needs.

    tokens[i] is the byte string id `i` appends to the output text, or None
    when the id must never be sampled under a constraint (special token,
    lossy mapping, out of tokenizer range).
    """

    tokens: list
    eos_ids: tuple  # allowed exactly in DFA accept states
    vocab_size: int

    @classmethod
    def from_tokenizer(cls, tokenizer, vocab_size: int,
                       eos_ids: tuple, special_ids: tuple) -> "TokenVocab":
        """`eos_ids`: cfg.all_stop_ids — any of them may end a completed
        constraint. `special_ids`: never sampleable (pad/bos + stop ids)."""
        from ..utils.tokenizer import ByteTokenizer, HFTokenizer

        banned = set(int(i) for i in special_ids) | set(
            int(i) for i in eos_ids
        )
        tokens: list = [None] * vocab_size
        if isinstance(tokenizer, ByteTokenizer):
            off = ByteTokenizer.OFFSET
            for i in range(off, min(vocab_size, 256 + off)):
                if i not in banned:
                    tokens[i] = bytes([i - off])
        elif isinstance(tokenizer, HFTokenizer):
            tok = tokenizer._tok
            special = set(
                int(i) for i in getattr(tok, "all_special_ids", []) or []
            ) | banned
            n = min(vocab_size, int(tok.vocab_size))
            strs = tok.convert_ids_to_tokens(list(range(n)))
            for i, s in enumerate(strs):
                if i in special or not isinstance(s, str) or not s:
                    continue
                tokens[i] = _token_str_to_bytes(s)
        else:
            # generic duck-typed tokenizer (tests): per-id decode, lossy
            # round-trips rejected
            for i in range(vocab_size):
                if i in banned:
                    continue
                try:
                    s = tokenizer.decode([i], skip_special_tokens=False)
                except Exception:
                    continue
                if s and "�" not in s:
                    tokens[i] = s.encode("utf-8")
        return cls(tokens=tokens, eos_ids=tuple(int(i) for i in eos_ids),
                   vocab_size=vocab_size)
