"""Regex subset -> byte-level DFA (full-match semantics).

Pipeline: pattern string -> AST -> Thompson NFA over UTF-8 BYTES -> subset
construction -> dense DFA (`trans [S, 256]` int32 with -1 = dead,
`accept [S]` bool) -> live-state set (states from which an accept state is
reachable). Everything downstream (tables.py) only ever walks live states,
so a token whose bytes stray into a dead path is simply disallowed.

Supported syntax (the subset the JSON-schema compiler and the serving
surface need — unsupported constructs raise RegexError, never silently
mis-match): literals (unicode literals expand to their UTF-8 byte
sequence), `.` (any byte except \\n), escapes (\\d \\D \\w \\W \\s \\S,
\\n \\t \\r \\f \\v, \\xNN, and escaped punctuation), character classes
`[...]` / `[^...]` with ranges, groups `(...)`, alternation `|`, and
quantifiers `*` `+` `?` `{m}` `{m,}` `{m,n}`.

Not supported: anchors (matching is whole-string anyway), backreferences,
lookaround, lazy quantifiers (irrelevant: a DFA has no match order), and
named/capturing group semantics (groups only group).
"""

from __future__ import annotations

import dataclasses

import numpy as np

MAX_DFA_STATES = 4096
MAX_REPEAT = 512

_META = set("\\^$.|?*+()[]{}")


class RegexError(ValueError):
    """Unsupported or malformed pattern."""


def escape_literal(text: str) -> str:
    """Escape `text` so the parser treats it as a literal."""
    return "".join("\\" + c if c in _META else c for c in text)


# -- AST ---------------------------------------------------------------------
# ('set', frozenset[int])       one byte from the set
# ('cat', [nodes])              concatenation
# ('alt', [nodes])              alternation
# ('rep', node, m, n|None)      repeat m..n times (None = unbounded)

_DIGITS = frozenset(range(0x30, 0x3A))
_WORD = frozenset(
    list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
    + list(range(0x61, 0x7B)) + [0x5F]
)
_SPACE = frozenset(b" \t\n\r\f\v")
_ALL = frozenset(range(256))
_DOT = _ALL - {0x0A}


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def error(self, msg: str):
        raise RegexError(f"{msg} at position {self.i} in {self.p!r}")

    def peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self):
        c = self.peek()
        if c is None:
            self.error("unexpected end of pattern")
        self.i += 1
        return c

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            self.error(f"unexpected {self.p[self.i]!r}")
        return node

    def _alt(self):
        branches = [self._cat()]
        while self.peek() == "|":
            self.next()
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        parts = []
        while self.peek() is not None and self.peek() not in "|)":
            parts.append(self._repeat())
        return ("cat", parts)

    def _repeat(self):
        atom = self._atom()
        c = self.peek()
        if c == "*":
            self.next()
            return ("rep", atom, 0, None)
        if c == "+":
            self.next()
            return ("rep", atom, 1, None)
        if c == "?":
            self.next()
            return ("rep", atom, 0, 1)
        if c == "{":
            return self._braces(atom)
        return atom

    def _braces(self, atom):
        self.next()  # '{'
        lo = self._int()
        hi = lo
        if self.peek() == ",":
            self.next()
            hi = self._int() if self.peek() != "}" else None
        if self.next() != "}":
            self.error("expected '}'")
        if hi is not None and hi < lo:
            self.error(f"bad repeat bounds {{{lo},{hi}}}")
        if lo > MAX_REPEAT or (hi or 0) > MAX_REPEAT:
            self.error(f"repeat bound exceeds {MAX_REPEAT}")
        return ("rep", atom, lo, hi)

    def _int(self) -> int:
        start = self.i
        while self.peek() is not None and self.peek().isdigit():
            self.next()
        if start == self.i:
            self.error("expected a number")
        return int(self.p[start: self.i])

    def _atom(self):
        c = self.next()
        if c == "(":
            node = self._alt()
            if self.next() != ")":
                self.error("expected ')'")
            return node
        if c == "[":
            return self._cls()
        if c == ".":
            return ("set", _DOT)
        if c == "\\":
            return self._escape(in_class=False)
        if c in "^$":
            self.error(f"anchors ({c!r}) are not supported; matching is "
                       "whole-string")
        if c in "*+?{":
            self.error(f"quantifier {c!r} with nothing to repeat")
        return _literal_node(c)

    def _escape(self, in_class: bool):
        c = self.next()
        simple = {
            "d": _DIGITS, "D": _ALL - _DIGITS,
            "w": _WORD, "W": _ALL - _WORD,
            "s": _SPACE, "S": _ALL - _SPACE,
        }
        if c in simple:
            return ("set", simple[c])
        ctrl = {"n": 0x0A, "t": 0x09, "r": 0x0D, "f": 0x0C, "v": 0x0B,
                "0": 0x00}
        if c in ctrl:
            return ("set", frozenset({ctrl[c]}))
        if c == "x":
            h = self.next() + self.next()
            try:
                return ("set", frozenset({int(h, 16)}))
            except ValueError:
                self.error(f"bad \\x escape {h!r}")
        if c.isalnum():
            self.error(f"unsupported escape \\{c}")
        return _literal_node(c)

    def _cls(self):
        negate = self.peek() == "^"
        if negate:
            self.next()
        members: set[int] = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                self.error("unterminated character class")
            if c == "]" and not first:
                self.next()
                break
            first = False
            lo = self._cls_member()
            if self.peek() == "-" and self.i + 1 < len(self.p) and \
                    self.p[self.i + 1] != "]":
                self.next()
                hi = self._cls_member()
                if not (len(lo) == len(hi) == 1):
                    self.error("class range endpoints must be single bytes")
                a, b = min(lo), min(hi)
                if b < a:
                    self.error(f"reversed class range")
                members.update(range(a, b + 1))
            else:
                members.update(lo)
        return ("set", frozenset(_ALL - members if negate else members))

    def _cls_member(self) -> frozenset:
        c = self.next()
        if c == "\\":
            node = self._escape(in_class=True)
            return node[1]
        b = c.encode("utf-8")
        if len(b) != 1:
            self.error(f"non-ASCII char {c!r} in class (use it as a literal "
                       "outside the class instead)")
        return frozenset({b[0]})


def _literal_node(char: str):
    """A literal char: one byte-set, or a cat of byte-sets for multi-byte
    UTF-8 (each byte matched exactly)."""
    b = char.encode("utf-8")
    if len(b) == 1:
        return ("set", frozenset({b[0]}))
    return ("cat", [("set", frozenset({x})) for x in b])


# -- Thompson NFA ------------------------------------------------------------


class _Nfa:
    """eps[s] = list of eps-targets; edge[s] = (byteset, target) or None."""

    def __init__(self):
        self.eps: list[list[int]] = []
        self.edge: list = []

    def state(self) -> int:
        self.eps.append([])
        self.edge.append(None)
        return len(self.eps) - 1

    def build(self, node) -> tuple[int, int]:
        kind = node[0]
        if kind == "set":
            s, e = self.state(), self.state()
            self.edge[s] = (node[1], e)
            return s, e
        if kind == "cat":
            if not node[1]:
                s = self.state()
                return s, s
            s, e = self.build(node[1][0])
            for sub in node[1][1:]:
                s2, e2 = self.build(sub)
                self.eps[e].append(s2)
                e = e2
            return s, e
        if kind == "alt":
            s, e = self.state(), self.state()
            for sub in node[1]:
                bs, be = self.build(sub)
                self.eps[s].append(bs)
                self.eps[be].append(e)
            return s, e
        if kind == "rep":
            _, sub, lo, hi = node
            s = self.state()
            cur = s
            for _ in range(lo):
                bs, be = self.build(sub)
                self.eps[cur].append(bs)
                cur = be
            if hi is None:  # star tail
                bs, be = self.build(sub)
                self.eps[cur].append(bs)
                self.eps[be].append(cur)
                return s, cur
            e = self.state()
            self.eps[cur].append(e)
            for _ in range(hi - lo):
                bs, be = self.build(sub)
                self.eps[cur].append(bs)
                cur = be
                self.eps[cur].append(e)
            return s, e
        raise RegexError(f"unknown AST node {kind!r}")


@dataclasses.dataclass
class Dfa:
    """Dense byte-level DFA. trans[s, b] = next state or -1 (dead);
    live[s] = an accept state is reachable from s (s itself counts)."""

    trans: np.ndarray  # [S, 256] int32
    accept: np.ndarray  # [S] bool
    live: np.ndarray  # [S] bool
    start: int = 0

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]


def compile_regex(pattern: str) -> Dfa:
    """Pattern -> byte-level DFA with full-match semantics."""
    ast = _Parser(pattern).parse()
    nfa = _Nfa()
    start, end = nfa.build(ast)

    def closure(states: frozenset) -> frozenset:
        out = set(states)
        stack = list(states)
        while stack:
            for t in nfa.eps[stack.pop()]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    start_set = closure(frozenset({start}))
    index = {start_set: 0}
    order = [start_set]
    rows = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        # bucket this subset's outgoing byte-sets once, then resolve each
        # byte against the handful of distinct edges (not 256 x edges)
        edges = [nfa.edge[s] for s in cur if nfa.edge[s] is not None]
        row = np.full((256,), -1, np.int32)
        if edges:
            targets: dict[int, set] = {}
            for byteset, tgt in edges:
                for b in byteset:
                    targets.setdefault(b, set()).add(tgt)
            for b, tset in targets.items():
                nxt = closure(frozenset(tset))
                j = index.get(nxt)
                if j is None:
                    if len(order) >= MAX_DFA_STATES:
                        raise RegexError(
                            f"constraint DFA exceeds {MAX_DFA_STATES} "
                            f"states; simplify the pattern"
                        )
                    j = len(order)
                    index[nxt] = j
                    order.append(nxt)
                row[b] = j
        rows.append(row)

    trans = np.stack(rows) if rows else np.full((1, 256), -1, np.int32)
    accept = np.asarray([end in s for s in order], bool)
    # live = backward reachability to an accept state
    live = accept.copy()
    changed = True
    while changed:
        changed = False
        # any state with a transition into a live state becomes live
        hits = np.isin(trans, np.flatnonzero(live)) & (trans >= 0)
        new_live = live | hits.any(axis=1)
        if (new_live != live).any():
            live = new_live
            changed = True
    if not live[0]:
        raise RegexError(f"pattern {pattern!r} matches no string")
    return Dfa(trans=trans, accept=accept, live=live)
