"""JSON-schema subset / generic-JSON grammar -> regex (schema-guided decoding).

The supported schema subset (the ISSUE's contract): `type` object / array /
string / number / integer / boolean / null, `enum`, object `properties` +
`required`, array `items`. Anything else raises SchemaError -> a clean 400
at the serving edge, never a silently-wrong grammar.

Termination discipline: every produced regex is BOUNDED — strings cap at
MAX_STRING_LEN chars, numbers at fixed digit widths, arrays at MAX_ITEMS
elements, and the generic-JSON grammar (`json_object`) recurses to
MAX_DEPTH. A bounded grammar compiles to an ACYCLIC DFA, so constrained
greedy decode provably terminates (the accept-with-no-continuation state
forces EOS) instead of letting the model pad a string literal until the
token budget dies. Output is compact JSON (no inter-token whitespace) for
the same reason: an unconstrained whitespace loop never has to end.

Object semantics: properties are emitted in declaration order, every
declared property present (`required` is validated to be a subset of
`properties`; optional properties are currently always emitted — still
schema-valid, and it keeps the comma grammar regular). This is the same
simplification the early schema-guided-decoding literature ships.
"""

from __future__ import annotations

import json

from .regex import escape_literal

# Bounded-grammar constants. Every counted repetition costs its bound in
# DFA states, and the state count multiplies across schema fields — these
# are sized so a realistic schema stays in the low hundreds of states
# (the [S, V] device tables and the Python trie walk both scale with S).
MAX_STRING_LEN = 24
MAX_ITEMS = 4
MAX_DEPTH = 2
_INT_DIGITS = 9
_FRAC_DIGITS = 4


class SchemaError(ValueError):
    """Unsupported or malformed schema."""


# one JSON string character: anything but quote/backslash/control, or a
# \-escape (JSON's single-char escape list; \uXXXX is omitted — its 4-hex
# tail costs 5 states per string position, a 3x table for a escape the
# sampler never needs since raw UTF-8 is allowed)
_CHAR = r'([^"\\\x00-\x1f]|\\["\\/bfnrt])'
_STRING = f'"{_CHAR}{{0,{MAX_STRING_LEN}}}"'
_INTEGER = f"-?(0|[1-9][0-9]{{0,{_INT_DIGITS - 1}}})"
_NUMBER = (
    f"{_INTEGER}(\\.[0-9]{{1,{_FRAC_DIGITS}}})?([eE][+-]?[0-9]{{1,2}})?"
)
_BOOLEAN = "(true|false)"
_NULL = "null"


def _enum_regex(values: list) -> str:
    if not values:
        raise SchemaError("enum must be a non-empty list")
    alts = []
    for v in values:
        if not isinstance(v, (str, int, float, bool)) and v is not None:
            raise SchemaError(f"enum values must be JSON scalars, got {v!r}")
        alts.append(escape_literal(json.dumps(v)))
    return "(" + "|".join(alts) + ")"


def _object_regex(schema: dict, depth: int) -> str:
    props = schema.get("properties")
    if props is None:
        return _generic_value(depth)  # untyped object: generic, bounded
    if not isinstance(props, dict) or not props:
        raise SchemaError("properties must be a non-empty object")
    required = schema.get("required", [])
    if not isinstance(required, list):
        raise SchemaError("required must be a list")
    unknown = [k for k in required if k not in props]
    if unknown:
        raise SchemaError(
            f"required names {unknown} missing from properties"
        )
    fields = [
        f'"{escape_literal(k)}":{schema_to_regex(v, depth)}'
        for k, v in props.items()
    ]
    return "\\{" + ",".join(fields) + "\\}"


def _array_regex(schema: dict, depth: int) -> str:
    items = schema.get("items")
    item = (
        schema_to_regex(items, depth) if items is not None
        else _generic_value(depth)
    )
    return f"\\[({item}(,{item}){{0,{MAX_ITEMS - 1}}})?\\]"


# the GENERIC grammar (untyped values / json_object mode) multiplies its
# own size once per nesting level, so it runs on tighter bounds than the
# schema-typed grammar: without a schema there is no structure to spend
# states on, only breadth. These also bound the WORST-CASE derivation
# (~160 bytes) — an adversarial argmax must complete its object inside an
# ordinary decode budget, or every truncated reply breaks the
# guaranteed-JSON contract.
_GEN_STRING_LEN = 12
_GEN_ITEMS = 2
_GEN_STRING = f'"{_CHAR}{{0,{_GEN_STRING_LEN}}}"'


def _generic_value(depth: int) -> str:
    """Any JSON value, nesting bounded at `depth` (json_object mode)."""
    scalar = f"({_GEN_STRING}|{_NUMBER}|{_BOOLEAN}|{_NULL})"
    if depth <= 0:
        return scalar
    inner = _generic_value(depth - 1)
    obj = (
        f'\\{{({_GEN_STRING}:{inner}(,{_GEN_STRING}:{inner})'
        f"{{0,{_GEN_ITEMS - 1}}})?\\}}"
    )
    arr = f"\\[({inner}(,{inner}){{0,{_GEN_ITEMS - 1}}})?\\]"
    return f"({scalar}|{obj}|{arr})"


def schema_to_regex(schema: dict, depth: int = MAX_DEPTH) -> str:
    if not isinstance(schema, dict):
        raise SchemaError(f"schema must be an object, got {type(schema).__name__}")
    if depth < 0:
        raise SchemaError(f"schema nests deeper than {MAX_DEPTH}")
    if "enum" in schema:
        return _enum_regex(schema["enum"])
    t = schema.get("type")
    if t is None:
        return _generic_value(min(depth, MAX_DEPTH))
    if isinstance(t, list):
        return "(" + "|".join(
            schema_to_regex({**schema, "type": x}, depth) for x in t
        ) + ")"
    if t == "object":
        return _object_regex(schema, depth - 1)
    if t == "array":
        return _array_regex(schema, depth - 1)
    if t == "string":
        return _STRING
    if t == "integer":
        return _INTEGER
    if t == "number":
        return _NUMBER
    if t == "boolean":
        return _BOOLEAN
    if t == "null":
        return _NULL
    raise SchemaError(f"unsupported schema type {t!r}")


def constraint_to_regex(spec: dict) -> str:
    """Normalized constraint spec (tables.parse_constraint_spec) -> the one
    regex everything compiles through."""
    kind = spec["kind"]
    if kind == "regex":
        return spec["pattern"]
    if kind == "choices":
        return "(" + "|".join(escape_literal(c) for c in spec["choices"]) + ")"
    if kind == "json_schema":
        return schema_to_regex(spec["schema"])
    if kind == "json_object":
        # a generic JSON OBJECT (OpenAI json_object mode promises an
        # object, not any value), members bounded like _generic_value
        inner = _generic_value(MAX_DEPTH - 1)
        return (
            f'\\{{({_GEN_STRING}:{inner}(,{_GEN_STRING}:{inner})'
            f"{{0,{_GEN_ITEMS - 1}}})?\\}}"
        )
    raise SchemaError(f"unknown constraint kind {kind!r}")
