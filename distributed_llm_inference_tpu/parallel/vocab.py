"""Vocab-sharded embedding + LM head over the pipeline mesh axis.

Round-1 review finding: replicating embed + lm_head on every device costs
~2.1 GB bf16 per device for a Llama-3-8B-class model on an 8-stage mesh.
Here both ends of the model shard their VOCAB dimension over `pp` (the
axis every SPMD backend always has):

  * embed [V, D] shards rows: a lookup is a local gather of the ids that
    land in this shard (others contribute zeros) + a `psum` over pp —
    each id lives in exactly one shard, so the psum adds one real row to
    zeros and the result is bit-identical to the replicated lookup;
  * lm_head [D, V] (or the tied embed transposed) shards columns: each
    device computes its [.., V/pp] logits slice and an `all_gather`
    concatenates them — columns of a matmul are independent, so this too
    is bit-identical to the replicated matmul.

V is padded up to a multiple of pp at shard time (pad_vocab); pad rows
are all-zero and pad logit columns are sliced off after the gather, so
they can never be sampled.

Comms per decode step: one [B, D] psum (embedding) + one [B, V] fp32
all_gather (logits) — both tiny next to a layer's weights streaming from
HBM, and the all_gather replaces the fp32 [B, V] masked psum the round-1
pipeline used anyway. In exchange every device holds only 1/pp of the
embedding + head instead of full copies.

These functions run INSIDE shard_map bodies: `shared` leaves are local
shards, and `pp` is the static pipeline-axis size (psum/all_gather over
an axis of size 1 are no-ops, so the sp-only context backend reuses the
same code path unchanged).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..ops.norms import layer_norm, rms_norm
from ..ops.quant import matmul as qmm
from .mesh import AXIS_PP

# shared leaves sharded on a vocab dim (leaf name -> vocab axis index)
VOCAB_SHARDED = {"embed": 0, "lm_head": 1}


def padded_vocab(vocab_size: int, pp: int) -> int:
    return -(-vocab_size // pp) * pp


def pad_vocab(cfg: ModelConfig, shared: dict, pp: int) -> dict:
    """Zero-pad the vocab dim of embed/lm_head to a multiple of pp.

    A quantized lm_head (ops/quant.QTensor / Q4Tensor) pads both the int
    columns (zeros) and their scales (zeros) — pad logits come out 0 and
    are sliced off after the gather either way."""
    from ..ops.quant import Q4Tensor, QTensor

    V_pad = padded_vocab(cfg.vocab_size, pp)
    if V_pad == cfg.vocab_size:
        return shared
    out = dict(shared)
    for name, axis in VOCAB_SHARDED.items():
        if name not in shared:
            continue
        x = shared[name]
        if isinstance(x, QTensor):
            n = V_pad - x.q.shape[axis]
            qpad = [(0, 0)] * x.q.ndim
            qpad[axis] = (0, n)
            out[name] = QTensor(jnp.pad(x.q, qpad), jnp.pad(x.s, [(0, n)]))
        elif isinstance(x, Q4Tensor):
            # lm_head q [G, g/2, V], s [G, V]: vocab is the LAST axis of
            # both — the packed nibble axis is untouched
            n = V_pad - x.q.shape[-1]
            out[name] = Q4Tensor(
                jnp.pad(x.q, [(0, 0), (0, 0), (0, n)]),
                jnp.pad(x.s, [(0, 0), (0, n)]),
                x.g,
            )
        else:
            pad = [(0, 0)] * x.ndim
            pad[axis] = (0, V_pad - x.shape[axis])
            out[name] = jnp.pad(x, pad)
    return out


def embed_sharded(cfg: ModelConfig, shared: dict, tokens: jnp.ndarray, pos, pp: int):
    """[B, T] ids -> [B, T, D] activations, replicated over pp.

    shared["embed"] is the LOCAL [V_pad/pp, D] row shard. Bit-identical to
    models/*.embed on replicated weights (reference orchestration.py:111).
    """
    e = shared["embed"]
    V_loc = e.shape[0]
    lo = jax.lax.axis_index(AXIS_PP) * V_loc
    idx = tokens - lo
    valid = (idx >= 0) & (idx < V_loc)
    x = e[jnp.clip(idx, 0, V_loc - 1)]
    x = jnp.where(valid[..., None], x, jnp.zeros((), x.dtype))
    if pp > 1:
        # jaxlint: disable=comms-wire-coverage -- one-hot shard merge: each id lives in exactly one vocab shard, so this psum adds one real [B, T, D] row-set to zeros; quantizing it is the embed half of the ROADMAP logits item
        x = jax.lax.psum(x, AXIS_PP)
    if cfg.embed_scale:  # gemma: sqrt(dim) in the activation dtype
        x = x * jnp.asarray(cfg.dim ** 0.5, x.dtype)
    if cfg.embed_multiplier is not None:  # granite
        x = x * jnp.asarray(cfg.embed_multiplier, x.dtype)
    if cfg.use_learned_pos:  # gpt2: add (replicated) position rows once
        T = tokens.shape[1]
        pos = jnp.asarray(pos, jnp.int32)
        if pos.ndim == 1:  # slots mode: per-row positions
            positions = pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
            x = x + shared["pos_embed"][positions]
        else:
            positions = pos + jnp.arange(T, dtype=jnp.int32)
            x = x + shared["pos_embed"][positions][None, :, :]
    return x


def unembed_sharded(cfg: ModelConfig, shared: dict, x: jnp.ndarray, pp: int):
    """[B, T, D] (replicated) -> [B, T, V] fp32 logits, replicated over pp.

    Final norm weights are replicated; the head matmul runs on the local
    column shard and the slices are concatenated with a tiled all_gather.
    Bit-identical to models/*.unembed (reference orchestration.py:140-141).
    """
    if cfg.arch == "gpt2":
        h = layer_norm(x, shared["final_norm_w"], shared["final_norm_b"], cfg.norm_eps)
    else:
        h = rms_norm(x, shared["final_norm"], cfg.norm_eps,
                     unit_offset=cfg.norm_unit_offset)
    if cfg.tie_embeddings:
        lg = (h @ shared["embed"].T).astype(jnp.float32)  # [B, T, V_pad/pp]
    else:
        # qmm: dense array or int8 QTensor column shard transparently
        lg = qmm(h, shared["lm_head"]).astype(jnp.float32)
    if pp > 1:
        # jaxlint: disable=comms-wire-coverage -- THE fat collective: fp32 [B, T, V_pad/pp] logits gather, tracked in FAT_INVENTORY (analysis/comms.py) as the ROADMAP quantized-logits worklist seed
        lg = jax.lax.all_gather(lg, AXIS_PP, axis=lg.ndim - 1, tiled=True)
    lg = lg[..., : cfg.vocab_size]
    if cfg.final_softcap is not None:  # gemma-2
        lg = cfg.final_softcap * jnp.tanh(lg / cfg.final_softcap)
    if cfg.logits_divider is not None:  # granite
        lg = lg / cfg.logits_divider
    return lg
