"""Ring attention + context-parallel decode over a sequence (`sp`) mesh axis.

Long-context support the reference cannot express at all — its whole
sequence lives on every stage and is re-sent over the WAN four times per
token (/root/reference/Worker1.py:82-177, orchestration.py:114-137). Here
the SEQUENCE is the sharded axis:

  * `ring_attend` — causal flash attention where Q stays put and K/V
    chunks rotate around the `sp` ring via `lax.ppermute` (one hop per
    step, compute overlapped by XLA's async collective-permute). Each
    device holds seq/sp of the context, so max context scales linearly
    with the ring size; per-hop traffic is O(chunk), all on ICI.

  * `cp_decode_attend` — decode-time context parallelism: the KV cache is
    sharded across `sp` devices as an UNORDERED set of (key, value,
    position) triples. Softmax over a key set is permutation-invariant,
    so each device attends its local slots (masked by per-slot position
    tags) and the partials merge with one psum/pmax log-sum-exp combine —
    a single collective per layer instead of a ring.

Both operate on the LOCAL shard inside `shard_map` and are verified
against the single-device `ops.attention.attend` in tests/test_ring.py.

Shapes (Tc = local query chunk, Sc = local cache slots, G = H // KV):
  q_local    [B, Tc, H, Dh]
  k/v_local  [B, Tc, KV, Dh]   (ring_attend: this device's seq chunk)
  cache_k/v  [B, KV, Sc, Dh]   (cp_decode_attend: local slot set)
  pos_ids    [Sc] int32        (absolute position per slot, -1 = empty)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.wire_quant import quantize_rows
from .mesh import AXIS_SP

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _gqa_scores(q: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """q [B,T,KV,G,Dh] x k [B,Tk,KV,Dh] -> [B,KV,G,T,Tk] fp32 (unscaled)."""
    return jnp.einsum(
        "btkgd,bskd->bkgts", q, k.astype(jnp.float32)
    )


def _bc(mask: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a [T, Tk] (shared) or [B, T, Tk] (ragged, per-row) mask
    over score shape [B, KV, G, T, Tk]."""
    return mask[None, None, None] if mask.ndim == 2 else mask[:, None, None]


def _raggedize(mask: jnp.ndarray, kv_pos: jnp.ndarray,
               valid_start: jnp.ndarray | None) -> jnp.ndarray:
    """Fold a per-row first-valid-position (left-padded ragged batches,
    ops/attention.ragged_causal_mask semantics) into a shared [T, Tk]
    position mask, giving [B, T, Tk]. kv_pos are ABSOLUTE positions, the
    same coordinate valid_start is expressed in."""
    if valid_start is None:
        return mask
    return mask[None] & (
        kv_pos[None, None, :] >= valid_start[:, None, None]
    )


def ring_attend(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = AXIS_SP,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    *,
    scale: float | None = None,
    softcap: float | None = None,
    window: int | None = None,
    valid_start: jnp.ndarray | None = None,
    wire: bool = False,
) -> jnp.ndarray:
    """Causal ring attention on sequence-sharded Q/K/V chunks.

    Device i holds queries and keys for global positions
    [i*Tc, (i+1)*Tc). K/V rotate around the ring; after sp steps every
    query has seen every key, with causal masking by absolute position.
    Online-softmax merge keeps only (m, l, acc) between steps.

    q [B,Tc,H,Dh], k/v [B,Tc,KV,Dh] (local chunks) -> [B,Tc,H,Dh].
    k_scale/v_scale [B,Tc,KV] (int8 caches, ops/kv_quant): k/v are int8
    chunks and the SCALES rotate with them — each ppermute hop ships
    int8 + one fp32 scale per (token, head) (~4x fewer ICI bytes than
    rotating the dequantized fp32 chunks), and dequant happens at use,
    where the scores einsum upcasts to fp32 anyway.
    valid_start [B] int32 (ragged left-padded batches): keys at absolute
    positions < valid_start[b] are row-b padding and masked out — the
    mask gains a batch dim, nothing else changes (pad QUERY rows produce
    all-masked scores and are already guarded by the l==0 floor).
    wire (EngineConfig.pp_wire_quant): raw-dtype K/V chunks adopt the
    int8 cache's rotation recipe — quantized ONCE at entry with the same
    per-(token, head) scales (ops/wire_quant.quantize_rows), int8 +
    scales rotate, dequant at use — so every ICI hop ships int8 whether
    the CACHE is quantized or not. Identical numerics to an int8 cache's
    ring; a no-op when k_scale is already present.
    """
    sp = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Tc, H, Dh = q.shape
    KV = k.shape[2]
    G = H // KV
    if scale is None:
        scale = Dh**-0.5
    if wire and k_scale is None:
        k, k_scale = quantize_rows(k)
        v, v_scale = quantize_rows(v)
    quant = k_scale is not None

    qg = (q.astype(jnp.float32) * scale).reshape(B, Tc, KV, G, Dh)
    q_pos = my * Tc + jnp.arange(Tc, dtype=jnp.int32)  # [Tc]
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def deq(c, s_):
        return c.astype(jnp.float32) * s_[..., None] if quant else c

    def update(s, m, l, acc, kc, vc, ksc, vsc):
        """Online-softmax update with the chunk held at ring step s."""
        src = (my - s) % sp  # chunk id currently held
        kv_pos = src * Tc + jnp.arange(Tc, dtype=jnp.int32)
        mask = kv_pos[None, :] <= q_pos[:, None]  # [Tc, Tc_k]
        if window is not None:  # uniform sliding window (Mistral-style)
            mask &= kv_pos[None, :] > q_pos[:, None] - window
        mask = _raggedize(mask, kv_pos, valid_start)
        scores = _gqa_scores(qg, deq(kc, ksc))  # [B,KV,G,Tc,Tc]
        if softcap is not None:  # Gemma-2 logit capping, pre-mask (HF order)
            scores = softcap * jnp.tanh(scores / softcap)
        scores = jnp.where(_bc(mask), scores, _NEG)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        p = jnp.where(_bc(mask), p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bkgts,bskd->bkgtd", p, deq(vc, vsc).astype(jnp.float32)
        )
        return m_new, l, acc

    # the rotating pytree carries the scales ONLY in quant mode: a dummy
    # array would come back from ppermute tagged varying-over-sp and
    # mismatch the loop carry type
    def step(s, carry):
        m, l, acc, kv_c = carry
        # Rotate FIRST (chunk ids held locally decrease by one per step, so
        # causal work stays contiguous); step 0 runs outside the loop on the
        # resident chunk, so only the sp-1 needed hops are ever sent.
        # jaxlint: disable=comms-wire-coverage -- K/V pre-quantized ONCE at entry under `wire` (int8 + scales rotate as one pytree); per-hop wire_ppermute would requantize sp-1 times
        kv_c = jax.lax.ppermute(kv_c, axis_name, perm)
        kc, vc, ksc, vsc = kv_c if quant else (*kv_c, None, None)
        m, l, acc = update(s, m, l, acc, kc, vc, ksc, vsc)
        return m, l, acc, kv_c

    m0 = jnp.full((B, KV, G, Tc, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((B, KV, G, Tc, 1), jnp.float32)
    a0 = jnp.zeros((B, KV, G, Tc, Dh), jnp.float32)
    m0, l0, a0 = update(0, m0, l0, a0, k, v, k_scale, v_scale)
    kv_c0 = (k, v, k_scale, v_scale) if quant else (k, v)
    m, l, acc, _ = jax.lax.fori_loop(1, sp, step, (m0, l0, a0, kv_c0))

    l = jnp.where(l == 0.0, 1.0, l)  # only padding rows can be all-masked
    out = acc / l  # [B,KV,G,Tc,Dh]
    out_dtype = q.dtype
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Tc, H, Dh).astype(out_dtype)


def ulysses_attend(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = AXIS_SP,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    *,
    scale: float | None = None,
    softcap: float | None = None,
    window: int | None = None,
    valid_start: jnp.ndarray | None = None,
    wire: bool = False,
) -> jnp.ndarray:
    """Ulysses-style (DeepSpeed) sequence parallelism: two all-to-alls
    instead of a ring.

    Input is sequence-sharded like ring_attend (device i holds positions
    [i*Tc, (i+1)*Tc)). One `all_to_all` re-shards from sequence to HEADS —
    every device then holds the FULL sequence for H/sp of the heads — local
    full causal attention runs with no per-step collective, and a second
    all_to_all restores the sequence sharding. Versus the ring: 2 fat a2a
    hops instead of sp-1 thin ppermute hops, and plain (unrolled-free)
    attention in between — typically wins when sp is large or the chunk is
    small enough that ring step overhead dominates.

    Requires n_heads % sp == 0 AND n_kv_heads % sp == 0 (kv heads scatter
    too). q [B,Tc,H,Dh], k/v [B,Tc,KV,Dh] -> [B,Tc,H,Dh].
    k_scale/v_scale [B,Tc,KV]: int8 chunks + scales ride the a2a (same
    traffic saving as ring_attend's quantized rotation), dequantized at
    use after the re-shard.
    wire: as in ring_attend — raw-dtype K/V quantize once at entry so
    the two fat a2a hops ship int8 + scales; q stays full precision
    (matching the int8-cache recipe, which never quantizes queries).
    """
    sp = jax.lax.psum(1, axis_name)
    B, Tc, H, Dh = q.shape
    if wire and k_scale is None:
        k, k_scale = quantize_rows(k)
        v, v_scale = quantize_rows(v)
    quant = k_scale is not None
    # seq -> heads: split the head axis sp ways, concat chunks on the
    # sequence axis (tiled a2a concatenates in ring order, so positions
    # stay globally ordered)
    # jaxlint: disable=comms-wire-coverage -- queries stay full precision by the int8-cache recipe (never quantized); K/V ship int8 below
    qh = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    # jaxlint: disable=comms-wire-coverage -- K pre-quantized at entry under `wire`: this a2a ships int8, its scales re-shard separately below
    kh = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    # jaxlint: disable=comms-wire-coverage -- V pre-quantized at entry under `wire`: this a2a ships int8, its scales re-shard separately below
    vh = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    if quant:
        # scales re-shard with their chunks; dequant happens PER KEY BLOCK
        # inside the loop below — materializing fp32 kh/vh up front would
        # 4x the K/V residency on exactly the long contexts sp serves
        # jaxlint: disable=comms-wire-coverage -- fp32 scale companion of the int8 K a2a (one scalar per (token, head) row)
        ksh = jax.lax.all_to_all(
            k_scale, axis_name, split_axis=2, concat_axis=1, tiled=True
        )
        # jaxlint: disable=comms-wire-coverage -- fp32 scale companion of the int8 V a2a (one scalar per (token, head) row)
        vsh = jax.lax.all_to_all(
            v_scale, axis_name, split_axis=2, concat_axis=1, tiled=True
        )
    T = qh.shape[1]  # full sequence
    Hl, KVl = qh.shape[2], kh.shape[2]
    G = Hl // KVl
    if scale is None:
        scale = Dh**-0.5

    # Local full-sequence attention in KEY BLOCKS with an online-softmax
    # accumulator — an unblocked [T, T] score matrix would peak sp x ring's
    # attention memory on exactly the long contexts the sp axis exists
    # for; blocked at Tc keys, the peak is Hl x T x Tc scores, the same
    # H·T²/sp² as one ring step.
    qg = (qh.astype(jnp.float32) * scale).reshape(B, T, KVl, G, Dh)
    q_pos = jnp.arange(T, dtype=jnp.int32)

    def block(s, carry):
        m, l, acc = carry
        kc = jax.lax.dynamic_slice_in_dim(kh, s * Tc, Tc, axis=1)
        vc = jax.lax.dynamic_slice_in_dim(vh, s * Tc, Tc, axis=1)
        if quant:
            kc = kc.astype(jnp.float32) * jax.lax.dynamic_slice_in_dim(
                ksh, s * Tc, Tc, axis=1
            )[..., None]
            vc = vc.astype(jnp.float32) * jax.lax.dynamic_slice_in_dim(
                vsh, s * Tc, Tc, axis=1
            )[..., None]
        kv_pos = s * Tc + jnp.arange(Tc, dtype=jnp.int32)
        mask = kv_pos[None, :] <= q_pos[:, None]  # [T, Tc]
        if window is not None:
            mask &= kv_pos[None, :] > q_pos[:, None] - window
        mask = _raggedize(mask, kv_pos, valid_start)
        scores = _gqa_scores(qg, kc)  # [B,KVl,G,T,Tc]
        if softcap is not None:
            scores = softcap * jnp.tanh(scores / softcap)
        scores = jnp.where(_bc(mask), scores, _NEG)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        p = jnp.where(_bc(mask), p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bkgts,bskd->bkgtd", p, vc.astype(jnp.float32)
        )
        return m_new, l, acc

    m0 = jnp.full((B, KVl, G, T, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((B, KVl, G, T, 1), jnp.float32)
    a0 = jnp.zeros((B, KVl, G, T, Dh), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, sp, block, (m0, l0, a0))
    l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l).transpose(0, 3, 1, 2, 4).reshape(B, T, Hl, Dh).astype(q.dtype)
    # heads -> seq: inverse a2a
    # jaxlint: disable=comms-wire-coverage -- attention output re-shard: fp32 accumulator precision is the contract here; quantizing it is the ROADMAP fp8 item, not a wire_ppermute retrofit
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def cp_decode_attend(
    q: jnp.ndarray,
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    pos_ids: jnp.ndarray,
    pos: jnp.ndarray,
    axis_name: str = AXIS_SP,
    *,
    scale: float | None = None,
    softcap: float | None = None,
    window: int | None = None,
    valid_start: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Decode attention over a context-sharded KV cache.

    Each device holds an unordered local slot set (cache_k/v + pos_ids);
    a slot participates iff 0 <= pos_ids[s] <= pos. Local flash partials
    (m, l, acc) merge across `sp` with pmax/psum — softmax over a key set
    is permutation-invariant, so slot placement across devices is free.

    q [B,T,H,Dh] (replicated over sp), cache_k/v [B,KV,Sc,Dh],
    pos_ids [Sc], pos scalar int32 -> [B,T,H,Dh] (replicated over sp).
    valid_start [B] int32 (ragged left-padded batches): slots tagged with
    absolute positions < valid_start[b] hold row-b padding and are masked
    for that row — pos_ids carry exactly the coordinate needed.
    """
    B, T, H, Dh = q.shape
    KV, Sc = cache_k.shape[1], cache_k.shape[2]
    G = H // KV
    if scale is None:
        scale = Dh**-0.5

    qg = (q.astype(jnp.float32) * scale).reshape(B, T, KV, G, Dh)
    # A slot participates iff occupied; each query t at absolute position
    # pos+t sees slots with pos_ids <= pos+t (covers T>1 chunked decode).
    q_abs = pos + jnp.arange(T, dtype=jnp.int32)
    mask = (pos_ids >= 0)[None, :] & (pos_ids[None, :] <= q_abs[:, None])  # [T, Sc]
    if window is not None:  # slot tags carry absolute positions: windowing
        mask &= pos_ids[None, :] > q_abs[:, None] - window
    mask = _raggedize(mask, pos_ids, valid_start)
    scores = jnp.einsum(
        "btkgd,bksd->bkgts", qg, cache_k.astype(jnp.float32)
    )
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    scores = jnp.where(_bc(mask), scores, _NEG)
    m_loc = jnp.max(scores, axis=-1, keepdims=True)  # [B,KV,G,T,1]
    p = jnp.exp(scores - m_loc)
    p = jnp.where(_bc(mask), p, 0.0)
    l_loc = jnp.sum(p, axis=-1, keepdims=True)
    acc_loc = jnp.einsum("bkgts,bksd->bkgtd", p, cache_v.astype(jnp.float32))

    # Log-sum-exp merge across the sp axis: one pmax + two psums.
    m_glb = jax.lax.pmax(m_loc, axis_name)
    w = jnp.exp(m_loc - m_glb)
    # jaxlint: disable=comms-wire-coverage -- log-sum-exp partial merge: every shard contributes, so the one-hot masked_psum precondition cannot hold; fp32 partials are the numerics contract
    l_glb = jax.lax.psum(l_loc * w, axis_name)
    # jaxlint: disable=comms-wire-coverage -- log-sum-exp partial merge (see l_glb): all-participant fp32 reduction by design
    acc_glb = jax.lax.psum(acc_loc * w, axis_name)

    l_glb = jnp.where(l_glb == 0.0, 1.0, l_glb)
    out = acc_glb / l_glb  # [B,KV,G,T,Dh]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, H, Dh).astype(q.dtype)


def cp_select_slot(fill: jnp.ndarray, axis_name: str = AXIS_SP):
    """Pick the ring member to store the next decoded token.

    Ownership goes to the LEAST-FILLED shard (ties to the lowest index —
    argmin is deterministic, so every device agrees). Prefill places
    prompt chunks contiguously, which can load one shard up to its whole
    chunk; least-filled placement re-balances decode appends around that,
    so max fill never exceeds max(prefill chunk, ceil(total/sp)+1) and a
    cache sized ceil(max_seq/sp)+1 cannot overflow. (A naive pos % sp
    round-robin would overflow the prefill-heavy shard long before the
    cache is actually full.)

    fill [1] int32 (this device's count) -> (fills [sp] — every device's
    count, identical everywhere; owner_idx [] int32; owner [] bool — True
    on the selected device). Capacity/overflow is checked by the caller
    against its cache: overflow iff fills[owner_idx] >= Sc.
    """
    my = jax.lax.axis_index(axis_name)
    # jaxlint: disable=comms-wire-coverage,comms-fat-collective -- int32 slot-fill control vector, 4*sp bytes/step: not an activation transfer, quantization would save nothing
    fills = jax.lax.all_gather(fill[0], axis_name)  # [sp], same everywhere
    owner_idx = jnp.argmin(fills)
    owner = owner_idx == my
    return fills, owner_idx, owner


def cp_kv_write(
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    slot: jnp.ndarray,
    owner: jnp.ndarray,
):
    """Owner-gated write of one token's K/V at a local slot (SPMD: every
    device runs the write, non-owners read-modify-write their own slot).

    k_new/v_new [B, 1, KV, Dh] -> cache layout [B, KV, Sc, Dh].
    """
    kc = k_new.astype(cache_k.dtype).transpose(0, 2, 1, 3)  # [B,KV,1,Dh]
    vc = v_new.astype(cache_v.dtype).transpose(0, 2, 1, 3)
    zero = jnp.int32(0)
    start = (zero, zero, slot, zero)
    old_k = jax.lax.dynamic_slice(cache_k, start, kc.shape)
    old_v = jax.lax.dynamic_slice(cache_v, start, vc.shape)
    kc = jnp.where(owner, kc, old_k)
    vc = jnp.where(owner, vc, old_v)
    cache_k = jax.lax.dynamic_update_slice(cache_k, kc, start)
    cache_v = jax.lax.dynamic_update_slice(cache_v, vc, start)
    return cache_k, cache_v


def cp_scale_write(
    cache_s: jnp.ndarray,
    s_new: jnp.ndarray,
    slot: jnp.ndarray,
    owner: jnp.ndarray,
):
    """Owner-gated write of one token's quantization SCALE at a local slot
    — the [B, KV, Sc] companion of cp_kv_write for int8 caches
    (ops/kv_quant.KVQuant leaves). s_new [B, 1, KV] (chunk layout) ->
    cache layout [B, KV, Sc]."""
    sc = s_new.transpose(0, 2, 1)  # [B, KV, 1]
    zero = jnp.int32(0)
    start = (zero, zero, slot)
    old = jax.lax.dynamic_slice(cache_s, start, sc.shape)
    sc = jnp.where(owner, sc, old)
    return jax.lax.dynamic_update_slice(cache_s, sc, start)


def cp_cache_append(
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    pos_ids: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
    fill: jnp.ndarray,
    axis_name: str = AXIS_SP,
):
    """Append one decoded token's K/V to the context-sharded cache — the
    one-shot convenience form of (cp_select_slot + cp_kv_write + pos_ids
    tag), which is what parallel/context.py's decode loop does per layer
    with shared slot bookkeeping.

    k_new/v_new [B, 1, KV, Dh]; fill [1] int32 = this device's local fill
    count (shape [1], not scalar, so shard_map can concatenate it over sp).
    Returns (cache_k, cache_v, pos_ids, fill, overflow) — overflow [1] bool
    is True (on every device) when even the least-filled shard is full: the
    token was NOT stored, and the caller must stop decoding. There is no
    silent eviction.
    """
    Sc = cache_k.shape[2]
    fills, owner_idx, owner = cp_select_slot(fill, axis_name)
    # pmax (not fills[owner_idx]) so shard_map can statically infer the
    # flag is replicated over the ring
    overflow = jax.lax.pmax(
        (owner & (fill[0] >= Sc)).astype(jnp.int32), axis_name
    ).astype(bool)
    owner = owner & jnp.logical_not(overflow)
    slot = jnp.minimum(fill[0], Sc - 1)

    cache_k, cache_v = cp_kv_write(cache_k, cache_v, k_new, v_new, slot, owner)

    old_id = jax.lax.dynamic_slice(pos_ids, (slot,), (1,))
    new_id = jnp.where(owner, pos.astype(jnp.int32)[None], old_id)
    pos_ids = jax.lax.dynamic_update_slice(pos_ids, new_id, (slot,))
    fill = fill + owner.astype(jnp.int32)
    return cache_k, cache_v, pos_ids, fill, overflow[None]
