"""Parameter / KV-cache partitioning over the mesh.

The reference partitions by hand: each worker downloads the full model and
keeps `layers[LAYER_START:LAYER_END]` (plus, accidentally, the whole model
— /root/reference/Worker1.py:68-75). Here partitioning is a sharding
annotation: stacked layer params [L, ...] and the stacked KV cache
[L, B, S, KV, Dh] shard their leading layer axis over `pp` (a stage's
"layer range" is just its shard), embeddings/head replicate across `pp`,
and XLA moves exactly one stage's weights to each device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import ModelConfig
from ..models import api as M
from .mesh import AXIS_PP


def split_params(params: dict) -> tuple[dict, dict]:
    """(shared, layers): shared = embeddings/final-norm/head (replicated
    over pp), layers = stacked per-layer stacks (sharded over pp)."""
    shared = {k: v for k, v in params.items() if k != "layers"}
    return shared, params["layers"]


def layer_specs(layers: dict) -> dict:
    """PartitionSpec pytree for the stacked layer params: shard axis 0
    (the layer axis) over pp, replicate everything else."""
    return jax.tree.map(lambda x: P(AXIS_PP), layers)


def shared_specs(shared: dict) -> dict:
    return jax.tree.map(lambda x: P(), shared)


def cache_spec() -> P:
    """KV cache [L, B, S, KV, Dh]: layer axis over pp."""
    return P(AXIS_PP)


def shard_params(cfg: ModelConfig, params: dict, mesh: Mesh) -> tuple[dict, dict]:
    """Place (shared, layers) on the mesh. Requires n_layers % pp == 0
    (config.stage_layer_range enforces the same invariant)."""
    pp = mesh.shape[AXIS_PP]
    if cfg.n_layers % pp != 0:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={pp}")
    shared, layers = split_params(params)
    shared = jax.device_put(
        shared, jax.tree.map(lambda s: NamedSharding(mesh, s), shared_specs(shared))
    )
    layers = jax.device_put(
        layers, jax.tree.map(lambda s: NamedSharding(mesh, s), layer_specs(layers))
    )
    return shared, layers


def init_sharded_cache(cfg: ModelConfig, mesh: Mesh, batch: int, max_seq: int):
    """Zeroed KV cache sharded over pp along the stacked layer axis,
    allocated shard-local (no full-size host materialization)."""
    sharding = NamedSharding(mesh, cache_spec())

    @jax.jit
    def make():
        cache = M.init_kv_cache(cfg, batch, max_seq=max_seq)
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sharding), cache
        )

    return make()
