"""Parameter / KV-cache partitioning over the (dp, pp, tp) mesh.

The reference partitions by hand: each worker downloads the full model and
keeps `layers[LAYER_START:LAYER_END]` (plus, accidentally, the whole model
— /root/reference/Worker1.py:68-75). Here partitioning is a sharding
annotation: stacked layer params [L, ...] shard their leading layer axis
over `pp` (a stage's "layer range" is just its shard), and within a stage
the Megatron-style tensor split shards attention heads and FFN columns over
`tp` (column-sharded wq/wk/wv/w_gate/w_up, row-sharded wo/w_down — the psum
pairing lives in models/*.decoder_layer). Embedding rows and LM-head
columns shard their vocab dim over pp (parallel/vocab.py); norms and
position rows replicate. The KV cache [L, B, KV, S, Dh] shards layers over
pp, batch over dp, and kv heads over tp. XLA moves exactly one shard's
weights to each device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import ModelConfig
from ..models import api as M
from .mesh import AXIS_DP, AXIS_EP, AXIS_PP, AXIS_TP

# Per-leaf PartitionSpecs for the stacked layer params (leading axis = layer
# axis, always sharded over pp). Column-sharded leaves put tp on the output
# dim; row-sharded leaves put tp on the input (contraction) dim and rely on
# the model's psum. Norm weights and row-projection biases replicate over tp.
_LLAMA_LAYER_SPECS = {
    "attn_norm": P(AXIS_PP),
    "mlp_norm": P(AXIS_PP),
    # Gemma-2 sandwich norms + per-layer sliding-window flag: stacked on
    # the layer axis like everything else
    "attn_post_norm": P(AXIS_PP),
    "mlp_post_norm": P(AXIS_PP),
    "window_flag": P(AXIS_PP),
    "wq": P(AXIS_PP, None, AXIS_TP),
    "wk": P(AXIS_PP, None, AXIS_TP),
    "wv": P(AXIS_PP, None, AXIS_TP),
    # Qwen2-style qkv biases: per-output-column, shard alongside them
    "bq": P(AXIS_PP, AXIS_TP),
    "bk": P(AXIS_PP, AXIS_TP),
    "bv": P(AXIS_PP, AXIS_TP),
    # Qwen3 per-head q/k norms [L, Dh]: head_dim is tp-invariant (heads
    # shard, head_dim doesn't) -> replicate over tp
    "q_norm": P(AXIS_PP, None),
    "k_norm": P(AXIS_PP, None),
    "wo": P(AXIS_PP, AXIS_TP, None),
    "w_gate": P(AXIS_PP, None, AXIS_TP),
    "w_up": P(AXIS_PP, None, AXIS_TP),
    "w_down": P(AXIS_PP, AXIS_TP, None),
    # Paged LoRA adapter leaves [L, P(ages), in, r] / [L, P, r, out]
    # (engine/adapters.py): the delta (h @ a) @ b is added BEFORE each
    # base projection's psum, so the factors shard to make the partial
    # products sum by linearity. Column-sharded bases (wq/wk/wv/
    # w_gate/w_up): a replicates (h is replicated, the rank dim is
    # tiny), b shards its OUT dim with the base columns. Row-sharded
    # bases (wo/w_down): a shards its IN dim with the base rows (h
    # arrives input-sharded), b replicates — each tp shard contributes
    # (h_s @ a_s) @ b and the existing psum completes the contraction.
    "lora_wq_a": P(AXIS_PP, None, None, None),
    "lora_wq_b": P(AXIS_PP, None, None, AXIS_TP),
    "lora_wk_a": P(AXIS_PP, None, None, None),
    "lora_wk_b": P(AXIS_PP, None, None, AXIS_TP),
    "lora_wv_a": P(AXIS_PP, None, None, None),
    "lora_wv_b": P(AXIS_PP, None, None, AXIS_TP),
    "lora_wo_a": P(AXIS_PP, None, AXIS_TP, None),
    "lora_wo_b": P(AXIS_PP, None, None, None),
    "lora_w_gate_a": P(AXIS_PP, None, None, None),
    "lora_w_gate_b": P(AXIS_PP, None, None, AXIS_TP),
    "lora_w_up_a": P(AXIS_PP, None, None, None),
    "lora_w_up_b": P(AXIS_PP, None, None, AXIS_TP),
    "lora_w_down_a": P(AXIS_PP, None, AXIS_TP, None),
    "lora_w_down_b": P(AXIS_PP, None, None, None),
}

_GPT2_LAYER_SPECS = {
    "ln1_w": P(AXIS_PP),
    "ln1_b": P(AXIS_PP),
    "ln2_w": P(AXIS_PP),
    "ln2_b": P(AXIS_PP),
    "wq": P(AXIS_PP, None, AXIS_TP),
    "wk": P(AXIS_PP, None, AXIS_TP),
    "wv": P(AXIS_PP, None, AXIS_TP),
    "bq": P(AXIS_PP, AXIS_TP),
    "bk": P(AXIS_PP, AXIS_TP),
    "bv": P(AXIS_PP, AXIS_TP),
    "wo": P(AXIS_PP, AXIS_TP, None),
    "bo": P(AXIS_PP),
    "w_fc": P(AXIS_PP, None, AXIS_TP),
    "b_fc": P(AXIS_PP, AXIS_TP),
    "w_proj": P(AXIS_PP, AXIS_TP, None),
    "b_proj": P(AXIS_PP),
}

_FAMILY_LAYER_SPECS = {"llama": _LLAMA_LAYER_SPECS, "gpt2": _GPT2_LAYER_SPECS}

# MoE (Mixtral-style) expert leaves: the expert bank shards its E axis
# over ep; the tiny router replicates.
_MOE_LAYER_SPECS = {
    "w_router": P(AXIS_PP, None, None),
    "w_gate": P(AXIS_PP, AXIS_EP, None, None),
    "w_up": P(AXIS_PP, AXIS_EP, None, None),
    "w_down": P(AXIS_PP, AXIS_EP, None, None),
}


def validate_mesh(cfg: ModelConfig, pp: int, tp: int, ep: int = 1) -> None:
    """Divisibility invariants for a (pp, tp, ep) factorization.

    pp need not divide n_layers: uneven splits are padded with zero no-op
    layers (pad_stacked_layers), so any pp <= n_layers is valid."""
    if not 1 <= pp <= cfg.n_layers:
        raise ValueError(f"pp={pp} must be in [1, n_layers={cfg.n_layers}]")
    if cfg.n_heads % tp != 0:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={tp}")
    if tp > 1 and cfg.use_qk_norm and cfg.qk_norm_dim == "proj":
        raise NotImplementedError(
            "qk_norm_dim='proj' (OLMo-2) does not compose with tp>1: the "
            "norm's mean-of-squares spans the whole projection, which a "
            "column shard cannot compute locally"
        )
    if cfg.n_kv_heads % tp != 0:
        raise ValueError(f"n_kv_heads={cfg.n_kv_heads} not divisible by tp={tp}")
    if cfg.ffn_dim % tp != 0:
        raise ValueError(f"ffn_dim={cfg.ffn_dim} not divisible by tp={tp}")
    if ep > 1 and not cfg.n_experts:
        raise ValueError("ep>1 needs an MoE model (cfg.n_experts > 0)")
    if cfg.n_experts:
        if cfg.n_experts % ep != 0:
            raise ValueError(
                f"n_experts={cfg.n_experts} not divisible by ep={ep}"
            )
        if tp > 1:
            raise NotImplementedError(
                "MoE + tensor parallelism is not wired yet: shard experts "
                "over ep instead of splitting each expert over tp"
            )


def split_params(params: dict) -> tuple[dict, dict]:
    """(shared, layers): shared = embeddings/final-norm/head (replicated),
    layers = stacked per-layer stacks (sharded over pp × tp)."""
    shared = {k: v for k, v in params.items() if k != "layers"}
    return shared, params["layers"]


def padded_layers_per_stage(n_layers: int, pp: int) -> int:
    """Stacked-layer slots each stage holds after no-op padding."""
    return -(-n_layers // pp)


def pad_stacked_layers(cfg: ModelConfig, layers: dict, pp: int) -> dict:
    """Pad the stacked [L, ...] layer leaves to ceil(L/pp)*pp slots so the
    layer axis shards evenly over pp when pp does not divide n_layers
    (TinyLlama's 22 layers at pp=4 -> 6,6,5+pad,5+pad; the reference's own
    model split generalized, /root/reference/Worker1.py:27-28).

    Padding layers are ALL-ZERO, which makes them exact no-ops in a
    pre-norm residual block: zero norm weight zeroes q/k/v (and the MLP
    input), so both residual branches contribute exactly 0 and x passes
    through bit-identically. Their KV-cache slots only ever hold zeros, so
    no real slot is ever polluted.
    """
    L = cfg.n_layers
    per = padded_layers_per_stage(L, pp)
    if per * pp == L:
        return layers
    from ..config import stage_layer_range

    src = np.zeros(per * pp, np.int32)
    valid = np.zeros(per * pp, bool)
    for s in range(pp):
        lo, hi = stage_layer_range(L, pp, s)
        for j in range(hi - lo):
            src[s * per + j] = lo + j
            valid[s * per + j] = True
    src_j = jnp.asarray(src)

    def pad_leaf(x):
        y = jnp.take(x, src_j, axis=0)
        mask = jnp.asarray(valid.reshape((per * pp,) + (1,) * (x.ndim - 1)))
        return jnp.where(mask, y, jnp.zeros((), x.dtype))

    return jax.tree.map(pad_leaf, layers)


def layer_specs(cfg: ModelConfig, layers: dict) -> dict:
    """PartitionSpec pytree for the stacked layer params.

    Quantized leaves (ops/quant.QTensor) get a QTensor-of-specs: the int8
    weight q [L, in, out] keeps the weight's spec, and its per-output-
    channel scale s [L, out] drops the contraction axis — so scales shard
    with their columns under tp and replicate for row-sharded weights.
    int4 leaves (Q4Tensor) split the contraction axis into (groups, g/2):
    an in-axis shard moves to the GROUP axis (q [L, G, g/2, out],
    s [L, G, out]), so row-sharded int4 weights shard whole groups and
    each device keeps its groups' scales."""
    from ..ops.quant import Q4Tensor, QTensor

    specs = dict(_FAMILY_LAYER_SPECS[cfg.arch])
    if cfg.n_experts:
        specs.update(_MOE_LAYER_SPECS)
    missing = set(layers) - set(specs)
    if missing:
        raise KeyError(f"no partition spec for layer params: {sorted(missing)}")
    out = {}
    for k, v in layers.items():
        base = specs[k]
        if isinstance(v, QTensor):
            if len(base) == 4:  # MoE expert bank [L, E, in, out]
                out[k] = QTensor(base, P(base[0], base[1], base[3]))
            else:
                out[k] = QTensor(base, P(base[0], base[2]))
        elif isinstance(v, Q4Tensor):
            out[k] = Q4Tensor(
                P(base[0], base[1], None, base[2]),
                P(base[0], base[1], base[2]),
                v.g,
            )
        else:
            out[k] = base
    return out


def shared_specs(shared: dict) -> dict:
    """Embed rows / head columns shard their VOCAB dim over pp
    (parallel/vocab.py — round-1 review: full replicas cost ~2.1 GB/device
    for a Llama-3-8B-class model); norms / position rows replicate."""
    from .vocab import VOCAB_SHARDED

    from ..ops.quant import Q4Tensor, QTensor

    specs = {}
    for k, v in shared.items():
        if k in VOCAB_SHARDED:
            axes = [None, None]
            axes[VOCAB_SHARDED[k]] = AXIS_PP
            spec = P(*axes)
            if isinstance(v, QTensor):
                # lm_head [D, V]: scale s [V] shards with the vocab columns
                spec = QTensor(spec, P(AXIS_PP))
            elif isinstance(v, Q4Tensor):
                # lm_head q [G, g/2, V], s [G, V]
                spec = Q4Tensor(
                    P(axes[0], None, axes[1]), P(axes[0], axes[1]), v.g
                )
            specs[k] = spec
        else:
            specs[k] = P()
    return specs


def cache_spec(cfg=None):
    """KV cache [L, B, KV, S, Dh]: layers over pp, batch over dp, kv heads
    over tp. With cfg.kv_quant the cache leaves are KVQuant pytrees
    (ops/kv_quant.py) whose int8 data keeps the 5-axis spec and whose
    per-(token, head) scales [L, B, KV, S] drop the head_dim axis — the
    returned SPEC tree mirrors that structure (a KVQuant holding specs:
    same treedef trick as the quantized weight specs above), so every
    shard_map in/out spec and sharding constraint distributes per leaf.
    cfg=None keeps the raw single-spec form (legacy callers; the
    pipeline and 1F1B backends pass cfg and serve KVQuant caches — the
    context backend has its own quant-aware cp_cache_spec).
    """
    p5 = P(AXIS_PP, AXIS_DP, AXIS_TP, None, None)
    if cfg is None or getattr(cfg, "kv_quant", None) is None:
        return p5
    from ..ops.kv_quant import KVQuant

    leaf = KVQuant(p5, P(AXIS_PP, AXIS_DP, AXIS_TP, None))
    return {"k": leaf, "v": leaf}


def pool_spec(cfg):
    """Paged-KV block pool [L, N, KV, bs, Dh]: layers over pp, kv heads
    over tp — the block axis N replicates (every stage holds every block's
    slice of ITS layers; the table is plain replicated data). KVQuant
    pools mirror the spec per leaf like cache_spec does (scales
    [L, N, KV, bs] drop the head_dim axis)."""
    p5 = P(AXIS_PP, None, AXIS_TP, None, None)
    if getattr(cfg, "kv_quant", None) is None:
        return {"k": p5, "v": p5}
    from ..ops.kv_quant import KVQuant

    leaf = KVQuant(p5, P(AXIS_PP, None, AXIS_TP, None))
    return {"k": leaf, "v": leaf}


def shadow_block_spec(cfg):
    """Stacked shadow-block buffers [N, L, KV, bs(, Dh)] (engine/paged.
    gather_shadow_blocks / restore_shadow_blocks): block rows replicate,
    the LAYER axis — position 1 after the gather's swapaxes — shards
    over pp and kv heads over tp, mirroring pool_spec one axis over.
    KVQuant scales [N, L, KV, bs] drop the head_dim axis like always."""
    p5 = P(None, AXIS_PP, AXIS_TP, None, None)
    if getattr(cfg, "kv_quant", None) is None:
        return {"k": p5, "v": p5}
    from ..ops.kv_quant import KVQuant

    leaf = KVQuant(p5, P(None, AXIS_PP, AXIS_TP, None))
    return {"k": leaf, "v": leaf}


def init_sharded_pool(cfg: ModelConfig, mesh: Mesh, n_blocks: int,
                      block_size: int):
    """Zeroed paged-KV pool sharded per pool_spec(), allocated shard-local.
    The layer axis matches the PADDED stacked layers (ceil(L/pp)*pp) for
    uneven pp splits, exactly like init_sharded_cache."""
    from ..engine import paged as EP

    pp = int(mesh.shape[AXIS_PP])
    n_layers = padded_layers_per_stage(cfg.n_layers, pp) * pp
    spec_tree = pool_spec(cfg)

    @jax.jit
    def make():
        pool = EP.init_pool(cfg, n_blocks, block_size, n_layers=n_layers)
        return jax.tree.map(
            lambda x, sp: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, sp)
            ),
            pool,
            spec_tree,
        )

    return make()


def params_already_placed(params: dict, mesh: Mesh) -> bool:
    """True when every leaf is a jax.Array already carrying a NamedSharding
    on (an equal copy of) `mesh` — i.e. the checkpoint was restored with
    models/checkpoint.load_params_sharded, which pads + places shard-by-
    shard off mmap. shard_params then skips its pad/device_put pass, whose
    jnp.take/jnp.pad would re-materialize full-size arrays."""
    leaves = jax.tree.leaves(params)
    return bool(leaves) and all(
        isinstance(leaf, jax.Array)
        and isinstance(leaf.sharding, NamedSharding)
        and leaf.sharding.mesh == mesh
        for leaf in leaves
    )


def shard_params(cfg: ModelConfig, params: dict, mesh: Mesh) -> tuple[dict, dict]:
    """Place (shared, layers) on the mesh (uneven pp splits are padded;
    embed/head vocab dims are padded + sharded over pp)."""
    from .vocab import pad_vocab

    pp = int(mesh.shape[AXIS_PP])
    validate_mesh(
        cfg, pp, int(mesh.shape[AXIS_TP]), int(mesh.shape.get(AXIS_EP, 1))
    )
    if params_already_placed(params, mesh):
        return split_params(params)
    shared, layers = split_params(params)
    layers = pad_stacked_layers(cfg, layers, pp)
    shared = pad_vocab(cfg, shared, pp)
    shared = jax.device_put(
        shared,
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), shared_specs(shared),
            is_leaf=lambda x: isinstance(x, P),
        ),
    )
    layers = jax.device_put(
        layers,
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), layer_specs(cfg, layers),
            is_leaf=lambda x: isinstance(x, P),
        ),
    )
    return shared, layers


def init_sharded_cache(cfg: ModelConfig, mesh: Mesh, batch: int, max_seq: int):
    """Zeroed KV cache sharded per cache_spec(), allocated shard-local (no
    full-size host materialization). The layer axis matches the PADDED
    stacked layers (ceil(L/pp)*pp slots) for uneven pp splits."""
    dp = int(mesh.shape[AXIS_DP])
    pp = int(mesh.shape[AXIS_PP])
    if batch % dp != 0:
        raise ValueError(f"batch={batch} not divisible by dp={dp}")
    n_layers = padded_layers_per_stage(cfg.n_layers, pp) * pp
    spec_tree = cache_spec(cfg)

    @jax.jit
    def make():
        cache = M.init_kv_cache(cfg, batch, max_seq=max_seq, n_layers=n_layers)
        specs = (
            spec_tree
            if not isinstance(spec_tree, P)  # per-leaf tree (kv_quant)
            else jax.tree.map(lambda _: spec_tree, cache)
        )
        return jax.tree.map(
            lambda x, sp: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, sp)
            ),
            cache,
            specs,
        )

    return make()
