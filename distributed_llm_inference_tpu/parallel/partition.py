"""Parameter / KV-cache partitioning over the (dp, pp, tp) mesh.

The reference partitions by hand: each worker downloads the full model and
keeps `layers[LAYER_START:LAYER_END]` (plus, accidentally, the whole model
— /root/reference/Worker1.py:68-75). Here partitioning is a sharding
annotation: stacked layer params [L, ...] shard their leading layer axis
over `pp` (a stage's "layer range" is just its shard), and within a stage
the Megatron-style tensor split shards attention heads and FFN columns over
`tp` (column-sharded wq/wk/wv/w_gate/w_up, row-sharded wo/w_down — the psum
pairing lives in models/*.decoder_layer). Embeddings/head replicate; the
KV cache [L, B, KV, S, Dh] shards layers over pp, batch over dp, and kv
heads over tp. XLA moves exactly one shard's weights to each device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import ModelConfig
from ..models import api as M
from .mesh import AXIS_DP, AXIS_PP, AXIS_TP

# Per-leaf PartitionSpecs for the stacked layer params (leading axis = layer
# axis, always sharded over pp). Column-sharded leaves put tp on the output
# dim; row-sharded leaves put tp on the input (contraction) dim and rely on
# the model's psum. Norm weights and row-projection biases replicate over tp.
_LLAMA_LAYER_SPECS = {
    "attn_norm": P(AXIS_PP),
    "mlp_norm": P(AXIS_PP),
    "wq": P(AXIS_PP, None, AXIS_TP),
    "wk": P(AXIS_PP, None, AXIS_TP),
    "wv": P(AXIS_PP, None, AXIS_TP),
    # Qwen2-style qkv biases: per-output-column, shard alongside them
    "bq": P(AXIS_PP, AXIS_TP),
    "bk": P(AXIS_PP, AXIS_TP),
    "bv": P(AXIS_PP, AXIS_TP),
    "wo": P(AXIS_PP, AXIS_TP, None),
    "w_gate": P(AXIS_PP, None, AXIS_TP),
    "w_up": P(AXIS_PP, None, AXIS_TP),
    "w_down": P(AXIS_PP, AXIS_TP, None),
}

_GPT2_LAYER_SPECS = {
    "ln1_w": P(AXIS_PP),
    "ln1_b": P(AXIS_PP),
    "ln2_w": P(AXIS_PP),
    "ln2_b": P(AXIS_PP),
    "wq": P(AXIS_PP, None, AXIS_TP),
    "wk": P(AXIS_PP, None, AXIS_TP),
    "wv": P(AXIS_PP, None, AXIS_TP),
    "bq": P(AXIS_PP, AXIS_TP),
    "bk": P(AXIS_PP, AXIS_TP),
    "bv": P(AXIS_PP, AXIS_TP),
    "wo": P(AXIS_PP, AXIS_TP, None),
    "bo": P(AXIS_PP),
    "w_fc": P(AXIS_PP, None, AXIS_TP),
    "b_fc": P(AXIS_PP, AXIS_TP),
    "w_proj": P(AXIS_PP, AXIS_TP, None),
    "b_proj": P(AXIS_PP),
}

_FAMILY_LAYER_SPECS = {"llama": _LLAMA_LAYER_SPECS, "gpt2": _GPT2_LAYER_SPECS}


def validate_mesh(cfg: ModelConfig, pp: int, tp: int) -> None:
    """Divisibility invariants for a (pp, tp) factorization of the model."""
    if cfg.n_layers % pp != 0:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={pp}")
    if cfg.n_heads % tp != 0:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={tp}")
    if cfg.n_kv_heads % tp != 0:
        raise ValueError(f"n_kv_heads={cfg.n_kv_heads} not divisible by tp={tp}")
    if cfg.ffn_dim % tp != 0:
        raise ValueError(f"ffn_dim={cfg.ffn_dim} not divisible by tp={tp}")


def split_params(params: dict) -> tuple[dict, dict]:
    """(shared, layers): shared = embeddings/final-norm/head (replicated),
    layers = stacked per-layer stacks (sharded over pp × tp)."""
    shared = {k: v for k, v in params.items() if k != "layers"}
    return shared, params["layers"]


def layer_specs(cfg: ModelConfig, layers: dict) -> dict:
    """PartitionSpec pytree for the stacked layer params."""
    specs = _FAMILY_LAYER_SPECS[cfg.arch]
    missing = set(layers) - set(specs)
    if missing:
        raise KeyError(f"no partition spec for layer params: {sorted(missing)}")
    return {k: specs[k] for k in layers}


def shared_specs(shared: dict) -> dict:
    return jax.tree.map(lambda x: P(), shared)


def cache_spec() -> P:
    """KV cache [L, B, KV, S, Dh]: layers over pp, batch over dp, kv heads
    over tp."""
    return P(AXIS_PP, AXIS_DP, AXIS_TP, None, None)


def shard_params(cfg: ModelConfig, params: dict, mesh: Mesh) -> tuple[dict, dict]:
    """Place (shared, layers) on the mesh."""
    validate_mesh(cfg, int(mesh.shape[AXIS_PP]), int(mesh.shape[AXIS_TP]))
    shared, layers = split_params(params)
    shared = jax.device_put(
        shared, jax.tree.map(lambda s: NamedSharding(mesh, s), shared_specs(shared))
    )
    layers = jax.device_put(
        layers,
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), layer_specs(cfg, layers),
            is_leaf=lambda x: isinstance(x, P),
        ),
    )
    return shared, layers


def init_sharded_cache(cfg: ModelConfig, mesh: Mesh, batch: int, max_seq: int):
    """Zeroed KV cache sharded per cache_spec(), allocated shard-local (no
    full-size host materialization)."""
    dp = int(mesh.shape[AXIS_DP])
    if batch % dp != 0:
        raise ValueError(f"batch={batch} not divisible by dp={dp}")
    sharding = NamedSharding(mesh, cache_spec())

    @jax.jit
    def make():
        cache = M.init_kv_cache(cfg, batch, max_seq=max_seq)
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sharding), cache
        )

    return make()
