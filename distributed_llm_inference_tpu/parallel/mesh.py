"""Device-mesh construction.

Replaces the reference's topology wiring — hand-pasted ngrok worker URLs
(/root/reference/orchestration.py:22-24, Worker1.py:264) — with a
`jax.sharding.Mesh` over the (dp, pp, tp) axes. Intra-pod stage hand-off
rides ICI collectives inside one compiled program; multi-host pods extend
the same mesh over DCN via `jax.distributed.initialize` (no code change:
`jax.devices()` then spans all hosts).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

from ..config import MeshConfig

AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP, AXIS_EP = "dp", "pp", "sp", "tp", "ep"


def build_mesh(mesh_cfg: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    """(dp, pp, sp, tp, ep) mesh over the given (default: all) devices.

    Device order: pp and sp are middle axes so consecutive devices form
    pipeline / ring-attention rings over ICI neighbours; tp and ep are
    innermost so their per-layer psums ride the highest-bandwidth
    neighbour links. All axes execute (parallel/pipeline.PipelineBackend
    for dp×pp×tp×ep, parallel/context.ContextParallelBackend for dp×sp);
    dp>1 needs batch % dp == 0, ep>1 needs an MoE model with
    n_experts % ep == 0.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    need = mesh_cfg.n_devices
    if len(devs) < need:
        raise ValueError(
            f"need {need} devices (dp*pp*sp*tp*ep), have {len(devs)}"
        )
    grid = np.array(devs[:need]).reshape(
        mesh_cfg.dp, mesh_cfg.pp, mesh_cfg.sp, mesh_cfg.tp, mesh_cfg.ep
    )
    return Mesh(grid, (AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP, AXIS_EP))


def multihost_initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> None:
    """Multi-host bring-up over DCN (the reference's 'paste three ngrok
    URLs' bootstrap, /root/reference/orchestration.py:22-24, replaced by
    jax.distributed coordination).

    All three of (coordinator_address, num_processes, process_id) must be
    given together, or all omitted (TPU-pod metadata auto-detection).
    After it returns, `jax.devices()` spans every host and build_mesh
    lays the same (dp, pp, sp, tp) axes over the whole pod — stage
    hand-off inside a host rides ICI, across hosts DCN, with no code
    change anywhere above this layer.
    """
    explicit = (coordinator_address, num_processes, process_id)
    given = [x is not None for x in explicit]
    if any(given) and not all(given):
        raise ValueError(
            "multihost bring-up needs coordinator_address, num_processes "
            "AND process_id together (or none, for TPU-pod auto-detection); "
            f"got {dict(zip(('coordinator_address', 'num_processes', 'process_id'), explicit))}"
        )
    if all(given):
        if not 0 <= process_id < num_processes:
            raise ValueError(
                f"process_id {process_id} out of range for "
                f"num_processes {num_processes}"
            )
        kwargs.update(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    jax.distributed.initialize(**kwargs)
