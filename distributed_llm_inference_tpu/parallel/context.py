"""Context-parallel SPMD backend: the SEQUENCE is the sharded axis.

Long-context serving the reference cannot express — it ships the WHOLE
sequence through every stage over the WAN four times per token
(/root/reference/orchestration.py:114-137) and caps output at 30 tokens to
survive its O(n²) recompute (orchestration.py:347). Here an `sp` ring of
devices splits the context:

  * prefill — tokens shard over `sp`; every layer runs `ring_attend`
    (parallel/ring.py): K/V chunks rotate over ICI while queries stay put,
    so each device holds seq/sp of the activations and KV cache and max
    context scales linearly with the ring size;
  * decode — activations are replicated (one token), but the KV cache
    stays sharded: each device attends its local position-tagged slot set
    and the partials merge with one pmax/psum log-sum-exp combine per
    layer (`cp_decode_attend`); decoded tokens round-robin across shards;
  * both phases inject their attention strategy through
    `models/llama.decoder_layer`'s attn_hook seam — same block, same
    weights, different cache topology.

Engine-compatible (same init_cache/prefill/decode/health interface as
SingleDeviceBackend / PipelineBackend); the cache pytree additionally
carries `pos_ids` (absolute position per local slot, -1 = empty) and
`fill` (per-device slot count). Composes with dp (batch shards), tp
(head shards), and — since round 5 — pp: layers shard over the pipeline
axis and prefill/decode run the pp backend's gated microstep ring with
the sequence still sharded over sp (each stage's layer scan runs the
ring/merge collectives on its local chunk; activations ppermute between
stages; embed/lm_head take the vocab-sharded pp forms).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import ModelConfig
from ..engine.generate import stop_mask
from ..models import api as M
from ..ops.kv_quant import KVQuant
from ..ops.kv_quant import dequantize as kv_dequantize
from ..ops.kv_quant import quantize_chunk
from ..ops.sampling import sample_token
from .mesh import AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP
from .pipeline import SPMDBackendBase
from .vocab import embed_sharded, unembed_sharded
from .ring import (
    cp_decode_attend,
    cp_kv_write,
    cp_scale_write,
    cp_select_slot,
    ring_attend,
    ulysses_attend,
)

# pos_ids/fill carry a leading dp axis: each dp ring decodes independently
# (its while_loop may exit at a different step), so its slot bookkeeping
# diverges and must be dp-sharded, not replicated.
_AUX_SPEC = P(AXIS_DP, AXIS_SP)


def _gated(gate, new, old):
    """Discard a cache write when this pp microstep isn't the stage's own
    (the pipeline ring's update_gate contract — None means ungated, i.e.
    pp == 1). KVQuant leaves gate data + scales together."""
    if gate is None:
        return new
    if isinstance(new, KVQuant):
        return KVQuant(
            jnp.where(gate, new.q, old.q), jnp.where(gate, new.s, old.s)
        )
    return jnp.where(gate, new, old)


def cp_cache_spec(cfg=None):
    """KV cache [L, B, KV, S, Dh]: batch over dp, kv heads over tp, and —
    unlike the dense cache_spec() — the SLOT axis over sp. With
    cfg.kv_quant the leaf is a KVQuant-of-specs (int8 data keeps the
    5-axis spec, the per-(slot, head) scales [L, B, KV, S] drop head_dim)
    — the same per-leaf distribution trick as partition.cache_spec."""
    p5 = P(AXIS_PP, AXIS_DP, AXIS_TP, AXIS_SP, None)
    if cfg is None or getattr(cfg, "kv_quant", None) is None:
        return p5
    return KVQuant(p5, P(AXIS_PP, AXIS_DP, AXIS_TP, AXIS_SP))


class ContextParallelBackend(SPMDBackendBase):
    """dp × sp × tp backend with a sequence-sharded KV cache."""

    name = "context-parallel"

    def __init__(self, cfg: ModelConfig, params: dict, mesh: Mesh,
                 sp_strategy: str = "ring", wire_quant=None):
        if sp_strategy not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_strategy must be 'ring' or 'ulysses', got {sp_strategy!r}"
            )
        self.sp_strategy = sp_strategy
        # Both families since round 5: gpt2's block routes through the
        # shared attn_hook seam, its learned position rows are absolute
        # (chunk offsets and slot tags are absolute positions, exactly
        # what the ring/merge masks key on), and the vocab-sharded embed
        # handles pos_embed. An arch without the seam still rejects.
        if cfg.arch not in ("llama", "gpt2"):
            raise NotImplementedError(
                f"context parallelism needs the shared attn_hook seam "
                f"(llama/gpt2 families); got arch={cfg.arch!r}"
            )
        self.sp = int(mesh.shape[AXIS_SP])
        if self.sp < 2:
            raise ValueError("ContextParallelBackend needs sp >= 2")
        # tp already shards the head axis: the all_to_all splits the LOCAL
        # head count, so the divisibility check must be tp-aware or a
        # passing global check would crash later with an opaque trace error
        tp = int(mesh.shape.get(AXIS_TP, 1))
        if sp_strategy == "ulysses" and (
            (cfg.n_heads // tp) % self.sp or (cfg.n_kv_heads // tp) % self.sp
        ):
            raise ValueError(
                f"ulysses scatters heads over sp={self.sp}: needs the LOCAL "
                f"head counts (n_heads {cfg.n_heads} / tp {tp} = "
                f"{cfg.n_heads // tp}, n_kv_heads {cfg.n_kv_heads} / tp {tp} "
                f"= {cfg.n_kv_heads // tp}) divisible by sp "
                f"(use sp_strategy='ring')"
            )
        pp = int(mesh.shape[AXIS_PP])
        if pp > 1 and cfg.n_layers % pp:
            # the sp cache builder stacks cfg.n_layers directly; the
            # padded-layer-slot trick the dense pipeline uses
            # (parallel/partition.pad_stacked_layers) is not threaded
            # through the sp cache spec yet — fail loudly, not misaligned
            raise NotImplementedError(
                f"sp x pp needs n_layers ({cfg.n_layers}) divisible by "
                f"pp ({pp}) for now (uneven stage splits pad layer slots, "
                f"which the context-sharded cache does not model yet)"
            )
        super().__init__(cfg, params, mesh, wire_quant=wire_quant)
        # the masked broadcast of the sampled window crosses the sp axis
        # (sp >= 2 always — a real transfer), so the wire knob applies
        # regardless of pp; the ring-hop flag stays pp-gated (base class)
        self._wire_bcast = wire_quant is not None
        # pp > 1 composes now (round-5): layers shard over pp exactly like
        # the PipelineBackend (SPMDBackendBase.shard_params is mesh-
        # driven), prefill/decode run the gated microstep ring over pp
        # with the sp collectives INSIDE each stage's layer scan, and
        # embed/lm_head switch to the vocab-sharded pp forms. /workers
        # reports pipeline stages when there are several, context shards
        # otherwise.
        self.n_stages = self.pp if self.pp > 1 else self.sp

    # -- cache ---------------------------------------------------------------
    def local_slots(self, max_seq: int) -> int:
        """Per-device slot count: even share of max_seq plus one slot of
        round-robin slack (decode appends differ by at most one across the
        ring)."""
        return -(-max_seq // self.sp) + 1

    def init_cache(self, batch: int, max_seq: int):
        cfg, sp, dp = self.cfg, self.sp, self.dp
        Sc = self.local_slots(max_seq)
        mesh = self.mesh
        spec_tree = {"k": cp_cache_spec(cfg), "v": cp_cache_spec(cfg)}
        aux_sharding = NamedSharding(mesh, _AUX_SPEC)

        @jax.jit
        def make():
            kv = M.init_kv_cache(cfg, batch, max_seq=sp * Sc)
            kv = jax.tree.map(
                lambda x, sp_: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, sp_)
                ),
                kv,
                spec_tree,
            )
            pos_ids = jax.lax.with_sharding_constraint(
                jnp.full((dp, sp * Sc), -1, jnp.int32), aux_sharding
            )
            fill = jax.lax.with_sharding_constraint(
                jnp.zeros((dp, sp), jnp.int32), aux_sharding
            )
            return {"k": kv["k"], "v": kv["v"], "pos_ids": pos_ids, "fill": fill}

        return make()

    # repetition-penalty presence, OpenAI penalty counts, logit_bias and
    # per-token logprobs all serve on the sp ring (round-4: the full solo
    # request surface on every topology) — the variants are local ops on
    # the replicated logits, exactly like the pp backend's
    supports_presence = True
    supports_counts = True
    supports_bias = True
    supports_logprobs = True
    # Ragged left-padded batches (round-4 review #5): valid_start rides the
    # ring/ulysses/merge masks as a per-row floor on ABSOLUTE key positions
    # (parallel/ring.py:_raggedize) — chunk offsets and slot tags are both
    # absolute, so the queue-coalesced batched serving path shards over sp
    # like any other batch. Llama-family only (gpt2's forward_layers
    # raises on valid_start — learned absolute positions are not
    # shift-invariant), gated HERE at the backend seam so a ragged gpt2
    # sp batch rejects loudly instead of relying on the engine/queue
    # arch gates upstream (round-5 advice #1; same pattern as the
    # supports_score property).
    @property
    def supports_ragged(self) -> bool:
        return self.cfg.arch == "llama"

    def prefill(self, tokens, prompt_len, cache, key, sampling,
                valid_start=None, presence=None, bias=None):
        if tokens.shape[1] % self.sp:
            raise ValueError(
                f"prefill bucket {tokens.shape[1]} not divisible by sp={self.sp}; "
                f"pick prefill_buckets that are multiples of the ring size"
            )
        ragged = valid_start is not None
        pres = presence is not None
        wb = bias is not None
        fn = self._programs.get(("prefill", ragged, pres, wb))
        if fn is None:
            fn = self._build_prefill_impl(
                with_ragged=ragged, with_presence=pres, with_bias=wb
            )
            self._programs[("prefill", ragged, pres, wb)] = fn
        args = [self.shared, self.layers, tokens, prompt_len, cache, key,
                sampling]
        if ragged:
            args.append(valid_start)
        if pres:
            args.append(presence)
        if wb:
            args.append(bias)
        self._account_sp_prefill_wire(tokens.shape)
        return fn(*args)

    def _account_sp_prefill_wire(self, tokens_shape):
        """Static sp-wire accounting for one ring/ulysses forward: every
        layer rotates its K and V chunk (sp - 1) hops (the a2a moves the
        same chunk volume once re-sharded); int8 caches ship int8 +
        scales with or without the wire knob, so `quant` reflects what
        actually crossed. pp microstep hops and the sampled-window
        broadcast ride their own families."""
        cfg = self.cfg
        B, bucket = int(tokens_shape[0]), int(tokens_shape[1])
        Tc = bucket // self.sp
        self._account_link(
            "sp-kv-ring", rows=B, t_chunk=Tc, axis_size=self.sp,
            quant=self.wire_quant is not None or cfg.kv_quant is not None,
        )
        self._account_link("pp-microstep-prefill", rows=B, t=Tc)
        self._account_link(
            "sp-broadcast-prefill", rows=B, axis_size=self.sp
        )

    # -- shared hook ---------------------------------------------------------
    def _layer_window(self, window_flag):
        """Per-layer effective window for the collective attention masks.

        Uniform configs keep the static cfg.attn_window (None = full).
        Mixed patterns (Gemma-2/3 — the stacked window_flag leaf exists
        only for them) resolve to a TRACED per-layer width: windowed
        layers take cfg.attn_window, full layers take an unreachably
        large width, which the pure-arithmetic masks in parallel/ring.py
        treat as no window at all."""
        cfg = self.cfg
        if window_flag is None or cfg.attn_window is None:
            return cfg.attn_window
        return jnp.where(
            window_flag > 0, jnp.int32(cfg.attn_window), jnp.int32(1 << 30)
        )

    def _make_ring_hook(self):
        """The prefill-phase attn_hook: sequence-parallel attention over
        the chunk (ring or ulysses) + local cache write at slot 0 —
        quantizing on write for int8 caches, with the quantized chunks +
        scales riding the collective. Shared by the prefill and scoring
        programs. valid_start (ragged left-padded batches) flows straight
        into the collective attention's mask."""
        cfg = self.cfg
        prefill_attend = (
            ulysses_attend if self.sp_strategy == "ulysses" else ring_attend
        )

        def ring_hook(cfg_, q, k, v, ck, cv, pos, mask, gate, valid_start=None,
                      window_flag=None):
            zero = jnp.int32(0)
            win = self._layer_window(window_flag)
            if isinstance(ck, KVQuant):
                # int8 cache: store quantized chunks, and attend over the
                # quantized round-trip — ring_attend/ulysses_attend ship
                # the int8 chunks + scales over ICI (~4x fewer bytes than
                # rotating dequantized fp32) and dequantize at use, the
                # exact values the dense kv_quant path attends (its hook
                # reads the written cache), so cross-topology numerics
                # stay consistent
                qk, sk = quantize_chunk(k)
                qv, sv = quantize_chunk(v)
                attn = prefill_attend(
                    q, qk, qv, AXIS_SP, k_scale=sk, v_scale=sv,
                    scale=cfg.query_scale, softcap=cfg.attn_softcap,
                    window=win, valid_start=valid_start,
                )
                ck_new = KVQuant(
                    jax.lax.dynamic_update_slice(
                        ck.q, qk.transpose(0, 2, 1, 3), (zero,) * 4
                    ),
                    jax.lax.dynamic_update_slice(
                        ck.s, sk.transpose(0, 2, 1), (zero,) * 3
                    ),
                )
                cv_new = KVQuant(
                    jax.lax.dynamic_update_slice(
                        cv.q, qv.transpose(0, 2, 1, 3), (zero,) * 4
                    ),
                    jax.lax.dynamic_update_slice(
                        cv.s, sv.transpose(0, 2, 1), (zero,) * 3
                    ),
                )
                return attn, _gated(gate, ck_new, ck), _gated(gate, cv_new, cv)
            # raw-dtype cache: with pp_wire_quant on, the chunk hops
            # adopt the int8 recipe (quantize once at entry, rotate int8
            # + scales, dequantize at use — parallel/ring.py `wire`)
            attn = prefill_attend(
                q, k, v, AXIS_SP, scale=cfg.query_scale,
                softcap=cfg.attn_softcap, window=win,
                valid_start=valid_start,
                wire=self.wire_quant is not None,
            )
            kc = k.astype(ck.dtype).transpose(0, 2, 1, 3)  # [B,KV,Tc,Dh]
            vc = v.astype(cv.dtype).transpose(0, 2, 1, 3)
            ck_new = jax.lax.dynamic_update_slice(ck, kc, (zero,) * 4)
            cv_new = jax.lax.dynamic_update_slice(cv, vc, (zero,) * 4)
            return attn, _gated(gate, ck_new, ck), _gated(gate, cv_new, cv)

        return ring_hook

    # -- teacher-forced scoring (OpenAI echo) --------------------------------
    @property
    def supports_score(self) -> bool:
        """Echo-scoring runs on sp-only meshes; on sp x pp the score
        program is still whole-model per ring member, so the engine's
        capability gate rejects it cleanly as invalid_request instead of
        the call-time NotImplementedError surfacing as a 500."""
        return self.pp == 1

    def score_chunk(self, tokens, pos, cache, *, top_n=0):
        """Single-chunk echo scoring on the ring: the chunk shards over
        sp, each member computes its local teacher-forced logits, and one
        tiled all_gather assembles [B, T, V] replicated so score_post
        (the shared tail) runs identically everywhere. pos must be 0 —
        the ring hook writes at chunk offsets, not a running offset, so
        prompts longer than the largest bucket reject loudly."""
        if self.pp > 1:
            raise NotImplementedError(
                f"{self.name} echo-scoring does not run on sp x pp meshes "
                f"yet (the score program is whole-model per ring member); "
                f"score on an sp-only or pp server"
            )
        if int(pos) != 0:
            raise ValueError(
                f"{self.name} scores single-bucket prompts only (chunked "
                f"scoring needs a running cache offset the ring prefill "
                f"does not expose); raise prefill_buckets or score on a "
                f"pp/single-chip server"
            )
        if tokens.shape[1] % self.sp:
            raise ValueError(
                f"score bucket {tokens.shape[1]} not divisible by "
                f"sp={self.sp}"
            )
        fn = self._programs.get(("score", top_n))
        if fn is None:
            fn = self._build_score(top_n)
            self._programs[("score", top_n)] = fn
        self._account_sp_prefill_wire(tokens.shape)
        return fn(self.shared, self.layers, tokens, cache)

    def _build_score(self, top_n: int):
        cfg = self.cfg
        from ..engine.generate import score_post

        ring_hook = self._make_ring_hook()

        def body(shared, layers, tokens, cache):
            my = jax.lax.axis_index(AXIS_SP)
            Tc = tokens.shape[1]
            chunk_start = my * Tc
            x = M.embed(cfg, shared, tokens, chunk_start)
            x, kv = M.forward_layers(
                cfg, layers, x, {"k": cache["k"], "v": cache["v"]},
                jnp.asarray(chunk_start, jnp.int32),
                tp_axis=self.tp_axis, attn_hook=ring_hook,
            )
            logits_local = M.unembed(cfg, shared, x)  # [B, Tc, V]
            # jaxlint: disable=comms-wire-coverage -- fp32 [B, Tc, V] scoring logits gather, tracked in FAT_INVENTORY (analysis/comms.py): score-call duty cycle, same quantization story as the vocab gather
            logits = jax.lax.all_gather(
                logits_local, AXIS_SP, axis=1, tiled=True
            )
            # jaxlint: disable=comms-wire-coverage,comms-fat-collective -- int32 token ids re-gathered for score_post alignment, 4*T bytes: control payload, not an activation
            toks_full = jax.lax.all_gather(tokens, AXIS_SP, axis=1, tiled=True)
            cache2 = {
                "k": kv["k"], "v": kv["v"],
                "pos_ids": cache["pos_ids"], "fill": cache["fill"],
            }
            return score_post(logits, toks_full, top_n) + (cache2,)

        cache_specs = {
            "k": cp_cache_spec(cfg), "v": cp_cache_spec(cfg),
            "pos_ids": _AUX_SPEC, "fill": _AUX_SPEC,
        }
        shmapped = self._shard(
            body,
            in_specs=(
                self._shared_specs, self._layer_specs, P(AXIS_DP, AXIS_SP),
                cache_specs,
            ),
            out_specs=(
                P(AXIS_DP), P(AXIS_DP), P(AXIS_DP), P(AXIS_DP), cache_specs
            ),
        )
        return jax.jit(shmapped, donate_argnums=(3,))

    # -- prefill -------------------------------------------------------------
    def _build_prefill(self):
        # base-class hook: build the plain program ONCE and seed the memo
        # prefill() consults, so the base-held self._prefill and the
        # memo entry are the same compiled object (the pp backend's
        # pattern)
        fn = self._build_prefill_impl(
            with_ragged=False, with_presence=False, with_bias=False
        )
        self._programs[("prefill", False, False, False)] = fn
        return fn

    def _build_prefill_impl(self, *, with_ragged: bool = False,
                            with_presence: bool, with_bias: bool):
        cfg = self.cfg
        ring_hook = self._make_ring_hook()

        def body(shared, layers, tokens, prompt_len, cache, key, sampling,
                 *extra):
            i = 0
            valid_start = presence = bias = None
            if with_ragged:
                valid_start = extra[i]
                i += 1
            if with_presence:
                presence = extra[i]
                i += 1
            if with_bias:
                bias = extra[i]
                i += 1
            key = self._dp_key(key)
            my = jax.lax.axis_index(AXIS_SP)
            Tc = tokens.shape[1]  # local chunk of the padded bucket
            Sc = cache["k"].shape[3]
            chunk_start = my * Tc
            pos0 = jnp.asarray(chunk_start, jnp.int32)
            PP = self.pp

            # embed/lm_head are vocab-sharded over pp (parallel/vocab.py;
            # no-ops at pp == 1, where the local shard is the full table).
            # The forward is the pipeline's gated microstep ring
            # (SPMDBackendBase._microstep_loop) with the SEQUENCE still
            # sharded over sp: each stage's layer scan runs the
            # ring/ulysses collectives on its local chunk, the chunk
            # activations ppermute between stages, and cache writes keep
            # only the stage's own microstep (the gate threads into the
            # ring hook's _gated writes). pp == 1 degenerates exactly.
            x = embed_sharded(cfg, shared, tokens, pos0, PP)
            kvc = {"k": cache["k"], "v": cache["v"]}
            x, kv = self._microstep_loop(
                layers, x, kvc, pos0, valid_start, attn_hook=ring_hook
            )

            # slot bookkeeping: slots [0,Tc) hold this chunk's positions,
            # pad positions (>= prompt_len) stay invalid. Ragged batches
            # keep their LEFT-pad slots tagged (prompt_len = bucket): the
            # tags are shared across rows, and per-row pad slots are
            # masked at attention time by valid_start (parallel/ring.py),
            # mirroring the dense ragged_causal_mask contract.
            lpos = chunk_start + jnp.arange(Tc, dtype=jnp.int32)
            pos_ids = jnp.full((1, Sc), -1, jnp.int32)
            pos_ids = pos_ids.at[0, :Tc].set(jnp.where(lpos < prompt_len, lpos, -1))
            fill = jnp.clip(prompt_len - chunk_start, 0, Tc)[None, None]

            # activations of the last prompt position live on ONE ring
            # member (and, under pp, on stage 0 — the microstep ring's
            # final shift lands the real output there); a masked psum over
            # the owning axes broadcasts the [B, 1, D] slice, then the
            # vocab-sharded unembed computes replicated logits
            li = prompt_len - 1 - chunk_start
            owner = (li >= 0) & (li < Tc)
            last = jax.lax.dynamic_slice_in_dim(x, jnp.clip(li, 0, Tc - 1), 1, axis=1)
            sel = owner & (jax.lax.axis_index(AXIS_PP) == 0)
            last = self._bcast(last, sel, (AXIS_SP, AXIS_PP))
            logits = unembed_sharded(cfg, shared, last, PP)[:, 0, :]
            first = sample_token(
                key, logits, *sampling, presence=presence, bias=bias
            )
            cache = {"k": kv["k"], "v": kv["v"], "pos_ids": pos_ids, "fill": fill}
            return first, logits, cache

        cache_specs = {
            "k": cp_cache_spec(cfg), "v": cp_cache_spec(cfg),
            "pos_ids": _AUX_SPEC, "fill": _AUX_SPEC,
        }
        specs = [
            self._shared_specs, self._layer_specs, P(AXIS_DP, AXIS_SP),
            P(), cache_specs, P(), P(),
        ]
        if with_ragged:
            specs.append(P(AXIS_DP))  # valid_start [B] shards with the batch
        if with_presence:
            specs.append(P(AXIS_DP))
        if with_bias:
            specs.append(P())  # [V] bias replicates: logits are replicated
        # shared specs name AXIS_PP on the vocab dims: the bodies use the
        # vocab-sharded embed/unembed forms (parallel/vocab.py), which
        # psum/all_gather over pp when pp > 1 and see the full table as
        # their "shard" when pp == 1 — exact either way
        shmapped = self._shard(
            body,
            in_specs=tuple(specs),
            out_specs=(P(AXIS_DP), P(AXIS_DP), cache_specs),
        )
        return jax.jit(shmapped, donate_argnums=(4,))

    # -- decode --------------------------------------------------------------
    def _build_decode(self, max_steps: int, with_presence: bool = False):
        return self._build_decode_any(max_steps, with_presence=with_presence)

    def _build_decode_ragged(self, max_steps: int, with_presence: bool = False):
        return self._build_decode_any(
            max_steps, with_ragged=True, with_presence=with_presence
        )

    def _build_decode_full(self, max_steps: int, *, ragged: bool,
                           with_presence: bool, with_bias: bool,
                           with_logprobs: bool, with_counts: bool = False):
        return self._build_decode_any(
            max_steps, with_ragged=ragged, with_presence=with_presence,
            with_counts=with_counts, with_bias=with_bias,
            with_logprobs=with_logprobs,
        )

    def _build_decode_any(self, max_steps: int, *, with_ragged: bool = False,
                          with_presence: bool = False,
                          with_counts: bool = False, with_bias: bool = False,
                          with_logprobs: bool = False):
        from ..engine.generate import count_update, presence_update

        cfg, sp = self.cfg, self.sp
        PP = self.pp

        def body(shared, layers, first_token, cache, start_pos, limit, key,
                 sampling, *extra):
            i = 0
            valid_start = presence0 = counts0 = bias = None
            if with_ragged:
                valid_start = extra[i]
                i += 1
            if with_presence:
                presence0 = extra[i]
                i += 1
            if with_counts:
                counts0 = extra[i]
                i += 1
            if with_bias:
                bias = extra[i]
                i += 1
            key = self._dp_key(key)
            Sc = cache["k"].shape[3]
            B = first_token.shape[0]
            pad = jnp.int32(cfg.pad_token_id)
            out0 = jnp.full((B, max_steps), pad, jnp.int32)
            finished0 = stop_mask(cfg, first_token)
            pres0 = (
                presence0 if with_presence else jnp.zeros((B, 1), jnp.bool_)
            )
            cnt0 = counts0 if with_counts else jnp.zeros((B, 1), jnp.int32)
            lp0 = jnp.zeros((B, max_steps if with_logprobs else 1), jnp.float32)

            def cond(c):
                step, _, _, _, _, _, _, _, finished, _, _ = c[:11]
                return (step < limit) & ~jnp.all(finished)

            def step_fn(c):
                (step, token, pos, ck, cv, pids, fill, key, finished, out,
                 n_gen, pres, cnt, lps) = c
                # least-filled shard stores this token (parallel/ring.py:
                # cp_select_slot rationale — prefill places chunks
                # contiguously, so pos % sp round-robin would overflow the
                # prefill-heavy shard long before the cache is full)
                fills, owner_idx, owner = cp_select_slot(fill[0], AXIS_SP)
                overflow = fills[owner_idx] >= Sc
                owner = owner & jnp.logical_not(overflow)
                slot = jnp.minimum(fill[0, 0], Sc - 1)
                # local pos_ids view with this token's slot tagged (owner only)
                old_id = jax.lax.dynamic_slice(pids, (0, slot), (1, 1))
                new_id = jnp.where(owner, pos.astype(jnp.int32)[None, None], old_id)
                pids2 = jax.lax.dynamic_update_slice(pids, new_id, (0, slot))

                def cp_hook(cfg_, q, k, v, ck_l, cv_l, pos_, mask, gate,
                            vs=None, window_flag=None):
                    win = self._layer_window(window_flag)
                    # pp microstep ring: a stage only writes its cache on
                    # its own microstep. _microstep_loop always supplies
                    # the traced (i == stage) gate — True everywhere at
                    # pp == 1 — so the write keeps owner & gate, period.
                    owner_w = owner & gate
                    if isinstance(ck_l, KVQuant):
                        # int8 cache: quantize the token, write data +
                        # scale owner-gated, attend over the locally
                        # dequantized slot set (the log-sum-exp merge is
                        # over DEQUANTIZED partials, identical values to
                        # the dense int8 path's)
                        qk, sk = quantize_chunk(k)
                        qv, sv = quantize_chunk(v)
                        dq, dv_ = cp_kv_write(
                            ck_l.q, cv_l.q, qk, qv, slot, owner_w
                        )
                        ck_l = KVQuant(
                            dq, cp_scale_write(ck_l.s, sk, slot, owner_w)
                        )
                        cv_l = KVQuant(
                            dv_, cp_scale_write(cv_l.s, sv, slot, owner_w)
                        )
                        attn = cp_decode_attend(
                            q, kv_dequantize(ck_l), kv_dequantize(cv_l),
                            pids2[0], pos_, AXIS_SP,
                            scale=cfg.query_scale,
                            softcap=cfg.attn_softcap,
                            window=win, valid_start=vs,
                        )
                        return attn, ck_l, cv_l
                    ck_l, cv_l = cp_kv_write(ck_l, cv_l, k, v, slot, owner_w)
                    attn = cp_decode_attend(
                        q, ck_l, cv_l, pids2[0], pos_, AXIS_SP,
                        scale=cfg.query_scale, softcap=cfg.attn_softcap,
                        window=win, valid_start=vs,
                    )
                    return attn, ck_l, cv_l

                # the shared gated microstep ring (SPMDBackendBase.
                # _microstep_loop; pp == 1 degenerates exactly): each
                # stage's layers run the cp log-sum-exp merge over sp,
                # cache writes keep owner & gate only; the real
                # final-stage output lands on stage 0 and a masked psum
                # broadcasts it (no-op at pp == 1)
                x = embed_sharded(cfg, shared, token[:, None], pos, PP)
                x, kv = self._microstep_loop(
                    layers, x, {"k": ck, "v": cv}, pos, valid_start,
                    attn_hook=cp_hook,
                )
                # pp-only broadcast: quantize only when the pp axis is a
                # real wire (pp == 1 psums a no-op and must stay exact)
                x = self._bcast(
                    x, jax.lax.axis_index(AXIS_PP) == 0, AXIS_PP,
                    quant=self._wire_ring,
                )
                logits = unembed_sharded(cfg, shared, x[:, -1:, :], PP)[:, 0, :]
                key, sub = jax.random.split(key)
                nxt = sample_token(
                    sub, logits, *sampling,
                    presence=pres if with_presence else None,
                    counts=cnt if with_counts else None,
                    bias=bias,
                )
                if with_presence:
                    pres = presence_update(pres, nxt)
                # overflow (every shard full): token was not stored, so this
                # step's attention missed it — discard and stop, don't emit
                newly = finished | stop_mask(cfg, nxt) | overflow
                if with_counts:
                    cnt = count_update(cnt, nxt, ~newly)
                emit = jnp.where(newly, pad, nxt)
                out = jax.lax.dynamic_update_slice(
                    out, emit[:, None], (jnp.int32(0), step)
                )
                if with_logprobs:
                    # raw-distribution logprob of the emitted token (the
                    # OpenAI convention — pre-temperature/filters/bias),
                    # same as the single-device and pp variants
                    logp = jax.nn.log_softmax(
                        logits.astype(jnp.float32), axis=-1
                    )
                    tok_lp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)
                    lps = jax.lax.dynamic_update_slice(
                        lps, tok_lp, (jnp.int32(0), step)
                    )
                n_gen = n_gen + (~newly).astype(jnp.int32)
                fill = fill + owner.astype(jnp.int32)
                return (step + 1, emit, pos + 1, kv["k"], kv["v"], pids2, fill,
                        key, newly, out, n_gen, pres, cnt, lps)

            init = (
                jnp.int32(0),
                jnp.where(finished0, pad, first_token),
                start_pos,
                cache["k"], cache["v"], cache["pos_ids"], cache["fill"],
                key,
                finished0,
                out0,
                jnp.zeros((B,), jnp.int32),
                pres0,
                cnt0,
                lp0,
            )
            (_, _, _, ck, cv, pids, fill, _, _, out, n_gen, _, _, lps) = (
                jax.lax.while_loop(cond, step_fn, init)
            )
            cache2 = {"k": ck, "v": cv, "pos_ids": pids, "fill": fill}
            if with_logprobs:
                return out, n_gen, cache2, lps
            return out, n_gen, cache2

        cache_specs = {
            "k": cp_cache_spec(cfg), "v": cp_cache_spec(cfg),
            "pos_ids": _AUX_SPEC, "fill": _AUX_SPEC,
        }
        specs = [
            self._shared_specs, self._layer_specs, P(AXIS_DP), cache_specs,
            P(), P(), P(), P(),
        ]
        if with_ragged:
            specs.append(P(AXIS_DP))  # valid_start [B] shards with the batch
        if with_presence:
            specs.append(P(AXIS_DP))
        if with_counts:
            specs.append(P(AXIS_DP))
        if with_bias:
            specs.append(P())
        out_specs = [P(AXIS_DP), P(AXIS_DP), cache_specs]
        if with_logprobs:
            out_specs.append(P(AXIS_DP))
        shmapped = self._shard(
            body,
            in_specs=tuple(specs),
            out_specs=tuple(out_specs),
        )
        return jax.jit(shmapped, donate_argnums=(3,))

    # -- health --------------------------------------------------------------
    def health(self) -> list[dict]:
        """Context shards instead of pipeline stages: each 'worker' is one
        ring member holding seq/sp of the KV cache. On an sp x pp mesh
        the pipeline stages are the workers (each stage's row spans its
        sp ring members)."""
        from ..utils.probe import probe_device

        devs = self.mesh.devices  # [dp, pp, sp, tp]
        out = []
        if self.pp > 1:
            # the base sweep already does per-stage all-device concurrent
            # probing with worst-status aggregation (a dead non-first
            # device must not report healthy) and multi-process "remote"
            # handling — reuse it, tagging the composed role
            out = super().health()
            for line in out:
                line["role"] = "pipeline-stage+context-ring"
            return out
        for s in range(self.sp):
            shard_devs = devs[:, :, s, :].reshape(-1)
            out.append(
                {
                    "stage": s,
                    "devices": [str(d) for d in shard_devs],
                    "role": "context-shard",
                    **probe_device(shard_devs[0]),
                }
            )
        return out
