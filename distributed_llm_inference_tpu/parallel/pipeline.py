"""SPMD pipeline-parallel runtime: all stages in one compiled program.

This replaces the reference's entire distributed fabric — the orchestrator
POSTing JSON activations to worker Flask servers over ngrok tunnels, twice
per token (/root/reference/orchestration.py:114-137, Worker1.py:208-245) —
with a single `jax.shard_map` program over the `pp` mesh axis:

  * each device holds one stage: a contiguous shard of the stacked layer
    params and of the stacked KV cache (parallel/partition.py);
  * the activation hand-off is `lax.ppermute` over the ICI ring — the
    TPU-native form of the reference's HTTP hop (boundaries #2/#3 in
    SURVEY.md §3.1);
  * one microstep = every stage applies its layer shard to its current
    buffer, then the ring shifts; a stage's cache write is gated on the
    microstep owning it, so speculative compute on stale buffers is
    discarded at slice granularity;
  * after S microsteps the last stage's output has rotated to stage 0; a
    masked `psum` broadcasts that [B, 1, D] activation, every device
    computes its VOCAB SHARD of the logits (parallel/vocab.py — embed and
    head are vocab-sharded over pp, not replicated) and the all_gather'd
    logits are identical everywhere, so every device samples the SAME next
    token with the same key — the decode loop (`lax.while_loop`) then
    continues entirely on-device, with zero host round-trips per token.

Latency shape: batch-1 decode costs S microsteps/token (the classic
pipeline bubble — the whole model's FLOPs, just spread over stages);
microbatching (parallel.schedule) fills the bubble for batched configs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..analysis import comms
from ..config import ModelConfig
from ..engine.generate import (
    SamplingParams, count_update, presence_update, stop_mask,
)
from ..models import api as M
from ..ops.sampling import sample_token
from ..ops.wire_quant import masked_psum, wire_bytes, wire_ppermute
from .mesh import AXIS_DP, AXIS_EP, AXIS_PP, AXIS_TP
from .partition import (
    cache_spec, init_sharded_cache, layer_specs, shard_params, shared_specs,
)
from .vocab import embed_sharded, unembed_sharded


def _ring_perm(S: int):
    return [(j, (j + 1) % S) for j in range(S)]


def _replicated_specs(nt_cls):
    """Fully-replicated PartitionSpec tree for a NamedTuple class (slot
    state/params enter every shard_map whole) — field-count-proof: adding
    a field to the NamedTuple updates every spec site automatically."""
    return nt_cls(*([P()] * len(nt_cls._fields)))


class SPMDBackendBase:
    """Shared scaffolding for the SPMD mesh backends.

    Owns the mesh-axis bookkeeping, parameter sharding, shard_map partial,
    per-max_steps decode-program memoization, dp key decorrelation, and the
    per-stage health report. Subclasses implement `_build_prefill()` and
    `_build_decode(max_steps)`.
    """

    name = "spmd-base"
    # HF-parity repetition penalty: subclasses whose builders accept the
    # presence variants set this True (PipelineBackend); others reject
    # loudly at build time
    supports_presence = False

    def __init__(self, cfg: ModelConfig, params: dict, mesh: Mesh,
                 wire_quant=None):
        if wire_quant not in (None, "int8"):
            # same error shape as EngineConfig's validation — backends
            # constructed directly (tests, embedders) fail identically
            raise ValueError(
                f"pp_wire_quant must be None or 'int8', got {wire_quant!r}"
            )
        self.cfg = cfg
        self.mesh = mesh
        self.dp = int(mesh.shape.get(AXIS_DP, 1))
        self.pp = int(mesh.shape[AXIS_PP])
        self.tp = int(mesh.shape.get(AXIS_TP, 1))
        self.ep = int(mesh.shape.get(AXIS_EP, 1))
        self.n_stages = self.pp
        self.tp_axis = AXIS_TP if self.tp > 1 else None
        self.ep_axis = AXIS_EP if self.ep > 1 else None
        # int8 wire format (EngineConfig.pp_wire_quant, ops/wire_quant.py):
        # _wire_ring quantizes the microstep ring's ppermute hops,
        # _wire_bcast the masked-psum broadcasts of the final-stage
        # window. Both stay False on a singleton pp axis — there is no
        # wire, and a quantize round trip there would break the
        # pp == 1 exact-degeneration contract. The context backend
        # widens _wire_bcast for its sp axis (sp >= 2 always transfers).
        self.wire_quant = wire_quant
        self._wire_ring = wire_quant is not None and self.pp > 1
        self._wire_bcast = wire_quant is not None and self.pp > 1
        # dli_pp_wire_bytes_total family — attached by the engine
        # (attach_wire_metrics); accounting is host-side static
        # arithmetic at program-call seams, never traced
        self._wire_metrics = None
        self.shared, self.layers = shard_params(cfg, params, mesh)
        self._layer_specs = layer_specs(cfg, self.layers)
        self._shared_specs = shared_specs(self.shared)
        self._shard = functools.partial(
            jax.shard_map, mesh=mesh, check_vma=False
        )
        # memoized compiled shard_map programs beyond the core pair
        # (extend / ragged variants), keyed by (kind, flags)
        self._programs: dict = {}
        self._prefill = self._build_prefill()
        self._decode_cache: dict = {}

    # -- engine interface ---------------------------------------------------
    def init_cache(self, batch: int, max_seq: int):
        return init_sharded_cache(self.cfg, self.mesh, batch, max_seq)

    def prefill(self, tokens, prompt_len, cache, key, sampling,
                valid_start=None, presence=None):
        if valid_start is not None:
            raise NotImplementedError(
                f"{self.name} does not support ragged (valid_start) batches"
            )
        if presence is not None:
            raise NotImplementedError(
                f"{self.name} does not support repetition-penalty presence"
            )
        return self._prefill(
            self.shared, self.layers, tokens, prompt_len, cache, key, sampling
        )

    def decode(self, first_token, cache, start_pos, limit, key, sampling,
               valid_start=None, presence=None, counts=None, bias=None,
               constraint=None, *, max_steps, with_logprobs=False):
        """One dispatch for every subclass: programs are keyed by
        (max_steps, ragged, presence, counts, bias, constraint, logprobs);
        builders that don't support a variant raise NotImplementedError at
        build time (loud, not silently wrong)."""
        # static wire accounting: a host-int limit bounds the ring passes
        # exactly; a traced limit falls back to max_steps (never forces a
        # device sync for a byte counter)
        self._account_decode_wire(
            int(first_token.shape[0]),
            min(limit, max_steps) if isinstance(limit, int) else max_steps,
        )
        return self._decode_dispatch(
            self._decode_cache, self._variant_builder, first_token, cache,
            start_pos, limit, key, sampling, valid_start, presence, counts,
            bias, constraint, max_steps=max_steps,
            with_logprobs=with_logprobs,
        )

    def _variant_builder(self, variant):
        """variant (max_steps, ragged, pres, wc, wb, wcn, logprobs) ->
        compiled program, through the subclass's _build_decode* hooks."""
        max_steps, ragged, pres, wc, wb, wcn, with_logprobs = variant
        if wcn and not getattr(self, "supports_constrain", False):
            raise NotImplementedError(
                f"{self.name} does not support constrained decoding"
            )
        if wb or with_logprobs or wc or wcn:
            kw = {"with_constraint": True} if wcn else {}
            return self._build_decode_full(
                max_steps, ragged=ragged, with_presence=pres,
                with_counts=wc, with_bias=wb, with_logprobs=with_logprobs,
                **kw,
            )
        if ragged:
            return self._build_decode_ragged(max_steps, with_presence=pres)
        return self._build_decode(max_steps, with_presence=pres)

    def _decode_dispatch(self, memo, builder, first_token, cache, start_pos,
                         limit, key, sampling, valid_start, presence, counts,
                         bias, constraint, *, max_steps, with_logprobs):
        """The ONE copy of the variant->program->args contract (memo key,
        builder selection, limit clamp, positional extra-arg order) —
        shared by the base dispatch and the 1F1B backend's plain-ring
        fallback, which passes its own memo + builder."""
        ragged = valid_start is not None
        pres = presence is not None
        wc = counts is not None
        wb = bias is not None
        wcn = constraint is not None
        variant = (max_steps, ragged, pres, wc, wb, wcn, with_logprobs)
        fn = memo.get(variant)
        if fn is None:
            fn = builder(variant)
            memo[variant] = fn
        # clamp: limit > max_steps would walk dynamic_update_slice off the
        # end of `out` (the start index clamps, corrupting the last column)
        # and inflate n_gen past the buffer
        limit = jnp.minimum(jnp.int32(limit), jnp.int32(max_steps))
        args = [
            self.shared, self.layers, first_token, cache, start_pos, limit,
            key, sampling,
        ]
        for flag, val in (
            (ragged, valid_start), (pres, presence), (wc, counts), (wb, bias)
        ):
            if flag:
                args.append(val)
        if wcn:
            args.extend(constraint)  # fsm0 [B], cmask [S, V], ctrans [S, V]
        return fn(*args)

    def health(self) -> list[dict]:
        """Per-stage liveness — the reference's /workers sweep polls each
        worker's /health with a 5 s timeout and reports online/offline/
        error (orchestration.py:306-329); here a stage is a mesh slice, so
        EVERY device in the stage's (dp, sp, tp) slice gets a tiny timed
        device op (utils/probe.py) instead of an HTTP GET — a dead
        non-first device must not report healthy (round-2 review weak #8).
        All probes run CONCURRENTLY so a fully wedged mesh still answers
        in ~one probe timeout, not devices x timeout."""
        from concurrent.futures import ThreadPoolExecutor

        from ..config import stage_layer_range
        from ..utils.probe import probe_device

        devs = self.mesh.devices  # [dp, pp, sp, tp]
        stage_devs = [devs[:, s].reshape(-1) for s in range(self.pp)]
        flat = [d for sd in stage_devs for d in sd]
        # multi-process mesh: only THIS process's devices accept probe ops;
        # other processes' devices report "remote" (their own controller
        # probes them — a mirrored follower runs this same sweep locally)
        me = jax.process_index()

        def probe_local(d):
            if d.process_index != me:
                return {"status": "remote", "process": d.process_index}
            return probe_device(d)

        with ThreadPoolExecutor(max_workers=max(1, len(flat))) as ex:
            flat_probes = list(ex.map(probe_local, flat))
        out = []
        i = 0
        rank = {"online": 0, "remote": 1, "busy": 2, "error": 3, "offline": 4}
        for s in range(self.pp):
            probes = flat_probes[i : i + len(stage_devs[s])]
            i += len(stage_devs[s])
            worst = max(probes, key=lambda p: rank.get(p.get("status"), 2))
            stage_line = {
                "stage": s,
                "devices": [str(d) for d in stage_devs[s]],
                "layers": list(
                    range(*stage_layer_range(self.cfg.n_layers, self.pp, s))
                ),
                **worst,
            }
            if len(probes) > 1:
                stage_line["device_status"] = [
                    p.get("status") for p in probes
                ]
            out.append(stage_line)
        return out

    # -- the gated microstep ring — shared by every PipelineBackend
    # program AND the sp x pp composition (parallel/context.py); pp == 1
    # degenerates exactly (singleton-axis ppermute is a no-op and the
    # gate is always True) -------------------------------------------------
    def _microstep_loop(self, layers, x, cache, pos, valid_start=None,
                        attn_hook=None, attn_seq_len=None, lora_pages=None):
        """S microsteps of (apply local stage, ring-shift). Returns the
        final-stage output (landed on stage 0 by the last shift) + cache.
        attn_hook/attn_seq_len thread the paged-pool seam (cache = block
        pool, hook = engine/paged.make_paged_hook) through the same gated
        ring — one loop for the dense and paged cache strategies.
        lora_pages threads the paged-adapter delta (engine/adapters) —
        replicated per-row page ids; the lora leaves shard with their
        base projections (parallel/partition.py) so each stage computes
        its local delta shard."""
        cfg, S = self.cfg, self.pp
        s = jax.lax.axis_index(AXIS_PP)
        perm = _ring_perm(S)

        def micro(i, carry):
            buf, cache = carry
            gate = i == s
            y, cache = M.forward_layers(
                cfg, layers, buf, cache, pos, update_gate=gate,
                tp_axis=self.tp_axis, valid_start=valid_start,
                ep_axis=self.ep_axis, attn_hook=attn_hook,
                attn_seq_len=attn_seq_len, lora_pages=lora_pages,
            )
            # the inter-stage hand-off: int8 data + fp32 per-token-row
            # scales on the wire when pp_wire_quant is on (quant=False
            # IS lax.ppermute — bit-identical off path)
            buf = wire_ppermute(y, AXIS_PP, perm, quant=self._wire_ring)
            return buf, cache

        return jax.lax.fori_loop(0, S, micro, (x, cache))

    def _bcast(self, x, sel, axes=AXIS_PP, quant=None):
        """Masked psum broadcast of a single owner's [B, .., D] activation
        window — the hand-off every pp program's sampling tail starts
        with. With pp_wire_quant on, the all-reduce ships int8 data +
        fp32 scales (EQuARX recipe; ops/wire_quant.masked_psum);
        off, it is the exact masked-psum idiom this replaced."""
        if quant is None:
            quant = self._wire_bcast
        return masked_psum(x, sel, axes, quant=quant)

    # -- host-side static wire accounting (dli_pp_wire_bytes_total) ---------
    def attach_wire_metrics(self, registry):
        """Engine seam (engine/engine.py pre-registers the families): the
        backend increments per-launch byte counts computed from static
        shapes — no tracing cost, no host syncs."""
        self._wire_metrics = registry.counter(
            "dli_pp_wire_bytes_total",
            "inter-stage activation bytes shipped on the pp/sp wire, by "
            "transfer family", ("path",),
        )

    def _wire_account(self, path: str, shape, hops: int, axis_size=None,
                      quant=None):
        """Count `hops` crossings of one [..., D] activation on the wire
        (static shapes only; decode while_loops count their full
        ring-pass upper bound — documented in ARCHITECTURE.md).
        axis_size: participants on the transfer axis (default pp) — a
        singleton axis moves nothing, so it counts nothing. quant: what
        actually crossed (default: the wire knob; the sp path passes
        `or kv_quant` — an int8 cache's chunks are int8 on the wire
        with or without the knob)."""
        fam = self._wire_metrics
        if axis_size is None:
            axis_size = self.pp
        if fam is None or hops <= 0 or axis_size <= 1:
            return
        if quant is None:
            quant = self.wire_quant is not None
        itemsize = jnp.dtype(self.cfg.jnp_dtype).itemsize
        fam.labels(path=path).inc(
            wire_bytes(shape, itemsize, hops, quant=quant)
        )

    def _account_link(self, name: str, *, axis_size=None, quant=None,
                      **launch):
        """Account one launch of a named wire link (the ONE symbolic
        bytes model: analysis/comms.WIRE_LINKS). Shape and hop-count
        arithmetic live in the link table — the `--comms` report, the
        bench `comms_report` leg, and these counters all evaluate the
        same formulas, so they cannot drift. `launch` supplies the
        per-call params (rows/t/steps/...); topology dims default from
        the backend."""
        spec = comms.WIRE_LINKS[name]
        p = comms.params_from_config(self.cfg, **launch)
        p.setdefault("dp", self.dp)
        p.setdefault("pp", self.pp)
        sp = getattr(self, "sp", None)
        if sp is not None:
            p.setdefault("sp", sp)
        mb = getattr(self, "n_microbatches", None)
        if mb is not None:
            p.setdefault("mb", mb)
        self._wire_account(
            spec.path, spec.shape(p), spec.hops(p),
            axis_size=axis_size, quant=quant,
        )

    def _account_decode_wire(self, rows: int, steps: int):
        """Per-decode-launch accounting for the plain microstep ring:
        S ppermute hops + one broadcast per emitted token (bytes are
        PER ICI LINK — the binding quantity; dp rings are independent,
        so a dp shard's rows divide out)."""
        if self.pp <= 1:
            return
        self._account_link("pp-microstep-decode", rows=rows, steps=steps)
        self._account_link("pp-broadcast-decode", rows=rows, steps=steps)

    def _dp_key(self, key):
        """Decorrelate sampling across dp batch shards. dp=1 keeps the key
        untouched so the pipeline stays bit-identical to single-device."""
        if self.dp == 1:
            return key
        return jax.random.fold_in(key, jax.lax.axis_index(AXIS_DP))

    def _build_prefill(self):
        raise NotImplementedError

    def _build_decode(self, max_steps: int, with_presence: bool = False):
        raise NotImplementedError

    def _build_decode_ragged(self, max_steps: int, with_presence: bool = False):
        raise NotImplementedError(
            f"{self.name} does not support ragged (valid_start) batches"
        )

    def _build_decode_full(self, max_steps: int, *, ragged: bool,
                           with_presence: bool, with_bias: bool,
                           with_logprobs: bool, with_counts: bool = False):
        raise NotImplementedError(
            f"{self.name} does not support logit_bias / per-token-logprobs "
            f"/ frequency-presence-penalty-counts decode variants"
        )


class PipelineBackend(SPMDBackendBase):
    """Engine-compatible backend running (dp, pp, tp) SPMD over a mesh.

    Drop-in for SingleDeviceBackend (same init_cache/prefill/decode/health
    interface), so InferenceEngine and the serving layer are topology-
    agnostic — the reference needed three differently-coded processes for
    the same job (orchestration.py vs Worker1.py vs Worker2.py).

    Axes: `pp` stages hand activations around the ICI ring; `tp` shards
    heads/FFN within a stage (psums inside models/*.decoder_layer); `dp`
    shards the batch — each dp slice is an independent pipeline ring (its
    while-loop may even exit at a different step; no collective crosses dp).
    """

    name = "pipeline"
    # Ragged left-padded batches thread valid_start through the llama-family
    # mask; the engine checks arch before requesting them.
    supports_ragged = True
    supports_presence = True
    # OpenAI frequency/presence penalties (counts-tracked decode variants)
    supports_counts = True
    # grammar-constrained decoding (constrain/): the FSM gathers run on
    # the REPLICATED logits/tables after the vocab-shard all_gather, so
    # every device samples and advances the same state — identical to the
    # single-device stack by construction
    supports_constrain = True

    # -- chunked prefill (engine: prompts beyond the largest bucket) --------
    def extend(self, tokens, pos, cache):
        """Run a full prompt chunk at offset `pos` (no logits/sampling),
        mirroring engine.generate's chunked-prefill contract with
        SingleDeviceBackend (engine/generate.py extend)."""
        fn = self._programs.get("extend")
        if fn is None:
            fn = self._build_extend()
            self._programs["extend"] = fn
        self._account_link(
            "pp-microstep-prefill",
            rows=int(tokens.shape[0]), t=int(tokens.shape[1]),
        )
        return fn(self.shared, self.layers, tokens, pos, cache)

    def prefill_at(self, tokens, pos, valid_len, cache, key, sampling,
                   presence=None, bias=None):
        """Final chunked-prefill chunk at traced offset `pos`; samples the
        first token off position pos + valid_len - 1."""
        return self._prefill_any(
            tokens, pos, valid_len, cache, key, sampling, None, presence, bias
        )

    def prefill(self, tokens, prompt_len, cache, key, sampling,
                valid_start=None, presence=None, bias=None):
        return self._prefill_any(
            tokens, jnp.int32(0), prompt_len, cache, key, sampling,
            valid_start, presence, bias,
        )

    def _prefill_any(self, tokens, pos, valid_len, cache, key, sampling,
                     valid_start, presence=None, bias=None):
        ragged = valid_start is not None
        pres = presence is not None
        wb = bias is not None
        fn = self._programs.get(("prefill", ragged, pres, wb))
        if fn is None:
            fn = self._build_prefill_pos(ragged, pres, wb)
            self._programs[("prefill", ragged, pres, wb)] = fn
        args = [self.shared, self.layers, tokens, pos, valid_len, cache, key, sampling]
        if ragged:
            args.append(valid_start)
        if pres:
            args.append(presence)
        if wb:
            args.append(bias)
        B, T = int(tokens.shape[0]), int(tokens.shape[1])
        self._account_link("pp-microstep-prefill", rows=B, t=T)
        self._account_link("pp-broadcast-prefill", rows=B)
        return fn(*args)

    def _build_prefill(self):
        # base-class hook: the pos=0 non-ragged program, via the shared
        # builder (prefill()/prefill_at() both route through _prefill_any)
        fn = self._build_prefill_pos(False, False)
        self._programs[("prefill", False, False, False)] = fn
        return lambda shared, layers, tokens, prompt_len, cache, key, sampling: fn(
            shared, layers, tokens, jnp.int32(0), prompt_len, cache, key, sampling
        )

    def _build_prefill_pos(self, ragged: bool, with_presence: bool = False,
                           with_bias: bool = False):
        cfg, S = self.cfg, self.pp

        def body(shared, layers, tokens, pos, valid_len, cache, key, sampling,
                 *extra):
            i = 0
            valid_start = presence = bias = None
            if ragged:
                valid_start = extra[i]
                i += 1
            if with_presence:
                presence = extra[i]
                i += 1
            if with_bias:
                bias = extra[i]
                i += 1
            s = jax.lax.axis_index(AXIS_PP)
            key = self._dp_key(key)
            x = embed_sharded(cfg, shared, tokens, pos, S)
            buf, cache = self._microstep_loop(layers, x, cache, pos, valid_start)
            # the real final-stage output lives on stage 0; broadcast the
            # [B, 1, D] slice (not the vocab row) then compute the vocab-
            # sharded logits everywhere
            last = jax.lax.dynamic_slice_in_dim(buf, valid_len - 1, 1, axis=1)
            last = self._bcast(last, s == 0)
            logits = unembed_sharded(cfg, shared, last, S)[:, 0, :]
            first = sample_token(
                key, logits, *sampling, presence=presence, bias=bias
            )
            return first, logits, cache

        specs = [
            self._shared_specs, self._layer_specs, P(AXIS_DP), P(), P(),
            cache_spec(self.cfg), P(), P(),
        ]
        if ragged:
            specs.append(P(AXIS_DP))
        if with_presence:
            specs.append(P(AXIS_DP))
        if with_bias:
            specs.append(P())  # [V] bias replicates: logits are replicated
        shmapped = self._shard(
            body,
            in_specs=tuple(specs),
            out_specs=(P(AXIS_DP), P(AXIS_DP), cache_spec(self.cfg)),
        )
        return jax.jit(shmapped, donate_argnums=(5,))

    def _build_extend(self):
        cfg = self.cfg

        def body(shared, layers, tokens, pos, cache):
            x = embed_sharded(cfg, shared, tokens, pos, self.pp)
            _, cache = self._microstep_loop(layers, x, cache, pos)
            return cache

        shmapped = self._shard(
            body,
            in_specs=(
                self._shared_specs, self._layer_specs, P(AXIS_DP), P(),
                cache_spec(self.cfg),
            ),
            out_specs=cache_spec(self.cfg),
        )
        return jax.jit(shmapped, donate_argnums=(4,))

    # -- continuous batching (slot decode) over the pp ring -----------------
    @property
    def supports_slots(self) -> bool:
        """Slot decode (engine/continuous.py) on the pipeline mesh: the
        fleet's B rows are SLOTS, not data shards, so the host's slot
        bookkeeping requires dp == 1 (tp/ep replicate the batch and
        compose fine). Both families: gpt2's learned positions are exact
        in slots mode — every slot starts at position 0 (no left-pad)."""
        return self.dp == 1 and self.cfg.arch in ("llama", "gpt2")

    def _account_slots_wire(self, rows: int, num_steps: int):
        """Slot-decode chunk: S ring hops + one broadcast per step."""
        self._account_link("pp-microstep-slots", rows=rows, steps=num_steps)
        self._account_link("pp-broadcast-slots", rows=rows, steps=num_steps)

    def decode_slots(self, state, cache, key, sparams, *, num_steps):
        fn = self._programs.get(("slots", num_steps))
        if fn is None:
            fn = self._build_decode_slots(num_steps)
            self._programs[("slots", num_steps)] = fn
        self._account_slots_wire(int(state.token.shape[0]), num_steps)
        return fn(self.shared, self.layers, state, cache, key, sparams)

    def _build_decode_slots(self, num_steps: int):
        """shard_map slot-decode chunk: same per-row-position fleet as
        engine/generate.decode_slots, but each step's forward is S ring
        microsteps over the pp stages (cache writes gated per microstep,
        exactly like plain pipeline decode). Sampling keys/params are
        replicated, so every device computes identical tokens and state —
        the host reads one copy."""
        cfg, S = self.cfg, self.pp
        from ..engine.generate import slot_step

        def body(shared, layers, state, cache, key, sparams):
            def step(carry, sub):
                state, cache = carry
                x = embed_sharded(cfg, shared, state.token[:, None], state.pos, S)
                buf, cache = self._microstep_loop(layers, x, cache, state.pos)
                s = jax.lax.axis_index(AXIS_PP)
                last = self._bcast(buf[:, -1:, :], s == 0)
                logits = unembed_sharded(cfg, shared, last, S)[:, 0, :]
                # shared per-step sampling/bookkeeping (engine/generate.py):
                # the cross-backend token-parity guarantee lives in ONE place
                new, emit, can_emit = slot_step(cfg, state, sparams, logits, sub)
                return (new, cache), (emit, can_emit)

            subs = jax.random.split(key, num_steps)
            (state, cache), (emitted, emit_mask) = jax.lax.scan(
                step, (state, cache), subs
            )
            return emitted, emit_mask, state, cache

        from ..engine.generate import SlotParams, SlotState

        state_specs = _replicated_specs(SlotState)
        sparam_specs = _replicated_specs(SlotParams)
        shmapped = self._shard(
            body,
            in_specs=(
                self._shared_specs, self._layer_specs, state_specs,
                cache_spec(self.cfg), P(), sparam_specs,
            ),
            out_specs=(P(), P(), state_specs, cache_spec(self.cfg)),
        )
        return jax.jit(shmapped, donate_argnums=(3,))

    # -- constrained slot decode on the pp ring ------------------------------
    @property
    def supports_constrained_slots(self) -> bool:
        """Grammar-constrained tenants in the continuous fleet on a pp
        mesh: same dp == 1 slot constraint as decode_slots."""
        return self.supports_slots

    def decode_slots_constrained(self, state, cache, key, sparams, fsm,
                                 cmask, ctrans, *, num_steps):
        fn = self._programs.get(("slots_cn", num_steps))
        if fn is None:
            fn = self._build_decode_slots_constrained(num_steps)
            self._programs[("slots_cn", num_steps)] = fn
        self._account_slots_wire(int(state.token.shape[0]), num_steps)
        return fn(self.shared, self.layers, state, cache, key, sparams,
                  fsm, cmask, ctrans)

    def _build_decode_slots_constrained(self, num_steps: int):
        """Constrained twin of _build_decode_slots: the shared
        slot_step_constrained (engine/generate.py) runs on the replicated
        logits, so tokens AND fsm states are identical on every device —
        the same one-copy parity guarantee as the unconstrained fleet."""
        cfg, S = self.cfg, self.pp
        from ..engine.generate import slot_step_constrained

        def body(shared, layers, state, cache, key, sparams, fsm, cmask,
                 ctrans):
            def step(carry, sub):
                state, cache, fsm = carry
                x = embed_sharded(cfg, shared, state.token[:, None], state.pos, S)
                buf, cache = self._microstep_loop(layers, x, cache, state.pos)
                s = jax.lax.axis_index(AXIS_PP)
                last = self._bcast(buf[:, -1:, :], s == 0)
                logits = unembed_sharded(cfg, shared, last, S)[:, 0, :]
                new, emit, can_emit, fsm = slot_step_constrained(
                    cfg, state, sparams, logits, sub, fsm, cmask, ctrans
                )
                return (new, cache, fsm), (emit, can_emit)

            subs = jax.random.split(key, num_steps)
            (state, cache, fsm), (emitted, emit_mask) = jax.lax.scan(
                step, (state, cache, fsm), subs
            )
            return emitted, emit_mask, state, cache, fsm

        from ..engine.generate import SlotParams, SlotState

        state_specs = _replicated_specs(SlotState)
        sparam_specs = _replicated_specs(SlotParams)
        shmapped = self._shard(
            body,
            in_specs=(
                self._shared_specs, self._layer_specs, state_specs,
                cache_spec(self.cfg), P(), sparam_specs, P(), P(), P(),
            ),
            out_specs=(P(), P(), state_specs, cache_spec(self.cfg), P()),
        )
        return jax.jit(shmapped, donate_argnums=(3,))

    # -- block-paged KV on the pp ring (round-3 review #2: the flagship
    # memory feature on the reference's flagship topology) ------------------
    @property
    def supports_paged(self) -> bool:
        """Paged slot decode on the pipeline mesh: same constraints as
        dense slots (dp == 1 — slot rows are slots, not data shards).
        Both families ride the shared attn_hook seam the pool writes use
        (gpt2's block routes through llama.default_attn_hook since
        round 5)."""
        return self.dp == 1 and self.cfg.arch in ("llama", "gpt2")

    def init_paged_pool(self, n_blocks, block_size):
        from .partition import init_sharded_pool

        return init_sharded_pool(self.cfg, self.mesh, n_blocks, block_size)

    def insert_slot_paged(self, pool, scratch, state, sparams, slot,
                          table_row, *args):
        fn = self._programs.get("insert_paged")
        if fn is None:
            fn = self._build_insert_paged()
            self._programs["insert_paged"] = fn
        return fn(pool, scratch, state, sparams, jnp.int32(slot), table_row,
                  *args)

    def _build_insert_paged(self):
        """shard_map twin of engine/paged.insert_slot_paged: the scratch →
        pool block scatter is LAYER-LOCAL (each stage scatters its own
        layer shard of the prefilled scratch into its pool slice), and
        arm_slot runs replicated so every device derives identical slot
        state."""
        cfg = self.cfg
        from ..engine import generate as G
        from ..engine import paged as EP
        from .partition import pool_spec

        def body(pool, scratch, state, sparams, slot, table_row,
                 first_token, prompt_len, max_tokens, temperature, top_k,
                 top_p, greedy, min_p, rep_penalty, freq_penalty,
                 pres_penalty, presence_row):
            pool = EP.scatter_scratch(pool, scratch, table_row)
            state, sparams = G.arm_slot(
                cfg, state, sparams, slot, first_token, prompt_len,
                max_tokens, temperature, top_k, top_p, greedy, min_p,
                rep_penalty, freq_penalty, pres_penalty, presence_row,
            )
            return pool, state, sparams

        from ..engine.generate import SlotParams, SlotState

        state_specs = _replicated_specs(SlotState)
        sparam_specs = _replicated_specs(SlotParams)
        shmapped = self._shard(
            body,
            in_specs=(
                pool_spec(cfg), cache_spec(cfg), state_specs, sparam_specs,
            ) + (P(),) * 14,
            out_specs=(pool_spec(cfg), state_specs, sparam_specs),
        )
        return jax.jit(shmapped, donate_argnums=(0,))

    def decode_slots_paged(self, state, pool, table, key, sparams, *,
                           num_steps, pages=None):
        mkey = ("slots_paged", num_steps, pages is not None)
        fn = self._programs.get(mkey)
        if fn is None:
            fn = self._build_decode_slots_paged(num_steps, pages is not None)
            self._programs[mkey] = fn
        self._account_slots_wire(int(state.token.shape[0]), num_steps)
        args = [self.shared, self.layers, state, pool, table, key, sparams]
        if pages is not None:
            args.append(pages)
        return fn(*args)

    def fill_scratch_paged(self, pool, table_row):
        fn = self._programs.get("fill_paged")
        if fn is None:
            fn = self._build_fill_paged()
            self._programs["fill_paged"] = fn
        return fn(pool, table_row)

    def _build_fill_paged(self):
        """shard_map twin of engine/paged.gather_scratch_blocks: the pool →
        scratch block gather is LAYER-LOCAL (each stage reads its own
        layer shard of the pool into its slice of the contiguous scratch),
        so block-level prefix sharing serves the pp fleet unchanged. The
        pool is mapped shared state — read, never donated."""
        cfg = self.cfg
        from ..engine import paged as EP
        from .partition import pool_spec

        def body(shared_pool, table_row):
            return EP._gather_blocks(shared_pool, table_row)

        shmapped = self._shard(
            body,
            in_specs=(pool_spec(cfg), P()),
            out_specs=cache_spec(cfg),
        )
        return jax.jit(shmapped)

    # -- warm-recovery shadow gather/scatter on the pp ring ------------------
    # shard_map twins of engine/paged.gather_shadow_blocks /
    # restore_shadow_blocks: both moves are LAYER-LOCAL (a stage reads or
    # writes its own layer shard of every requested block), so the
    # host-side shadow store sees the same [N, L, ...] stacked leaves as
    # on a single device — pp-sharded pools now recover WARM instead of
    # cold (the ROADMAP follow-up seam from the warm-recovery PR).
    def gather_shadow_blocks(self, pool, block_ids):
        fn = self._programs.get("gather_shadow")
        if fn is None:
            fn = self._build_gather_shadow()
            self._programs["gather_shadow"] = fn
        return fn(pool, block_ids)

    def _build_gather_shadow(self):
        cfg = self.cfg
        from ..engine import paged as EP
        from .partition import pool_spec, shadow_block_spec

        def body(shared_pool, block_ids):
            return EP._gather_shadow(shared_pool, block_ids)

        shmapped = self._shard(
            body,
            in_specs=(pool_spec(cfg), P()),
            out_specs=shadow_block_spec(cfg),
        )
        # the pool is mapped shared state here — read, never donated
        # (live block tables keep reading these buffers), exactly like
        # the single-device program's inverse-donation rule
        return jax.jit(shmapped)

    def restore_shadow_blocks(self, pool, blocks, block_ids):
        fn = self._programs.get("restore_shadow")
        if fn is None:
            fn = self._build_restore_shadow()
            self._programs["restore_shadow"] = fn
        return fn(pool, blocks, block_ids)

    def _build_restore_shadow(self):
        cfg = self.cfg
        from ..engine import paged as EP
        from .partition import pool_spec, shadow_block_spec

        def body(pool, blocks, block_ids):
            return EP._restore_shadow(pool, blocks, block_ids)

        shmapped = self._shard(
            body,
            in_specs=(pool_spec(cfg), shadow_block_spec(cfg), P()),
            out_specs=pool_spec(cfg),
        )
        return jax.jit(shmapped, donate_argnums=(0,))

    # -- ragged paged ingest on the pp ring (engine/paged.py twins) ----------
    @property
    def supports_ragged_fill(self) -> bool:
        """Ragged pool prefill on the pipeline mesh: same dp == 1 / family
        constraints as the rest of the paged fleet. The flat token axis is
        fleet-shaped (W rows of T=1 at per-token positions), so it rides
        the same gated microstep ring as paged slot decode — ungated
        microsteps redirect their block writes to the trash block through
        the ragged hook's update_gate, exactly like the decode hook."""
        return self.supports_paged

    def extend_ragged_paged(self, tokens, tok_row, tok_pos, meta, pool,
                            table, pages=None):
        mkey = ("extend_ragged_paged", pages is not None)
        fn = self._programs.get(mkey)
        if fn is None:
            fn = self._build_extend_ragged_paged(pages is not None)
            self._programs[mkey] = fn
        self._account_link(
            "pp-microstep-prefill", rows=int(tokens.shape[0]), t=1
        )
        args = [self.shared, self.layers, tokens, tok_row, tok_pos, meta,
                pool, table]
        if pages is not None:
            args.append(pages)
        return fn(*args)

    def _build_extend_ragged_paged(self, with_pages: bool = False):
        """shard_map twin of engine/paged.extend_ragged_paged: each of the
        S ring microsteps runs the local layer shard over the flat token
        fleet with the ragged fill hook; the pool is donated (updated in
        place), the table/metadata/adapter pages replicate."""
        cfg = self.cfg
        from ..engine import paged as EP
        from .partition import pool_spec

        def body(shared, layers, tokens, tok_row, tok_pos, meta, pool,
                 table, *extra):
            pages = extra[0] if with_pages else None
            hook = EP.make_ragged_fill_hook(table, meta, tok_row)
            x = embed_sharded(cfg, shared, tokens[:, None], tok_pos, self.pp)
            _, pool = self._microstep_loop(
                layers, x, pool, tok_pos, attn_hook=hook, attn_seq_len=1,
                lora_pages=EP._token_pages(pages, tok_row),
            )
            return pool

        specs = [
            self._shared_specs, self._layer_specs, P(), P(), P(), P(),
            pool_spec(cfg), P(),
        ]
        if with_pages:
            specs.append(P())
        shmapped = self._shard(
            body,
            in_specs=tuple(specs),
            out_specs=pool_spec(cfg),
        )
        return jax.jit(shmapped, donate_argnums=(6,))

    def prefill_ragged_paged(self, tokens, tok_row, tok_pos, meta, pool,
                             table, sample_at, key, sampling, presence=None,
                             bias=None, pages=None):
        pres = presence is not None
        wb = bias is not None
        wp = pages is not None
        mkey = ("prefill_ragged_paged", pres, wb, wp)
        fn = self._programs.get(mkey)
        if fn is None:
            fn = self._build_prefill_ragged_paged(pres, wb, wp)
            self._programs[mkey] = fn
        args = [self.shared, self.layers, tokens, tok_row, tok_pos, meta,
                pool, table, sample_at, key, sampling]
        if pres:
            args.append(presence)
        if wb:
            args.append(bias)
        if wp:
            args.append(pages)
        self._account_link(
            "pp-microstep-prefill", rows=int(tokens.shape[0]), t=1
        )
        self._account_link("pp-broadcast-prefill", rows=1)
        return fn(*args)

    def _build_prefill_ragged_paged(self, with_presence: bool,
                                    with_bias: bool,
                                    with_pages: bool = False):
        """Final ragged launch on the ring: after the microstep loop the
        real final-stage output sits on stage 0; the sampled flat position
        is sliced there, psum-broadcast, and unembedded through the vocab
        shards — the same replicated-logits sampling discipline as every
        other pp program, so tokens are identical on every device."""
        cfg, S = self.cfg, self.pp
        from ..engine import paged as EP
        from .partition import pool_spec

        def body(shared, layers, tokens, tok_row, tok_pos, meta, pool,
                 table, sample_at, key, sampling, *extra):
            i = 0
            presence = bias = pages = None
            if with_presence:
                presence = extra[i]
                i += 1
            if with_bias:
                bias = extra[i]
                i += 1
            if with_pages:
                pages = extra[i]
                i += 1
            hook = EP.make_ragged_fill_hook(table, meta, tok_row)
            s = jax.lax.axis_index(AXIS_PP)
            x = embed_sharded(cfg, shared, tokens[:, None], tok_pos, S)
            buf, pool = self._microstep_loop(
                layers, x, pool, tok_pos, attn_hook=hook, attn_seq_len=1,
                lora_pages=EP._token_pages(pages, tok_row),
            )
            last = jax.lax.dynamic_slice_in_dim(buf, sample_at, 1, axis=0)
            last = self._bcast(last, s == 0)  # [1, 1, D]
            logits = unembed_sharded(cfg, shared, last, S)[:, 0, :]
            first = sample_token(
                key, logits, *sampling, presence=presence, bias=bias
            )
            return first, logits, pool

        specs = [
            self._shared_specs, self._layer_specs, P(), P(), P(), P(),
            pool_spec(cfg), P(), P(), P(), P(),
        ]
        if with_presence:
            specs.append(P())
        if with_bias:
            specs.append(P())
        if with_pages:
            specs.append(P())
        shmapped = self._shard(
            body,
            in_specs=tuple(specs),
            out_specs=(P(), P(), pool_spec(cfg)),
        )
        return jax.jit(shmapped, donate_argnums=(6,))

    def arm_slot_paged(self, state, sparams, slot, *arm):
        # state/sparams are replicated — the shared jitted arm program
        # (engine/paged.arm_slot_only) runs on them directly, no shard_map
        from ..engine import paged as EP

        return EP.arm_slot_only(self.cfg, state, sparams, slot, *arm)

    # -- paged adapter pool writes (engine/adapters.AdapterPool seam) --------
    def write_adapter_page(self, page, updates):
        """shard_map twin of the single-device adapter page write: each
        host [L, ...] factor stack is padded/reordered to the ring's
        padded layer layout (partition.pad_stacked_layers — uneven pp
        splits put each stage's padding at its own tail), sharded like
        its buffer (parallel/partition.py lora specs), and written into
        `page` of the donated lora leaves. `page` is traced — loading
        into any page runs ONE compiled program per leaf set."""
        from .partition import pad_stacked_layers

        host = {}
        for leaf, (a, b) in updates.items():
            host[f"lora_{leaf}_a"] = jnp.asarray(a, self.cfg.jnp_dtype)
            host[f"lora_{leaf}_b"] = jnp.asarray(b, self.cfg.jnp_dtype)
        vals = pad_stacked_layers(self.cfg, host, self.pp)
        names = tuple(sorted(vals))
        mkey = ("adapter_write", names)
        fn = self._programs.get(mkey)
        if fn is None:
            fn = self._build_adapter_write(names)
            self._programs[mkey] = fn
        new = fn(
            {n: self.layers[n] for n in names}, jnp.int32(page), vals
        )
        self.layers.update(new)

    def _build_adapter_write(self, names):
        bspecs = {n: self._layer_specs[n] for n in names}
        vspecs = {
            n: P(*((tuple(s)[:1]) + tuple(s)[2:]))
            for n, s in bspecs.items()
        }

        def body(bufs, page, vals):
            return {n: bufs[n].at[:, page].set(vals[n]) for n in bufs}

        shmapped = self._shard(
            body, in_specs=(bspecs, P(), vspecs), out_specs=bspecs,
        )
        return jax.jit(shmapped, donate_argnums=(0,))

    def ragged_program_count(self) -> int:
        """Compiled ragged-ingest programs resident on this backend (the
        dli_ragged_compiled_programs gauge: flat after warmup = no
        per-tail recompile)."""
        return sum(
            1 for k in self._programs
            if isinstance(k, tuple) and k
            and k[0] in ("extend_ragged_paged", "prefill_ragged_paged")
        )

    # -- mixed scheduler step on the pp ring (engine/scheduler.py) -----------
    @property
    def supports_mixed_step(self) -> bool:
        """The chunked-prefill scheduler's mixed launch (decode rows +
        prefill chunks in one program): same dp == 1 / family constraints
        as the rest of the ragged paged fleet."""
        return self.supports_ragged_fill

    def mixed_step_ragged(self, tokens, tok_row, tok_pos, dec_flag, meta,
                          pool, table, state, sparams, key, dec_idx, arm,
                          spec=None, spec_toks=None, dev=None, pages=None):
        mkey = ("mixed_step_ragged", spec is not None,
                spec_toks is not None, dev is not None, pages is not None)
        fn = self._programs.get(mkey)
        if fn is None:
            fn = self._build_mixed_step_ragged(
                spec is not None, spec_toks is not None, dev is not None,
                pages is not None,
            )
            self._programs[mkey] = fn
        args = [self.shared, self.layers, tokens, tok_row, tok_pos,
                dec_flag, meta, pool, table, state, sparams, key,
                dec_idx, arm]
        if spec is not None:
            args.append(spec)
        if spec_toks is not None:
            args.append(spec_toks)
        if dev is not None:
            args.append(dev)
        if pages is not None:
            args.append(pages)
        self._account_link(
            "pp-microstep-prefill", rows=int(tokens.shape[0]), t=1
        )
        # two replicated-logits gathers (decode rows + arm positions),
        # plus the K+1 verify positions per slot on the spec variant
        bh = 2 + (int(spec.idx.shape[1]) if spec is not None else 0)
        self._account_link(
            "pp-broadcast-prefill", rows=int(dec_idx.shape[0]), bh=bh
        )
        return fn(*args)

    def _build_mixed_step_ragged(self, with_spec: bool = False,
                                 with_spec_toks: bool = False,
                                 with_dev: bool = False,
                                 with_pages: bool = False):
        """shard_map twin of engine/paged.mixed_step_ragged: the flat
        token fleet (decode rows gathered from the replicated slot state,
        prefill chunks from the host plan) runs the S ring microsteps
        with the ragged fill hook (ungated microsteps trash-redirect
        their pool writes); the decode and first-token positions are
        gathered off stage 0's real output, psum-broadcast, and
        unembedded through the vocab shards — then the SHARED
        engine/paged.mixed_epilogue advances/arm-s the slots, so tokens
        are identical on every device and cannot drift from the
        single-device program. The speculative variants (with_spec /
        with_spec_toks) gather the verify rows' positions through the
        same replicated-logits seam and run the SHARED
        engine/paged.spec_verify inside the epilogue — pp verify rows
        are token-identical to the single chip by construction. The
        with_dev variant applies the SHARED engine/paged.
        apply_device_meta substitution (decode/verify positions derived
        from the replicated slot state) before the hook sees the plan —
        device-derived metadata cannot drift across backends either."""
        cfg, S = self.cfg, self.pp
        from ..engine import paged as EP
        from ..engine.generate import SlotParams, SlotState
        from .partition import pool_spec

        def body(shared, layers, tokens, tok_row, tok_pos, dec_flag, meta,
                 pool, table, state, sparams, key, dec_idx, arm, *extra):
            spec = spec_toks = dev = pages = None
            i = 0
            if with_spec:
                spec = extra[i]
                i += 1
            if with_spec_toks:
                spec_toks = extra[i]
                i += 1
            if with_dev:
                dev = extra[i]
                i += 1
            if with_pages:
                pages = extra[i]
            if dev is not None:
                meta, tok_pos = EP.apply_device_meta(
                    meta, tok_row, tok_pos, dev, state.pos
                )
            hook = EP.make_ragged_fill_hook(table, meta, tok_row)
            s = jax.lax.axis_index(AXIS_PP)
            rows_ix = jnp.maximum(tok_row, 0)
            toks = jnp.where(dec_flag, state.token[rows_ix], tokens)
            if spec is not None and spec_toks is not None:
                # draft-model proposals scattered into the flat axis —
                # same drop-out-of-range recipe as the single device
                K = spec_toks.shape[1]
                jk = jnp.arange(K, dtype=jnp.int32)[None, :]
                want = spec.on[:, None] & (jk < spec.n_draft[:, None])
                tgt = jnp.where(
                    want, spec.idx[:, 1:], jnp.int32(toks.shape[0])
                )
                toks = toks.at[tgt.reshape(-1)].set(
                    spec_toks.reshape(-1), mode="drop"
                )
            pos = jnp.where(dec_flag, state.pos[rows_ix], tok_pos)
            x = embed_sharded(cfg, shared, toks[:, None], pos, S)
            buf, pool = self._microstep_loop(
                layers, x, pool, pos, attn_hook=hook, attn_seq_len=1,
                lora_pages=EP._token_pages(pages, tok_row),
            )

            def replicated_logits(idx):
                sel = buf[idx]  # [N, 1, D]
                sel = self._bcast(sel, s == 0)
                return unembed_sharded(cfg, shared, sel, S)[:, 0, :]

            sp_logits = sp_draft = None
            if spec is not None:
                B, K1 = spec.idx.shape
                sp_logits = replicated_logits(
                    spec.idx.reshape(-1)
                ).reshape(B, K1, -1)
                sp_draft = toks[spec.idx[:, 1:]]
            packed, state, sparams = EP.mixed_epilogue(
                cfg, state, sparams, replicated_logits(dec_idx),
                replicated_logits(arm.idx), key, arm,
                spec=spec, sp_logits=sp_logits, sp_draft=sp_draft,
            )
            return packed, state, sparams, pool

        state_specs = _replicated_specs(SlotState)
        sparam_specs = _replicated_specs(SlotParams)
        arm_specs = EP.MixedArm(
            P(), P(), P(), P(), _replicated_specs(SlotParams), P()
        )
        specs = [
            self._shared_specs, self._layer_specs, P(), P(), P(), P(),
            P(), pool_spec(cfg), P(), state_specs, sparam_specs, P(),
            P(), arm_specs,
        ]
        if with_spec:
            specs.append(EP.SpecPlan(P(), P(), P(), P()))
        if with_spec_toks:
            specs.append(P())
        if with_dev:
            specs.append(EP.DeviceMeta(P(), P(), P(), P()))
        if with_pages:
            specs.append(P())
        shmapped = self._shard(
            body,
            in_specs=tuple(specs),
            out_specs=(P(), state_specs, sparam_specs, pool_spec(cfg)),
        )
        return jax.jit(shmapped, donate_argnums=(7,))

    def _build_decode_slots_paged(self, num_steps: int,
                                  with_pages: bool = False):
        """Paged twin of _build_decode_slots: each of the S ring
        microsteps runs the local layer shard over the slot fleet with the
        paged attn_hook (engine/paged.make_paged_hook); pool writes are
        gated per microstep by redirecting ungated scatters to the trash
        block. Shares slot_step, so cross-backend/cross-mode token parity
        is structural."""
        cfg, S = self.cfg, self.pp
        from ..engine import paged as EP
        from ..engine.generate import SlotParams, SlotState, slot_step
        from .partition import pool_spec

        def body(shared, layers, state, pool, table, key, sparams, *extra):
            pages = extra[0] if with_pages else None
            hook = EP.make_paged_hook(table)
            bs = pool["k"].shape[3]
            MB = table.shape[1]
            s = jax.lax.axis_index(AXIS_PP)

            def step(carry, sub):
                state, pool = carry
                x = embed_sharded(
                    cfg, shared, state.token[:, None], state.pos, S
                )
                buf, pool = self._microstep_loop(
                    layers, x, pool, state.pos, attn_hook=hook,
                    attn_seq_len=MB * bs, lora_pages=pages,
                )
                last = self._bcast(buf[:, -1:, :], s == 0)
                logits = unembed_sharded(cfg, shared, last, S)[:, 0, :]
                new, emit, can_emit = slot_step(cfg, state, sparams, logits, sub)
                return (new, pool), (emit, can_emit)

            subs = jax.random.split(key, num_steps)
            (state, pool), (emitted, emit_mask) = jax.lax.scan(
                step, (state, pool), subs
            )
            return emitted, emit_mask, state, pool

        state_specs = _replicated_specs(SlotState)
        sparam_specs = _replicated_specs(SlotParams)
        specs = [
            self._shared_specs, self._layer_specs, state_specs,
            pool_spec(cfg), P(), P(), sparam_specs,
        ]
        if with_pages:
            specs.append(P())
        shmapped = self._shard(
            body,
            in_specs=tuple(specs),
            out_specs=(P(), P(), state_specs, pool_spec(cfg)),
        )
        return jax.jit(shmapped, donate_argnums=(3,))

    def _build_decode(self, max_steps: int, with_presence: bool = False):
        return self._build_decode_any(
            max_steps, ragged=False, with_presence=with_presence
        )

    def _build_decode_ragged(self, max_steps: int, with_presence: bool = False):
        return self._build_decode_any(
            max_steps, ragged=True, with_presence=with_presence
        )

    def _build_decode_full(self, max_steps: int, *, ragged: bool,
                           with_presence: bool, with_bias: bool,
                           with_logprobs: bool, with_counts: bool = False,
                           with_constraint: bool = False):
        # OpenAI logit_bias and per-token logprobs on the pp mesh (round-2
        # review #3: the full request surface on every topology) — the
        # logits are replicated after the vocab-shard all_gather, so both
        # reduce to the same local ops the single-device path runs
        return self._build_decode_any(
            max_steps, ragged=ragged, with_presence=with_presence,
            with_counts=with_counts, with_bias=with_bias,
            with_logprobs=with_logprobs, with_constraint=with_constraint,
        )

    def _build_decode_any(self, max_steps: int, *, ragged: bool,
                          with_presence: bool = False,
                          with_counts: bool = False,
                          with_bias: bool = False,
                          with_logprobs: bool = False,
                          with_constraint: bool = False):
        cfg, S = self.cfg, self.pp
        from ..engine.generate import fsm_advance, fsm_allowed

        def body(shared, layers, first_token, cache, start_pos, limit, key,
                 sampling, *extra):
            i = 0
            valid_start = presence0 = counts0 = bias = None
            fsm0 = cmask = ctrans = None
            if ragged:
                valid_start = extra[i]
                i += 1
            if with_presence:
                presence0 = extra[i]
                i += 1
            if with_counts:
                counts0 = extra[i]
                i += 1
            if with_bias:
                bias = extra[i]
                i += 1
            if with_constraint:
                fsm0, cmask, ctrans = extra[i: i + 3]
                i += 3
            s = jax.lax.axis_index(AXIS_PP)
            key = self._dp_key(key)
            B = first_token.shape[0]
            pad = jnp.int32(cfg.pad_token_id)
            out0 = jnp.full((B, max_steps), pad, jnp.int32)
            finished0 = stop_mask(cfg, first_token)
            pres0 = (
                presence0 if with_presence else jnp.zeros((B, 1), jnp.bool_)
            )
            cnt0 = counts0 if with_counts else jnp.zeros((B, 1), jnp.int32)
            lp0 = jnp.zeros((B, max_steps if with_logprobs else 1), jnp.float32)

            def cond(c):
                step, _, _, _, _, finished, _, _, _, _, _ = c[:11]
                return (step < limit) & ~jnp.all(finished)

            def step_fn(c):
                (step, token, pos, cache, key, finished, out, n_gen, pres,
                 cnt, lps) = c[:11]
                fsm = c[11] if with_constraint else None
                x = embed_sharded(cfg, shared, token[:, None], pos, S)
                buf, cache = self._microstep_loop(layers, x, cache, pos, valid_start)
                # broadcast stage 0's real [B, 1, D] output (a masked psum
                # of activations, NOT the [B, vocab] fp32 logits round-1
                # shipped), then every stage computes its vocab shard and
                # the all_gather'd logits are identical everywhere — so the
                # sampled token needs no further collective
                last = self._bcast(buf[:, -1:, :], s == 0)
                logits = unembed_sharded(cfg, shared, last, S)[:, 0, :]
                key, sub = jax.random.split(key)
                nxt = sample_token(
                    sub, logits, *sampling,
                    presence=pres if with_presence else None,
                    counts=cnt if with_counts else None,
                    bias=bias,
                    allowed=(
                        fsm_allowed(cmask, fsm) if with_constraint else None
                    ),
                )
                if with_presence:
                    pres = presence_update(pres, nxt)
                is_eos = stop_mask(cfg, nxt)
                newly = finished | is_eos
                if with_counts:
                    cnt = count_update(cnt, nxt, ~newly)
                emit = jnp.where(newly, pad, nxt)
                out = jax.lax.dynamic_update_slice(
                    out, emit[:, None], (jnp.int32(0), step)
                )
                if with_logprobs:
                    # raw-distribution logprob of the emitted token (the
                    # OpenAI convention — before temperature/filters/bias),
                    # same as engine/generate.decode's variant
                    logp = jax.nn.log_softmax(
                        logits.astype(jnp.float32), axis=-1
                    )
                    tok_lp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)
                    lps = jax.lax.dynamic_update_slice(
                        lps, tok_lp, (jnp.int32(0), step)
                    )
                n_gen = n_gen + (~newly).astype(jnp.int32)
                token = jnp.where(newly, pad, nxt)
                nc = (step + 1, token, pos + 1, cache, key, newly, out,
                      n_gen, pres, cnt, lps)
                if with_constraint:
                    nc = nc + (fsm_advance(ctrans, fsm, nxt, ~newly),)
                return nc

            init = (
                jnp.int32(0),
                jnp.where(finished0, pad, first_token),
                start_pos,
                cache,
                key,
                finished0,
                out0,
                jnp.zeros((B,), jnp.int32),
                pres0,
                cnt0,
                lp0,
            )
            if with_constraint:
                init = init + (fsm0,)
            final = jax.lax.while_loop(cond, step_fn, init)
            (_, _, _, cache, _, _, out, n_gen, _, _, lps) = final[:11]
            if with_logprobs:
                return out, n_gen, cache, lps
            return out, n_gen, cache

        specs = [
            self._shared_specs, self._layer_specs, P(AXIS_DP), cache_spec(self.cfg),
            P(), P(), P(), P(),
        ]
        if ragged:
            specs.append(P(AXIS_DP))
        if with_presence:
            specs.append(P(AXIS_DP))
        if with_counts:
            specs.append(P(AXIS_DP))
        if with_bias:
            specs.append(P())
        if with_constraint:
            # fsm [B] shards with the batch; the [S, V] tables replicate
            # (the gathers run on the replicated post-all_gather logits)
            specs.extend([P(AXIS_DP), P(), P()])
        out_specs = [P(AXIS_DP), P(AXIS_DP), cache_spec(self.cfg)]
        if with_logprobs:
            out_specs.append(P(AXIS_DP))
        shmapped = self._shard(
            body,
            in_specs=tuple(specs),
            out_specs=tuple(out_specs),
        )
        return jax.jit(shmapped, donate_argnums=(3,))

    # -- teacher-forced scoring / beam search over the pp ring --------------
    # (round-2 review #3: BASELINE configs 3-5 must serve the same request
    # surface as the single chip — score, logprobs, logit_bias, beams)
    supports_bias = True
    supports_logprobs = True
    supports_score = True
    supports_beam = True

    def score_chunk(self, tokens, pos, cache, *, top_n=0):
        fn = self._programs.get(("score", top_n))
        if fn is None:
            fn = self._build_score(top_n)
            self._programs[("score", top_n)] = fn
        B, T = int(tokens.shape[0]), int(tokens.shape[1])
        self._account_link("pp-microstep-prefill", rows=B, t=T)
        self._account_link("pp-broadcast-score", rows=B, t=T)
        return fn(self.shared, self.layers, tokens, pos, cache)

    def _build_score(self, top_n: int):
        """Chunked teacher-forced scoring (engine/generate.score_chunk) on
        the ring: run the chunk through the S microsteps, broadcast the
        final-stage [B, T, D] activations from stage 0, compute replicated
        logits from the vocab shards, then the SAME score_post tail as the
        single-device path — bit-consistent by construction."""
        cfg, S = self.cfg, self.pp
        from ..engine.generate import score_post

        def body(shared, layers, tokens, pos, cache):
            s = jax.lax.axis_index(AXIS_PP)
            x = embed_sharded(cfg, shared, tokens, pos, S)
            buf, cache = self._microstep_loop(layers, x, cache, pos)
            full = self._bcast(buf, s == 0)
            logits = unembed_sharded(cfg, shared, full, S)
            return score_post(logits, tokens, top_n) + (cache,)

        shmapped = self._shard(
            body,
            in_specs=(
                self._shared_specs, self._layer_specs, P(AXIS_DP), P(),
                cache_spec(self.cfg),
            ),
            out_specs=(
                P(AXIS_DP), P(AXIS_DP), P(AXIS_DP), P(AXIS_DP), cache_spec(self.cfg)
            ),
        )
        return jax.jit(shmapped, donate_argnums=(4,))

    @property
    def supports_speculative(self) -> bool:
        """Prompt-lookup speculation on the pp ring: one T=1+g verify
        forward costs the same S microsteps as a single token, so g
        accepted tokens amortize the batch-1 ring bubble g-fold — the
        speculation win is LARGER on a pipeline than on one chip. B=1
        only, so dp must be 1 (serving engines always are)."""
        return self.dp == 1

    def decode_speculative(self, first_token, cache, hist, hist_len, limit,
                           *, max_steps, draft_len):
        key_ = ("spec", max_steps, draft_len)
        fn = self._programs.get(key_)
        if fn is None:
            fn = self._build_speculative(max_steps, draft_len)
            self._programs[key_] = fn
        # upper bound: one [1, 1+G, D] verify window per spec cycle
        self._account_link(
            "pp-microstep-spec", rows=1, draft=draft_len, steps=max_steps
        )
        self._account_link(
            "pp-broadcast-spec", rows=1, draft=draft_len, steps=max_steps
        )
        return fn(
            self.shared, self.layers, first_token, cache, hist,
            jnp.int32(hist_len), jnp.int32(limit),
        )

    def _build_speculative(self, max_steps: int, draft_len: int):
        """engine/generate.spec_loop inside shard_map: the verify forward
        is ring microsteps + a masked psum of the [1, 1+G, D] window +
        vocab-shard logits; the n-gram matching / acceptance bookkeeping
        runs replicated on every device (identical logits in, identical
        argmaxes out)."""
        cfg, S = self.cfg, self.pp
        from ..engine.generate import spec_loop

        def body(shared, layers, first_token, cache, hist, hist_len, limit):
            s = jax.lax.axis_index(AXIS_PP)

            def fwd(tokens_in, cache, pos):
                x = embed_sharded(cfg, shared, tokens_in, pos, S)
                buf, cache = self._microstep_loop(layers, x, cache, pos)
                full = self._bcast(buf, s == 0)
                return unembed_sharded(cfg, shared, full, S), cache

            return spec_loop(
                cfg, fwd, first_token, cache, hist, hist_len, limit,
                max_steps=max_steps, draft_len=draft_len,
            )

        shmapped = self._shard(
            body,
            in_specs=(
                self._shared_specs, self._layer_specs, P(), cache_spec(self.cfg),
                P(), P(), P(),
            ),
            out_specs=(P(), P(), cache_spec(self.cfg)),
        )
        return jax.jit(shmapped, donate_argnums=(3,))

    @property
    def supports_draft(self) -> bool:
        """Two-model draft speculation on the pp ring (dp == 1, B=1)."""
        return self.dp == 1

    def decode_draft_speculative(self, dcfg, dparams, first_token, cache,
                                 dcache, start_pos, limit, *, max_steps,
                                 draft_len):
        key_ = ("draft", dcfg, max_steps, draft_len)
        fn = self._programs.get(key_)
        if fn is None:
            fn = self._build_draft_speculative(dcfg, max_steps, draft_len)
            self._programs[key_] = fn
        self._account_link(
            "pp-microstep-spec", rows=1, draft=draft_len, steps=max_steps
        )
        self._account_link(
            "pp-broadcast-spec", rows=1, draft=draft_len, steps=max_steps
        )
        return fn(
            self.shared, self.layers, dparams, first_token, cache, dcache,
            jnp.int32(start_pos), jnp.int32(limit),
        )

    def _build_draft_speculative(self, dcfg, max_steps: int, draft_len: int):
        """engine/generate.draft_spec_loop inside shard_map: the target
        verify forward is ring microsteps + masked psum + vocab-shard
        logits; the SMALL draft model runs fully replicated on every
        device (its params/cache enter with P() specs) — redundant
        compute, but far cheaper than scattering a model whose point is
        being tiny, and every device derives identical proposals."""
        cfg, S = self.cfg, self.pp
        from ..engine.generate import draft_spec_loop

        def body(shared, layers, dparams, first_token, cache, dcache,
                 start_pos, limit):
            s = jax.lax.axis_index(AXIS_PP)

            def fwd(tokens_in, cache, pos):
                x = embed_sharded(cfg, shared, tokens_in, pos, S)
                buf, cache = self._microstep_loop(layers, x, cache, pos)
                full = self._bcast(buf, s == 0)
                return unembed_sharded(cfg, shared, full, S), cache

            def dfwd(tok_11, dc, p):
                x = M.embed(dcfg, dparams, tok_11, p)
                x, dc = M.forward_layers(dcfg, dparams["layers"], x, dc, p)
                return M.unembed(dcfg, dparams, x), dc

            return draft_spec_loop(
                cfg, fwd, dfwd, first_token, cache, dcache, start_pos,
                limit, max_steps=max_steps, draft_len=draft_len,
            )

        # the draft's params/cache are replicated pytrees: a bare P() is a
        # valid PYTREE PREFIX spec covering every leaf
        shmapped = self._shard(
            body,
            in_specs=(
                self._shared_specs, self._layer_specs, P(), P(),
                cache_spec(self.cfg), P(), P(), P(),
            ),
            out_specs=(P(), P(), cache_spec(self.cfg), P()),
        )
        return jax.jit(shmapped, donate_argnums=(4, 5))

    def decode_beam(self, logits0, cache, start_pos, limit, length_penalty,
                    *, max_steps, num_beams, early_stopping):
        if self.dp > 1:
            # beams are one hypothesis set, not data shards: the in-program
            # top-k / cache reorder spans all rows, which a dp slice of the
            # batch axis cannot see (serving engines are dp=1 anyway)
            raise NotImplementedError("beam search needs dp == 1")
        key_ = ("beam", max_steps, num_beams, early_stopping)
        fn = self._programs.get(key_)
        if fn is None:
            fn = self._build_beam(max_steps, num_beams, early_stopping)
            self._programs[key_] = fn
        steps = min(limit, max_steps) if isinstance(limit, int) else max_steps
        self._account_slots_wire(num_beams, steps)
        return fn(
            self.shared, self.layers, logits0, cache, start_pos,
            jnp.int32(limit), jnp.float32(length_penalty),
        )

    def _build_beam(self, max_steps: int, num_beams: int,
                    early_stopping: bool):
        """HF-parity beam search on the pp ring: the entire algorithm is
        engine/generate.beam_loop — only the forward step differs (ring
        microsteps + masked psum + vocab-shard unembed). The beam
        bookkeeping runs replicated on every device (identical logits in,
        identical argsorts out), and each device reorders its own local KV
        shard by parent beam; dp must be 1 (the engine's serving meshes
        always are)."""
        cfg, S = self.cfg, self.pp
        from ..engine.generate import beam_loop

        def body(shared, layers, logits0, cache, start_pos, limit,
                 length_penalty):
            s = jax.lax.axis_index(AXIS_PP)

            def fwd(last, cache, pos):
                x = embed_sharded(cfg, shared, last, pos, S)
                buf, cache = self._microstep_loop(layers, x, cache, pos)
                lastb = self._bcast(buf[:, -1:, :], s == 0)
                logits = unembed_sharded(cfg, shared, lastb, S)[:, 0, :]
                return logits, cache

            return beam_loop(
                cfg, fwd, logits0, cache, start_pos, limit, length_penalty,
                max_steps=max_steps, num_beams=num_beams,
                early_stopping=early_stopping,
            )

        shmapped = self._shard(
            body,
            in_specs=(
                self._shared_specs, self._layer_specs, P(), cache_spec(self.cfg),
                P(), P(), P(),
            ),
            out_specs=(P(), P(), P(), cache_spec(self.cfg)),
        )
        return jax.jit(shmapped, donate_argnums=(3,))
