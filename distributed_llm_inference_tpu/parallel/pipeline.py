"""SPMD pipeline-parallel runtime: all stages in one compiled program.

This replaces the reference's entire distributed fabric — the orchestrator
POSTing JSON activations to worker Flask servers over ngrok tunnels, twice
per token (/root/reference/orchestration.py:114-137, Worker1.py:208-245) —
with a single `jax.shard_map` program over the `pp` mesh axis:

  * each device holds one stage: a contiguous shard of the stacked layer
    params and of the stacked KV cache (parallel/partition.py);
  * the activation hand-off is `lax.ppermute` over the ICI ring — the
    TPU-native form of the reference's HTTP hop (boundaries #2/#3 in
    SURVEY.md §3.1);
  * one microstep = every stage applies its layer shard to its current
    buffer, then the ring shifts; a stage's cache write is gated on the
    microstep owning it, so speculative compute on stale buffers is
    discarded at slice granularity;
  * after S microsteps the last stage's output has rotated to stage 0,
    which computes logits for the final position only; a masked `psum`
    broadcasts them so every device samples the SAME next token with the
    same key — the decode loop (`lax.while_loop`) then continues entirely
    on-device, with zero host round-trips per token.

Latency shape: batch-1 decode costs S microsteps/token (the classic
pipeline bubble — the whole model's FLOPs, just spread over stages);
microbatching (parallel.schedule) fills the bubble for batched configs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..config import ModelConfig
from ..engine.generate import SamplingParams
from ..models import api as M
from ..ops.sampling import sample_token
from .mesh import AXIS_DP, AXIS_PP, AXIS_TP
from .partition import cache_spec, init_sharded_cache, layer_specs, shard_params


def _ring_perm(S: int):
    return [(j, (j + 1) % S) for j in range(S)]


class SPMDBackendBase:
    """Shared scaffolding for the SPMD mesh backends.

    Owns the mesh-axis bookkeeping, parameter sharding, shard_map partial,
    per-max_steps decode-program memoization, dp key decorrelation, and the
    per-stage health report. Subclasses implement `_build_prefill()` and
    `_build_decode(max_steps)`.
    """

    name = "spmd-base"

    def __init__(self, cfg: ModelConfig, params: dict, mesh: Mesh):
        self.cfg = cfg
        self.mesh = mesh
        self.dp = int(mesh.shape.get(AXIS_DP, 1))
        self.pp = int(mesh.shape[AXIS_PP])
        self.tp = int(mesh.shape.get(AXIS_TP, 1))
        self.n_stages = self.pp
        self.tp_axis = AXIS_TP if self.tp > 1 else None
        self.shared, self.layers = shard_params(cfg, params, mesh)
        self._layer_specs = layer_specs(cfg, self.layers)
        self._shard = functools.partial(
            jax.shard_map, mesh=mesh, check_vma=False
        )
        self._prefill = self._build_prefill()
        self._decode_cache: dict[int, object] = {}

    # -- engine interface ---------------------------------------------------
    def init_cache(self, batch: int, max_seq: int):
        return init_sharded_cache(self.cfg, self.mesh, batch, max_seq)

    def prefill(self, tokens, prompt_len, cache, key, sampling):
        return self._prefill(
            self.shared, self.layers, tokens, prompt_len, cache, key, sampling
        )

    def decode(self, first_token, cache, start_pos, limit, key, sampling, *, max_steps):
        fn = self._decode_cache.get(max_steps)
        if fn is None:
            fn = self._build_decode(max_steps)
            self._decode_cache[max_steps] = fn
        # clamp: limit > max_steps would walk dynamic_update_slice off the
        # end of `out` (the start index clamps, corrupting the last column)
        # and inflate n_gen past the buffer
        limit = jnp.minimum(jnp.int32(limit), jnp.int32(max_steps))
        return fn(
            self.shared, self.layers, first_token, cache, start_pos, limit, key, sampling
        )

    def health(self) -> list[dict]:
        """Per-stage liveness — the reference's /workers sweep polls each
        worker's /health over HTTP (orchestration.py:306-329); here a stage
        is a mesh slice, so health = device presence per slice."""
        devs = self.mesh.devices  # [dp, pp, sp, tp]
        per = self.cfg.n_layers // self.pp
        return [
            {
                "stage": s,
                "devices": [str(d) for d in devs[:, s].reshape(-1)],
                "layers": list(range(s * per, (s + 1) * per)),
                "status": "online",
            }
            for s in range(self.pp)
        ]

    def _dp_key(self, key):
        """Decorrelate sampling across dp batch shards. dp=1 keeps the key
        untouched so the pipeline stays bit-identical to single-device."""
        if self.dp == 1:
            return key
        return jax.random.fold_in(key, jax.lax.axis_index(AXIS_DP))

    def _build_prefill(self):
        raise NotImplementedError

    def _build_decode(self, max_steps: int):
        raise NotImplementedError


class PipelineBackend(SPMDBackendBase):
    """Engine-compatible backend running (dp, pp, tp) SPMD over a mesh.

    Drop-in for SingleDeviceBackend (same init_cache/prefill/decode/health
    interface), so InferenceEngine and the serving layer are topology-
    agnostic — the reference needed three differently-coded processes for
    the same job (orchestration.py vs Worker1.py vs Worker2.py).

    Axes: `pp` stages hand activations around the ICI ring; `tp` shards
    heads/FFN within a stage (psums inside models/*.decoder_layer); `dp`
    shards the batch — each dp slice is an independent pipeline ring (its
    while-loop may even exit at a different step; no collective crosses dp).
    """

    name = "pipeline"

    # -- compiled programs --------------------------------------------------
    def _microstep_loop(self, layers, x, cache, pos):
        """S microsteps of (apply local stage, ring-shift). Returns the
        final-stage output (landed on stage 0 by the last shift) + cache."""
        cfg, S = self.cfg, self.pp
        s = jax.lax.axis_index(AXIS_PP)
        perm = _ring_perm(S)

        def micro(i, carry):
            buf, cache = carry
            gate = i == s
            y, cache = M.forward_layers(
                cfg, layers, buf, cache, pos, update_gate=gate,
                tp_axis=self.tp_axis,
            )
            buf = jax.lax.ppermute(y, AXIS_PP, perm)
            return buf, cache

        return jax.lax.fori_loop(0, S, micro, (x, cache))

    def _build_prefill(self):
        cfg, S = self.cfg, self.pp

        def body(shared, layers, tokens, prompt_len, cache, key, sampling):
            s = jax.lax.axis_index(AXIS_PP)
            key = self._dp_key(key)
            x = M.embed(cfg, shared, tokens, jnp.int32(0))
            buf, cache = self._microstep_loop(layers, x, cache, jnp.int32(0))
            last = jax.lax.dynamic_slice_in_dim(buf, prompt_len - 1, 1, axis=1)
            logits_local = M.unembed(cfg, shared, last)[:, 0, :]
            logits = jax.lax.psum(
                jnp.where(s == 0, logits_local, 0.0), AXIS_PP
            )
            first = sample_token(key, logits, *sampling)
            return first, logits, cache

        shmapped = self._shard(
            body,
            in_specs=(
                P(), self._layer_specs, P(AXIS_DP), P(), cache_spec(), P(), P(),
            ),
            out_specs=(P(AXIS_DP), P(AXIS_DP), cache_spec()),
        )
        return jax.jit(shmapped, donate_argnums=(4,))

    def _build_decode(self, max_steps: int):
        cfg, S = self.cfg, self.pp

        def body(shared, layers, first_token, cache, start_pos, limit, key, sampling):
            s = jax.lax.axis_index(AXIS_PP)
            key = self._dp_key(key)
            B = first_token.shape[0]
            pad = jnp.int32(cfg.pad_token_id)
            eos = jnp.int32(cfg.eos_token_id)
            out0 = jnp.full((B, max_steps), pad, jnp.int32)
            finished0 = first_token == eos

            def cond(c):
                step, _, _, _, _, finished, _, _ = c
                return (step < limit) & ~jnp.all(finished)

            def step_fn(c):
                step, token, pos, cache, key, finished, out, n_gen = c
                x = M.embed(cfg, shared, token[:, None], pos)
                buf, cache = self._microstep_loop(layers, x, cache, pos)
                logits_local = M.unembed(cfg, shared, buf[:, -1:, :])[:, 0, :]
                logits = jax.lax.psum(
                    jnp.where(s == 0, logits_local, 0.0), AXIS_PP
                )
                key, sub = jax.random.split(key)
                nxt = sample_token(sub, logits, *sampling)
                is_eos = nxt == eos
                newly = finished | is_eos
                emit = jnp.where(newly, pad, nxt)
                out = jax.lax.dynamic_update_slice(
                    out, emit[:, None], (jnp.int32(0), step)
                )
                n_gen = n_gen + (~newly).astype(jnp.int32)
                token = jnp.where(newly, pad, nxt)
                return step + 1, token, pos + 1, cache, key, newly, out, n_gen

            init = (
                jnp.int32(0),
                jnp.where(finished0, pad, first_token),
                start_pos,
                cache,
                key,
                finished0,
                out0,
                jnp.zeros((B,), jnp.int32),
            )
            _, _, _, cache, _, _, out, n_gen = jax.lax.while_loop(cond, step_fn, init)
            return out, n_gen, cache

        shmapped = self._shard(
            body,
            in_specs=(
                P(), self._layer_specs, P(AXIS_DP), cache_spec(), P(), P(),
                P(), P(),
            ),
            out_specs=(P(AXIS_DP), P(AXIS_DP), cache_spec()),
        )
        return jax.jit(shmapped, donate_argnums=(3,))
