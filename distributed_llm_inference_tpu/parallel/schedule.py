"""Microbatched pipeline schedule: zero-bubble round-robin decode.

BASELINE.json config 5 ("8-stage microbatched pipeline, batch=8, 1F1B
schedule") — the inference analogue of the training-side 1F1B schedule.
The plain `parallel.pipeline.PipelineBackend` keeps only one microbatch in
flight: during batch-1 decode every stage computes every microstep but only
1/S of that work is useful (the classic pipeline bubble — SURVEY.md §2's
"stage 1 idles while stage 0 computes", /root/reference/orchestration.py:
114-137, just hidden inside SPMD). Here the batch is split into
M >= n_stages microbatches that chase each other around the `pp` ring:

    microstep t:  stage s works on microbatch (t - s) mod M
                  stage 0 ingests microbatch  t        mod M
                  stage S-1's output (microbatch (t-S+1) mod M) rotates to
                  stage 0, where it is sampled and immediately re-embedded

With M == S, a microbatch's next token re-enters stage 0 on exactly the
microstep its previous token vacates it: in steady state every stage does
useful work on every microstep — the bubble is gone, and each microstep
moves 1/M of the batch instead of recomputing the whole batch on every
stage. Autoregressive dependencies are respected because a sequence's token
t+1 starts only after token t has been sampled (the round-trip around the
ring IS the dependency chain).

All of it is one compiled SPMD program (shard_map over the (dp, pp, sp,
tp, ep) mesh; `lax.fori_loop` over the prefill-ingest microsteps and
`lax.while_loop` over decode; `wire_ppermute` hand-off), with the
same gated-cache-write discipline as the plain pipeline: each stage's KV
write lands in the batch-row slice of the microbatch it currently holds,
and warmup/drain/finished microsteps are discarded at slice granularity.

Decode state (per device, uniform across the mesh): per-microbatch token,
position, finished mask, emit count. Stage 0's completed [b_m, 1, D]
output is broadcast with a masked `psum` over `pp`, each device computes
its vocab shard of the logits (parallel/vocab.py) and samples the
identical all_gather'd row with the shared key, so every device advances
identical state and the loop never leaves the device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..config import ModelConfig
from ..models import api as M
from ..ops.sampling import sample_token
from ..ops.wire_quant import wire_ppermute
from .mesh import AXIS_DP, AXIS_PP
from .partition import cache_spec
from ..engine.generate import stop_mask
from .pipeline import PipelineBackend, SPMDBackendBase, _ring_perm
from .vocab import embed_sharded, unembed_sharded


class MicrobatchPipelineBackend(PipelineBackend):
    """PipelineBackend specialization: fleet-shaped calls run 1F1B.

    Inherits the ENTIRE plain-ring surface — score, beam, logprobs,
    logit_bias, repetition/OAI penalties, prompt-lookup + draft
    speculation, slot decode, chunked prefill — from PipelineBackend
    (round-3 review #3: every topology serves the full request surface).
    The zero-bubble round-robin schedule is an OPTIMIZATION that kicks in
    for the calls it was built for: plain/ragged prefill+decode whose row
    count is a multiple of dp * n_microbatches (config 5's batched
    fleets). Everything else — solo rows, sampling-variant programs —
    dispatches to the inherited ring programs, which are bit-identical to
    the single-device backend; a solo request loses nothing, because with
    one sequence there is no second microbatch to fill the bubble with
    anyway (S microsteps/token on the plain ring vs M >= S in a padded
    fleet).

    Batch contract for the 1F1B path: rows are grouped
    [dp block][microbatch block][rows] and returned in the same order.

    RNG stream note: greedy decode is bit-identical everywhere
    (equivalence-tested). Stochastic FLEET sampling draws from a
    DIFFERENT but equally deterministic stream — per-(microbatch,
    emit-index) `fold_in` of the request key, because the round-robin
    schedule has no single sequential split chain to follow — so a fixed
    seed reproduces exactly on THIS backend but yields different draws
    than the sequential backends' split-per-step stream. Plain-ring
    dispatches (solo / variant programs) keep the sequential stream.
    """

    name = "pipeline-1f1b"
    # Ragged left-padded fleets (valid_start) thread through the llama
    # masks exactly like the plain pipeline — required for the engine's
    # generate_batch / queue-coalesced serving path (round-2 review #4).
    supports_ragged = True

    @property
    def batch_granularity(self) -> int:
        """Smallest row-count quantum this backend can decode: the engine
        pads fleets up to a multiple (and routes solo requests through the
        batched path)."""
        return self.dp * self.n_microbatches

    def __init__(
        self,
        cfg: ModelConfig,
        params: dict,
        mesh: Mesh,
        n_microbatches: int | None = None,
        return_prefill_logits: bool = False,
        wire_quant=None,
    ):
        pp = int(mesh.shape[AXIS_PP])
        self.n_microbatches = int(n_microbatches or pp)
        if self.n_microbatches < pp:
            raise ValueError(
                f"n_microbatches={self.n_microbatches} must be >= pp={pp}: "
                "a microbatch must vacate stage 0 before its next token returns"
            )
        # The engine only consumes prefill's sampled first tokens; carrying
        # a [Mb, b_m, vocab] fp32 logits accumulator through the prefill
        # loop costs ~0.5 GB per unit batch at a 128k vocab. Off by
        # default: prefill returns a zero-width [rows, 0] logits array and
        # each sample event psums one int32 per row instead of the full
        # vocab row. Parity tests opt in to get comparable logits.
        self.return_prefill_logits = bool(return_prefill_logits)
        super().__init__(cfg, params, mesh, wire_quant=wire_quant)
        # plain-ring variant programs get their own memo: the base
        # _decode_cache is keyed by (max_steps, flags) alone, which cannot
        # distinguish a fleet-shaped call (1F1B program) from a solo /
        # variant call (ring program) under the same flags
        self._ring_variants: dict = {}

    # -- engine interface ---------------------------------------------------
    # init_cache is inherited unconstrained: fleet-shaped caches feed the
    # 1F1B programs, any other row count (solo, beam hypotheses) feeds the
    # inherited plain-ring programs.

    def health(self) -> list[dict]:
        return [
            dict(stage, microbatches=self.n_microbatches)
            for stage in super().health()
        ]

    def _account_decode_wire(self, rows: int, steps: int):
        """Fleet-shaped dispatches run the 1F1B schedule: S - 1 + steps*M
        microsteps of one [b_m, 1, D] buffer per link + one broadcast
        per sample event. Non-fleet shapes fall back to the plain ring's
        accounting (matching decode()'s dispatch; the variant branch
        accounts for itself)."""
        if self.pp <= 1:
            return
        if rows % self.batch_granularity:
            return super()._account_decode_wire(rows, steps)
        b_m = rows // self.batch_granularity
        self._account_link("fleet-1f1b-decode", b_m=b_m, steps=steps)
        self._account_link("fleet-broadcast-decode", b_m=b_m, steps=steps)

    # -- schedule pieces ----------------------------------------------------
    def _stage_apply(self, layers, x, cache, pos_m, m_here, b_m, gate,
                     valid_start_m=None):
        """Run the local layer slice on microbatch `m_here`'s rows.

        The cache batch dim holds all M microbatches; slice out this
        microbatch's rows, scan the layers over them, write the slice back.
        XLA keeps the slice/update in place on the donated buffer.
        valid_start_m [b_m]: this microbatch's left-pad boundaries (ragged
        fleets), threaded into the attention mask like the plain pipeline.
        Tree-mapped so int8 caches (ops/kv_quant.KVQuant leaves: q
        [L, B, KV, S, Dh] + scales [L, B, KV, S]) slice/update per leaf —
        every leaf keeps batch at axis 1.
        """
        row0 = m_here * b_m
        sub = jax.tree.map(
            lambda c: jax.lax.dynamic_slice_in_dim(c, row0, b_m, axis=1),
            cache,
        )
        y, new = M.forward_layers(
            self.cfg, layers, x, sub, pos_m,
            update_gate=gate, tp_axis=self.tp_axis, ep_axis=self.ep_axis,
            valid_start=valid_start_m,
        )
        cache = jax.tree.map(
            lambda c, n: jax.lax.dynamic_update_slice_in_dim(c, n, row0, axis=1),
            cache, new,
        )
        return y, cache

    def _stage0_sample(self, shared, s, key, last, sampling):
        """Sample off stage 0's received buffer slice `last` [b_m, 1, D].

        Only stage 0 holds a completed last-stage output: a masked psum
        broadcasts the [b_m, 1, D] activation (not the [b_m, vocab]
        logits), each device computes its vocab shard
        (parallel/vocab.py), and the all_gather'd logits — identical
        everywhere — are sampled with the shared key. Returns
        (tok [b_m], logits [b_m, V]).
        """
        last = self._bcast(last, s == 0)
        logits = unembed_sharded(self.cfg, shared, last, self.pp)[:, 0, :]
        tok = sample_token(key, logits, *sampling)
        return tok, logits

    # -- prefill ------------------------------------------------------------
    def prefill(self, tokens, prompt_len, cache, key, sampling,
                valid_start=None, presence=None, bias=None):
        """Fleet-shaped plain calls run the 1F1B ingest schedule; solo
        rows and presence/bias variants run the inherited plain-ring
        program (bit-identical to PipelineBackend)."""
        rows = int(tokens.shape[0])
        fleet = (
            rows % self.batch_granularity == 0
            and presence is None and bias is None
        )
        if not fleet:
            return self._prefill_any(
                tokens, jnp.int32(0), prompt_len, cache, key, sampling,
                valid_start, presence, bias,
            )
        # static wire accounting for the 1F1B ingest: M + S - 1
        # microsteps of one [b_m, bucket, D] buffer per link + one
        # sampled-window broadcast per microbatch
        b_m = rows // self.batch_granularity
        self._account_link(
            "fleet-1f1b-prefill", b_m=b_m, t=int(tokens.shape[1])
        )
        self._account_link("fleet-broadcast-prefill", b_m=b_m)
        if valid_start is None:
            return self._prefill(
                self.shared, self.layers, tokens, prompt_len, cache, key,
                sampling,
            )
        fn = self._programs.get("prefill_1f1b_ragged")
        if fn is None:
            fn = self._build_prefill_impl(ragged=True)
            self._programs["prefill_1f1b_ragged"] = fn
        return fn(
            self.shared, self.layers, tokens, prompt_len, cache, key,
            sampling, valid_start,
        )

    def _build_prefill(self):
        return self._build_prefill_impl(ragged=False)

    def _build_prefill_impl(self, *, ragged: bool):
        cfg, S, Mb = self.cfg, self.pp, self.n_microbatches
        perm = _ring_perm(S)
        with_logits = self.return_prefill_logits

        def body(shared, layers, tokens, prompt_len, cache, key, sampling,
                 *extra):
            s = jax.lax.axis_index(AXIS_PP)
            key = self._dp_key(key)
            rows, bucket = tokens.shape
            b_m = rows // Mb
            toks = tokens.reshape(Mb, b_m, bucket)
            # ragged fleets: per-microbatch left-pad boundaries [Mb, b_m]
            vs = extra[0].reshape(Mb, b_m) if ragged else None
            D = shared["embed"].shape[-1]
            dt = cfg.jnp_dtype

            def micro(t, carry):
                buf, cache, first, logits_acc = carry
                # ingest: stage 0 embeds microbatch t's prompt (clamped so
                # drain microsteps re-embed a stale microbatch — gated off)
                m_in = jnp.clip(t, 0, Mb - 1)
                x_in = embed_sharded(cfg, shared, toks[m_in], jnp.int32(0), S)
                x = jnp.where(s == 0, x_in, buf)
                m_here = jnp.mod(t - s, Mb)
                gate = (t >= s) & (t - s < Mb)
                y, cache = self._stage_apply(
                    layers, x, cache, jnp.int32(0), m_here, b_m, gate,
                    valid_start_m=None if vs is None else vs[m_here],
                )
                # microbatch hand-off: int8 + per-token-row scales when
                # pp_wire_quant is on (quant=False IS lax.ppermute)
                buf = wire_ppermute(y, AXIS_PP, perm, quant=self._wire_ring)
                # sample: microbatch (t-S+1) finished all stages and just
                # rotated onto stage 0
                m_done = jnp.mod(t - (S - 1), Mb)
                ev = (t >= S - 1) & (t - (S - 1) < Mb)
                last = jax.lax.dynamic_slice_in_dim(buf, prompt_len - 1, 1, axis=1)
                kk = jax.random.fold_in(key, m_done)
                tok, lg = self._stage0_sample(shared, s, kk, last, sampling)
                if with_logits:
                    # parity/debug path: accumulate the full vocab rows
                    old_l = jax.lax.dynamic_slice_in_dim(logits_acc, m_done, 1, axis=0)
                    logits_acc = jax.lax.dynamic_update_slice_in_dim(
                        logits_acc, jnp.where(ev, lg[None], old_l), m_done, axis=0
                    )
                old_f = jax.lax.dynamic_slice_in_dim(first, m_done, 1, axis=0)
                first = jax.lax.dynamic_update_slice_in_dim(
                    first, jnp.where(ev, tok[None], old_f), m_done, axis=0
                )
                return buf, cache, first, logits_acc

            V_out = cfg.vocab_size if with_logits else 0
            init = (
                jnp.zeros((b_m, bucket, D), dt),
                cache,
                jnp.zeros((Mb, b_m), jnp.int32),
                jnp.zeros((Mb, b_m, V_out), jnp.float32),
            )
            _, cache, first, logits = jax.lax.fori_loop(0, Mb + S - 1, micro, init)
            return first.reshape(rows), logits.reshape(rows, V_out), cache

        specs = [
            self._shared_specs, self._layer_specs, P(AXIS_DP), P(),
            cache_spec(self.cfg), P(), P(),
        ]
        if ragged:
            specs.append(P(AXIS_DP))
        shmapped = self._shard(
            body,
            in_specs=tuple(specs),
            out_specs=(P(AXIS_DP), P(AXIS_DP), cache_spec(self.cfg)),
        )
        return jax.jit(shmapped, donate_argnums=(4,))

    # -- decode -------------------------------------------------------------
    def decode(self, first_token, cache, start_pos, limit, key, sampling,
               valid_start=None, presence=None, counts=None, bias=None,
               constraint=None, *, max_steps, with_logprobs=False):
        """Shape-aware dispatch. Fleet-shaped plain/ragged calls (rows a
        multiple of dp*M, no variant extras) run the zero-bubble 1F1B
        schedule; every other call — solo rows, presence/counts/bias/
        constraint/logprobs variants — runs the inherited plain-ring
        program from PipelineBackend (correct and bit-identical to
        single-device, at the plain ring's bubble cost — the variant
        paths are the rare ones)."""
        rows = int(first_token.shape[0])
        extras = (
            presence is not None or counts is not None or bias is not None
            or constraint is not None or with_logprobs
        )
        if rows % self.batch_granularity == 0 and not extras:
            return super().decode(
                first_token, cache, start_pos, limit, key, sampling,
                valid_start=valid_start, max_steps=max_steps,
            )
        # variant fallback runs the inherited plain-ring programs —
        # account those bytes, not the 1F1B schedule's
        steps = min(limit, max_steps) if isinstance(limit, int) else max_steps
        SPMDBackendBase._account_decode_wire(self, rows, steps)
        return self._decode_dispatch(
            self._ring_variants, self._ring_builder, first_token, cache,
            start_pos, limit, key, sampling, valid_start, presence, counts,
            bias, constraint, max_steps=max_steps,
            with_logprobs=with_logprobs,
        )

    def _ring_builder(self, variant):
        """Plain-ring programs for the non-fleet dispatch — bypasses this
        class's 1F1B _build_decode/_build_decode_ragged overrides."""
        max_steps, ragged, pres, wc, wb, wcn, with_logprobs = variant
        if wb or with_logprobs or wc or wcn:
            kw = {"with_constraint": True} if wcn else {}
            return self._build_decode_full(
                max_steps, ragged=ragged, with_presence=pres,
                with_counts=wc, with_bias=wb, with_logprobs=with_logprobs,
                **kw,
            )
        return self._build_decode_any(
            max_steps, ragged=ragged, with_presence=pres
        )

    def _build_decode(self, max_steps: int, with_presence: bool = False):
        if with_presence:
            # unreachable via decode() (presence routes to the plain ring
            # before the base dispatch), kept as a correct fallback for
            # direct builder calls
            return self._build_decode_any(
                max_steps, ragged=False, with_presence=True
            )
        return self._build_decode_impl(max_steps, ragged=False)

    def _build_decode_ragged(self, max_steps: int, with_presence: bool = False):
        if with_presence:
            return self._build_decode_any(
                max_steps, ragged=True, with_presence=True
            )
        return self._build_decode_impl(max_steps, ragged=True)

    def _build_decode_impl(self, max_steps: int, *, ragged: bool):
        cfg, S, Mb = self.cfg, self.pp, self.n_microbatches
        perm = _ring_perm(S)
        pad = jnp.int32(cfg.pad_token_id)

        def body(shared, layers, first_token, cache, start_pos, limit, key,
                 sampling, *extra):
            s = jax.lax.axis_index(AXIS_PP)
            key = self._dp_key(key)
            rows = first_token.shape[0]
            b_m = rows // Mb
            vs = extra[0].reshape(Mb, b_m) if ragged else None
            D = shared["embed"].shape[-1]
            dt = cfg.jnp_dtype

            finished0 = stop_mask(cfg, first_token).reshape(Mb, b_m)
            cur0 = jnp.where(finished0, pad, first_token.reshape(Mb, b_m))
            done0 = jnp.all(finished0, axis=1) | (limit <= 0)

            # carry: t, buf, cache, cur [Mb,b_m], pos [Mb], finished [Mb,b_m],
            #        done [Mb], emitted [Mb], out [Mb,b_m,max], n_gen [Mb,b_m]
            def cond(c):
                t = c[0]
                done = c[6]
                return (t < S - 1 + limit * Mb) & ~jnp.all(done)

            def micro(c):
                t, buf, cache, cur, pos, finished, done, emitted, out, n_gen = c
                # ingest: stage 0 embeds microbatch (t mod M)'s current token
                # at its current position
                m_in = jnp.mod(t, Mb)
                x_in = embed_sharded(cfg, shared, cur[m_in][:, None], pos[m_in], S)
                x = jnp.where(s == 0, x_in, buf)
                # apply local stage to the microbatch it holds
                m_here = jnp.mod(t - s, Mb)
                gate = (t >= s) & ~done[m_here]
                y, cache = self._stage_apply(
                    layers, x, cache, pos[m_here], m_here, b_m, gate,
                    valid_start_m=None if vs is None else vs[m_here],
                )
                buf = wire_ppermute(y, AXIS_PP, perm, quant=self._wire_ring)
                # sample event: microbatch (t-S+1) completed a ring pass
                m_done = jnp.mod(t - (S - 1), Mb)
                ev = (t >= S - 1) & ~done[m_done]
                kk = jax.random.fold_in(
                    jax.random.fold_in(key, m_done), emitted[m_done]
                )
                tok, _ = self._stage0_sample(shared, s, kk, buf[:, -1:, :], sampling)
                fin_m = finished[m_done]
                newly = fin_m | stop_mask(cfg, tok)
                emit = jnp.where(newly, pad, tok)
                # gated per-microbatch state updates (uniform across devices)
                old_row = jax.lax.dynamic_slice(
                    out, (m_done, jnp.int32(0), emitted[m_done]), (1, b_m, 1)
                )
                out = jax.lax.dynamic_update_slice(
                    out,
                    jnp.where(ev, emit[None, :, None], old_row),
                    (m_done, jnp.int32(0), emitted[m_done]),
                )
                upd = lambda arr, val: jax.lax.dynamic_update_slice_in_dim(
                    arr,
                    jnp.where(
                        ev, val, jax.lax.dynamic_slice_in_dim(arr, m_done, 1, axis=0)
                    ),
                    m_done, axis=0,
                )
                n_gen = upd(n_gen, (n_gen[m_done] + (~newly).astype(jnp.int32))[None])
                cur = upd(cur, jnp.where(newly, pad, tok)[None])
                pos = upd(pos, (pos[m_done] + 1)[None])
                finished = upd(finished, newly[None])
                new_emitted = emitted[m_done] + 1
                done_now = jnp.all(newly) | (new_emitted >= limit)
                emitted = upd(emitted, new_emitted[None])
                done = upd(done, done_now[None])
                return t + 1, buf, cache, cur, pos, finished, done, emitted, out, n_gen

            init = (
                jnp.int32(0),
                jnp.zeros((b_m, 1, D), dt),
                cache,
                cur0,
                jnp.broadcast_to(start_pos, (Mb,)).astype(jnp.int32),
                finished0,
                done0,
                jnp.zeros((Mb,), jnp.int32),
                jnp.full((Mb, b_m, max_steps), pad, jnp.int32),
                jnp.zeros((Mb, b_m), jnp.int32),
            )
            c = jax.lax.while_loop(cond, micro, init)
            _, _, cache, _, _, _, _, _, out, n_gen = c
            return out.reshape(rows, max_steps), n_gen.reshape(rows), cache

        specs = [
            self._shared_specs, self._layer_specs, P(AXIS_DP), cache_spec(self.cfg),
            P(), P(), P(), P(),
        ]
        if ragged:
            specs.append(P(AXIS_DP))
        shmapped = self._shard(
            body,
            in_specs=tuple(specs),
            out_specs=(P(AXIS_DP), P(AXIS_DP), cache_spec(self.cfg)),
        )
        return jax.jit(shmapped, donate_argnums=(3,))


# -- MPMD glue (pure, host-side) ---------------------------------------------
#
# The multi-process MPMD runtime (serving/stage_runtime.py) reuses the
# 1F1B intuition above but spans PROCESSES, not shard_map shards: each
# stage process owns a contiguous layer slice and the controller drives
# microbatches through them over the stage transport. These helpers are
# the pure planning half — unit-testable with no jax in sight.

def plan_stages(n_layers: int, n_stages: int) -> list:
    """Contiguous [lo, hi) layer ranges for each of `n_stages` stages.

    Remainder layers go to the EARLIEST stages (stage 0 also pays the
    embed, but the alternative — loading the tail stage, which already
    owns final_norm + lm_head — is strictly worse)."""
    if not 1 <= n_stages <= n_layers:
        raise ValueError(
            f"need 1 <= n_stages ({n_stages}) <= n_layers ({n_layers})"
        )
    base, rem = divmod(n_layers, n_stages)
    ranges, lo = [], 0
    for s in range(n_stages):
        hi = lo + base + (1 if s < rem else 0)
        ranges.append((lo, hi))
        lo = hi
    return ranges


def mpmd_1f1b_order(n_stages: int, n_microbatches: int) -> list:
    """The 1F1B wavefront as an explicit event list: [(tick, stage,
    microbatch), ...] such that microbatch m hits stage s at tick m + s.

    Properties the runtime (and tests) rely on:
      * per-stage order is FIFO in microbatch id — so a stage worker
        draining a queue in arrival order IS this schedule;
      * stage s+1 sees microbatch m strictly after stage s does — the
        dependency chain is the tick ordering;
      * makespan is n_microbatches + n_stages - 1 ticks (the classic
        fill-drain trapezoid)."""
    if n_stages < 1 or n_microbatches < 1:
        raise ValueError("n_stages and n_microbatches must be >= 1")
    events = [
        (m + s, s, m)
        for m in range(n_microbatches)
        for s in range(n_stages)
    ]
    events.sort()
    return events
