"""Model / engine / mesh configuration.

Replaces the reference's hand-edited module constants (MODEL_NAME / LAYER_START /
LAYER_END / WORKER_*_URL, /root/reference/Worker1.py:26-31,
/root/reference/orchestration.py:20-24) with dataclass configs: the layer ranges
per pipeline stage are *computed* from (n_layers, pp_stages) instead of pasted by
hand, and the mesh shape replaces the manual URL wiring.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters for a decoder-only causal LM.

    Covers the Llama family (RMSNorm + RoPE + GQA + SwiGLU: TinyLlama,
    Llama-2-7B/13B, Llama-3-8B) and the GPT-2 family (LayerNorm + learned
    positions + MHA + gelu_new, tied embeddings).
    """

    name: str = "tinyllama-1.1b"
    arch: str = "llama"  # "llama" | "gpt2"
    vocab_size: int = 32000
    dim: int = 2048
    n_layers: int = 22
    n_heads: int = 32
    n_kv_heads: int = 4  # GQA; == n_heads for MHA
    ffn_dim: int = 5632
    max_seq_len: int = 2048
    norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    # RoPE frequency scaling (Llama-3.1/3.2-style "llama3" rope_scaling):
    # HF applies it to the inverse frequencies unconditionally — including
    # positions below the original context — so checkpoints trained with it
    # produce wrong logits at EVERY position unless it is reproduced.
    # None = plain RoPE.
    rope_scaling: Optional[str] = None  # None | "llama3" | "linear"
    rope_scaling_factor: float = 8.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_len: int = 8192
    # Gemma-3 dual RoPE: sliding-window layers use their own (local)
    # theta with NO scaling; full-attention layers use rope_theta (+ any
    # rope_scaling). None = one table for every layer.
    rope_local_theta: Optional[float] = None
    # Sliding-window attention (Mistral-style): a query attends only the
    # last `attn_window` positions. None = full causal.
    attn_window: Optional[int] = None
    # Which layers use the sliding window: "all" (Mistral) or "even"
    # (Gemma-2: even-indexed layers slide, odd attend fully — the stacked
    # layer params carry a per-layer window_flag so pipeline stages keep
    # their own slice's pattern).
    attn_window_pattern: str = "all"
    # Explicit per-layer pattern (Gemma-3's 5 sliding : 1 full): tuple of
    # n_layers ints, 1 = sliding-window layer, 0 = full attention.
    # Overrides attn_window_pattern when set.
    attn_window_layer_types: Optional[tuple] = None
    # Gemma-family knobs (all default off => plain Llama semantics):
    # explicit head_dim (Gemma-7B: 16 heads x 256 != dim 3072)
    head_dim_override: Optional[int] = None
    # RMSNorm multiplies by (1 + weight) (HF GemmaRMSNorm)
    norm_unit_offset: bool = False
    # MLP activation on the gate projection
    act: str = "silu"  # "silu" | "gelu_tanh"
    # scale embeddings by sqrt(dim) after lookup (GemmaModel normalizer)
    embed_scale: bool = False
    # Granite scalar multipliers (all None = off): embeddings scale by
    # embed_multiplier; every sublayer output scales by residual_multiplier
    # before its residual add; attention scores use attn_scale_override as
    # a DIRECT multiplier (not a head_dim power); logits divide by
    # logits_divider.
    embed_multiplier: Optional[float] = None
    residual_multiplier: Optional[float] = None
    attn_scale_override: Optional[float] = None
    logits_divider: Optional[float] = None
    # Gemma-2 sandwich norms: post-attention and post-FFN RMSNorms applied
    # to each branch output before its residual add
    post_norms: bool = False
    # Gemma-2 logit softcapping: x -> cap * tanh(x / cap)
    attn_softcap: Optional[float] = None
    final_softcap: Optional[float] = None
    # Gemma-2 query_pre_attn_scalar: attention scores scale by its -0.5
    # power instead of head_dim**-0.5 (None = head_dim**-0.5)
    query_scale_override: Optional[float] = None
    # Biases on the q/k/v projections (Qwen2-style; llama family only —
    # gpt2 always has full biases).
    attn_qkv_bias: bool = False
    # Qwen3: per-head RMSNorm on q and k (weight [head_dim]) before RoPE
    use_qk_norm: bool = False
    # qk-norm granularity: "head" (weight [head_dim], Qwen3/Gemma-3) or
    # "proj" (weight [H*Dh] / [KV*Dh] over the whole projection, OLMo-2)
    qk_norm_dim: str = "head"
    # OLMo-2: NO pre-sublayer norms — the residual adds norm(sublayer(x))
    # (post_norms carries the norms; pre_norms=False skips the input ones)
    pre_norms: bool = True
    # MoE router: renormalize the top-k probabilities to sum 1 (Mixtral
    # always does; Qwen3-MoE gates it on norm_topk_prob)
    moe_renormalize: bool = True
    # Sparse mixture-of-experts FFN (Mixtral-style): n_experts == 0 means a
    # dense SwiGLU MLP; > 0 replaces it with a top-k routed expert bank
    # (models/llama.moe_ffn). Expert weights stack an E axis and shard
    # over the `ep` mesh axis.
    n_experts: int = 0
    n_experts_per_tok: int = 2
    tie_embeddings: bool = False
    # GPT-2 only: learned absolute position embeddings.
    use_learned_pos: bool = False
    dtype: str = "float32"  # parameter / activation dtype: "float32" | "bfloat16"
    # Three quantization knobs, one per byte stream (the first two live
    # here; the third is a TRANSPORT property, so it lives on
    # EngineConfig.pp_wire_quant beside the other engine-level levers):
    #   quant         — weight HBM bytes (the batch-1 decode bound)
    #   kv_quant      — KV-cache HBM bytes (the context/slot-count bound)
    #   pp_wire_quant — inter-stage ICI bytes (the deep-pipeline bound)
    # Weight-only quantization of the matmul weights (ops/quant.py):
    # None | "int8" | "int4". int8 halves decode's HBM bytes/token
    # (~1.6x measured on v5e); int4 halves them again (packed nibbles,
    # group-wise scales). Both families; works on the single device AND
    # the SPMD mesh backends (quantized leaves shard like their weights).
    quant: Optional[str] = None
    # KV-CACHE quantization (ops/kv_quant.py): "int8" stores K/V as int8
    # with per-(token, head) fp32 scales — half the cache HBM, 2x the
    # slots/context at the same budget. Both families via the shared
    # attn_hook seam, on EVERY topology — single device, pp/tp/dp
    # pipeline meshes, the 1F1B schedule (per-leaf cache specs +
    # tree-aware row slicing), and sp rings (the ring/cp hooks quantize
    # on write and rotate int8 chunks + scales over ICI). Composes with
    # the prefix KV cache (snapshots carry the scales), the paged block
    # pool (int8 blocks + scale blocks), warm recovery (shadowed KVQuant
    # leaves), and attn_impl="pallas" (the flash/paged kernels
    # dequantize int8 tiles/blocks in their prologues).
    kv_quant: Optional[str] = None
    # Attention implementation: "xla" (einsum + full mask, fused by XLA) or
    # "pallas" (flash kernel, ops/flash_attention.py; interpret-mode on CPU).
    attn_impl: str = "xla"
    eos_token_id: int = 2
    bos_token_id: int = 1
    pad_token_id: int = 0
    # Additional stop tokens beyond eos_token_id (e.g. Gemma-it's
    # <end_of_turn> id 107 — instruct checkpoints end their turn with it
    # and rarely emit <eos> mid-chat). Every decode loop stops on any of
    # them; the comparison unrolls statically (the tuple is tiny).
    stop_token_ids: tuple = ()
    # Chat prompt template (engine/chat.py): None derives from arch
    # (llama -> "tinyllama" Zephyr format, gpt2 -> passthrough);
    # "gemma" = <start_of_turn> turns.
    chat_template: Optional[str] = None

    def __post_init__(self):
        if self.attn_impl not in ("xla", "pallas"):
            raise ValueError(f"attn_impl must be 'xla' or 'pallas', got {self.attn_impl!r}")
        if self.act not in ("silu", "gelu_tanh"):
            raise ValueError(f"act must be 'silu' or 'gelu_tanh', got {self.act!r}")
        # "hf": render chat through the serving tokenizer's own jinja
        # template (requires an HF tokenizer with one; the engine checks)
        if self.chat_template not in (None, "tinyllama", "gemma", "phi3",
                                      "none", "hf"):
            raise ValueError(
                f"chat_template must be None, 'tinyllama', 'gemma', 'phi3', "
                f"'none', or 'hf', got {self.chat_template!r}"
            )
        if self.qk_norm_dim not in ("head", "proj"):
            raise ValueError(
                f"qk_norm_dim must be 'head' or 'proj', got "
                f"{self.qk_norm_dim!r}"
            )
        if not self.pre_norms and not self.post_norms:
            raise ValueError(
                "pre_norms=False needs post_norms=True (a block with no "
                "norms at all matches no supported architecture)"
            )
        if self.attn_window_pattern not in ("all", "even"):
            raise ValueError(
                f"attn_window_pattern must be 'all' or 'even', got "
                f"{self.attn_window_pattern!r}"
            )
        # attn_impl='pallas' is legal for every attention variant now:
        # BOTH kernels (the chunk flash kernel, ops/flash_attention.py,
        # and the paged decode kernel, ops/paged_attention.py) take
        # softcap and scale overrides as static params and per-layer
        # window patterns as a traced scalar-prefetch width.
        if self.quant not in (None, "int8", "int4"):
            raise ValueError(
                f"quant must be None, 'int8', or 'int4', got {self.quant!r}"
            )
        if self.kv_quant not in (None, "int8"):
            raise ValueError(
                f"kv_quant must be None or 'int8', got {self.kv_quant!r}"
            )
        # kv_quant rides the shared attn_hook seam (models/llama.
        # default_attn_hook), which BOTH families route through now —
        # gpt2's block adopted the hook in round 5, so the int8 cache
        # (and the paged pool) apply to it unchanged.
        if self.rope_scaling not in (None, "llama3", "linear"):
            raise ValueError(
                f"rope_scaling must be None, 'llama3', or 'linear', got "
                f"{self.rope_scaling!r}"
            )
        if self.attn_window_layer_types is not None:
            if len(self.attn_window_layer_types) != self.n_layers:
                raise ValueError(
                    f"attn_window_layer_types has "
                    f"{len(self.attn_window_layer_types)} entries for "
                    f"{self.n_layers} layers"
                )
            if self.attn_window is None:
                raise ValueError(
                    "attn_window_layer_types needs attn_window set"
                )
        if self.rope_local_theta is not None and (
            self.attn_window is None
            or (self.attn_window_pattern == "all"
                and self.attn_window_layer_types is None)
        ):
            raise ValueError(
                "rope_local_theta needs a per-layer window pattern "
                "(attn_window_layer_types or attn_window_pattern='even') — "
                "with one table per layer kind there must be two kinds"
            )
        if self.arch == "gpt2" and self.n_kv_heads != self.n_heads:
            raise ValueError(
                f"gpt2 is MHA: n_kv_heads ({self.n_kv_heads}) must equal "
                f"n_heads ({self.n_heads})"
            )
        if self.n_heads % self.n_kv_heads != 0:
            raise ValueError(
                f"n_heads ({self.n_heads}) must be divisible by n_kv_heads "
                f"({self.n_kv_heads})"
            )
        if self.n_experts:
            if self.arch != "llama":
                raise ValueError("MoE (n_experts > 0) is llama-family only")
            if not 1 <= self.n_experts_per_tok <= self.n_experts:
                raise ValueError(
                    f"n_experts_per_tok ({self.n_experts_per_tok}) must be in "
                    f"[1, n_experts={self.n_experts}]"
                )

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.dim // self.n_heads

    @property
    def all_stop_ids(self) -> tuple:
        """eos + extra stop tokens, for host-side stop checks."""
        return (self.eos_token_id,) + tuple(self.stop_token_ids)

    @property
    def query_scale(self) -> float:
        """Attention score scale (Gemma-2 overrides head_dim**-0.5 with
        query_pre_attn_scalar**-0.5; Granite's attention_multiplier is a
        direct multiplier)."""
        if self.attn_scale_override is not None:
            return float(self.attn_scale_override)
        base = self.query_scale_override or self.head_dim
        return float(base) ** -0.5

    @property
    def jnp_dtype(self):
        return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[self.dtype]

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Shape of the device mesh. Axes: data, pipeline, sequence, tensor.

    The reference's topology (orchestrator + 2 HTTP workers) maps to
    pp_stages=2; here any (dp, pp, sp, tp) factorization of the available
    devices is valid as long as pp <= n_layers (uneven splits are padded
    with zero no-op layers), n_kv_heads % tp == 0,
    and (for sp > 1) the prefill bucket % sp == 0. sp is the long-context
    axis: ring-attention prefill + context-parallel KV-cache decode
    (parallel/ring.py, parallel/context.py).
    """

    dp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    # expert parallelism: shards the MoE expert bank (ModelConfig.n_experts
    # % ep == 0); every device computes its local experts for all tokens
    # and a psum combines — the small-batch inference EP pattern.
    ep: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.tp * self.ep

    @property
    def is_trivial(self) -> bool:
        """True when every axis is 1 — the single-device topology.
        Backend selection (runtime.create_backend) keys off this instead
        of re-enumerating the axes, so a new axis cannot drift past it."""
        return self.n_devices == 1


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Per-request sampling parameters.

    Defaults mirror the reference's /generate route
    (/root/reference/orchestration.py:339-354): temperature 0.7,
    top_k 50, top_p 0.9, max_tokens default 20.
    """

    temperature: float = 0.7
    top_k: int = 50
    top_p: float = 0.9
    max_new_tokens: int = 20
    greedy: bool = False
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Decode-engine settings."""

    max_seq_len: int = 2048
    max_batch_size: int = 1
    # Prompt-length buckets for prefill compilation (TTFT: avoids recompiling
    # per prompt length; prompts are right-padded up to the bucket).
    prefill_buckets: tuple = (64, 128, 256, 512, 1024, 2048)
    # Per-request wall-clock deadline in seconds (None = unlimited). The
    # reference enforces 30s per stage hop (orchestration.py:118,131);
    # here a whole request that exceeds the deadline gets a timeout error
    # envelope and the engine keeps serving (the wedged device call is
    # abandoned to a daemon thread; the engine lock frees when it dies).
    request_deadline_s: Optional[float] = None
    # Prefix KV cache (engine/prefix.py): number of chunk-aligned prompt-
    # prefix snapshots kept on device (0 = disabled). Requests whose
    # prompt starts with a stored prefix splice its KV back and prefill
    # only the tail — TTFT scales with the new tokens, not the prompt.
    prefix_cache_entries: int = 0
    # Snapshot alignment: prefixes are stored at multiples of this length.
    prefix_chunk: int = 64
    # Grammar-constraint compiled-artifact LRU (constrain/): how many
    # distinct constraints keep their (mask, transition) tables — host
    # numpy + warm device copies — cached per engine. A resident artifact
    # costs ~num_states x vocab x 5 bytes; eviction only costs a
    # recompile (host-side, milliseconds-to-seconds), never correctness.
    constraint_cache_entries: int = 16
    # State-row capacity of the continuous fleet's COMBINED constraint
    # table (constrain/fleet.py): constraints whose DFA cannot ever fit
    # run on the solo engine instead; admission backpressures while the
    # resident set transiently fills. Memory: 2 tables x capacity x vocab
    # (bool + int32).
    constraint_fleet_states: int = 1024
    # Ragged paged ingest (engine/paged.py ragged programs + the
    # ops/paged_attention ragged kernel): paged-fleet admission prefills
    # straight into the pool in fixed-width flat-token launches — no
    # scratch cache, no insert scatter, no prefill-bucket ladder, and the
    # block-prefix planner reuses at EXACT chunk depth. False falls back
    # to the bucketed scratch path (prefill_buckets), which also serves
    # any backend without the ragged fill programs.
    ragged_prefill: bool = True
    # Flat-token launch width of the ragged ingest programs: one compiled
    # (extend, prefill) program pair per width serves every tail length
    # (longer tails loop whole-width launches; the final launch pads with
    # dead tiles the kernel's DMA skips). Rounded up to a multiple of the
    # query tile (8).
    ragged_width: int = 64
    # SLO-aware chunked-prefill scheduler (engine/scheduler.py): ragged
    # paged fleets stop prefilling an admission whole before it joins the
    # decode fleet — each scheduler step assembles ONE mixed ragged launch
    # of every active DECODE row plus PREFILL chunks of pending
    # admissions, sliced to the per-step flat-token budget below, so a
    # long prompt never stalls the decoding requests' TPOT. False (or a
    # non-ragged fleet) falls back to admit-then-prefill-whole.
    chunked_prefill: bool = True
    # Per-step flat-token budget of the mixed launch (rounded up to a
    # whole number of query tiles, and to at least one prefill tile above
    # the decode fleet — every active slot's decode row is reserved ahead
    # of any prefill chunk, so decode can never be starved by prefill and
    # at least one pending prefill always progresses).
    step_token_budget: int = 128
    # SLO classes: (name, ttft_target_s, tpot_target_s, weight,
    # sheddable). The scheduler apportions the per-step prefill budget
    # across classes by weight x urgency (urgency = queue head wait over
    # the class TTFT target, fed back from the request timing samples),
    # and admission sheds a sheddable class's request with a 429 when its
    # class-local queue drain estimate already overruns the TTFT target
    # (Retry-After derived from THAT class's drain estimate, never the
    # global queue depth). Non-sheddable classes only queue.
    slo_classes: tuple = (
        ("interactive", 0.5, 0.1, 4.0, True),
        ("standard", 2.0, 0.5, 2.0, True),
        ("batch", 30.0, 2.0, 1.0, False),
    )
    # Class assigned when a request carries no slo_class field.
    slo_default_class: str = "standard"
    # Warm-state recovery (engine/shadow.py): host-side crash-consistent
    # shadowing of filled paged-KV blocks, so supervisor restarts
    # re-prefill only each salvaged request's partial tail block and a
    # graceful drain can persist the block-prefix cache for a warm
    # rolling restart (--restore-dir). Paged fleets with a block-prefix
    # index only (prefix_cache_entries > 0 — restore re-enters through
    # the ordinary block-prefix hit machinery); the dense fleet has no
    # immutable-block contract to shadow.
    kv_shadow: bool = True
    # Host-RAM bound of the shadow store, in blocks (LRU with cascade
    # eviction, like the block-prefix index). 0 = auto: twice the pool,
    # so a full pool's worth of warm chains survives one generation of
    # churn.
    kv_shadow_blocks: int = 0
    # Cross-replica KV fabric (serving/kv_fabric.py): serve this
    # replica's shadowed KV chains by chunk digest on GET /kv/{digest},
    # and honor the router's X-KV-Transfer-* handoff hints by pulling a
    # missing prefix from the resident peer (scattered through the
    # pre-warmed restore program) instead of re-prefilling it. Needs the
    # same stack as kv_shadow (paged fleet + block-prefix index); False
    # keeps the shadow purely local (crash recovery only).
    kv_fabric: bool = True
    # Hard deadline on one fabric fetch, end to end: a dead or wedged
    # peer costs at most this long, then admission degrades to a local
    # cold prefill (the fallback ladder never errors).
    kv_fabric_timeout_s: float = 5.0
    # Disk tier of the KV cache hierarchy (ARCHITECTURE.md "Tiered KV"):
    # a directory of persisted parent-chained chunk files
    # (chunk_<digest>.npz) that LRU-evicted host-shadow entries DEMOTE
    # into instead of dropping, and every shadow read surface
    # (block-prefix restore planning, warm recovery, preemption swap,
    # the fabric) PROMOTES hits back out of — bounding the replica's
    # logical prefix cache by disk, not HBM. None (the default)
    # disables tier 2: eviction drops, as before.
    kv_disk_dir: Optional[str] = None
    # Disk-tier bound, in blocks (chunk files; LRU with the same
    # cascade discipline as the host tier). 0 = auto: 8x the host
    # tier, so the logical cache is an order of magnitude deeper than
    # host DRAM before files churn.
    kv_disk_blocks: int = 0
    # Streamed fabric transfer: pull peer chains chunk-at-a-time
    # (GET /kv/{digest}?stream=1 — length-prefixed single-block frames,
    # per-chunk digest recheck) so the importing replica overlaps the
    # network pull with its device scatters instead of buffering the
    # whole manifest first. False pins the PR-11 whole-manifest pull
    # (also the automatic fallback against pre-stream peers).
    kv_fabric_stream: bool = True
    # Cap on the digests /health advertises for router residency
    # bootstrap (MRU-first, host tier before disk): the disk tier makes
    # the full resident set unbounded, and bootstrap payloads must stay
    # O(1) however deep it grows.
    kv_health_digests: int = 64
    # Replica specialization class for prefill/decode disaggregation
    # ("prefill" | "decode" | "mixed"): the router sends fresh
    # long-prompt work to prefill-class replicas and hands the finished
    # prefix (by digest, via the fabric) to a decode-class replica for
    # the token loop. Engine-side this only labels the fabric metrics
    # and /health — specialization is routing policy, not a different
    # engine.
    replica_class: str = "mixed"
    # Speculative decoding on the ragged paged fleet (engine/continuous.py
    # + engine/paged.py spec programs): eligible greedy decode slots
    # submit a [current + K-token draft] VERIFY row instead of a 1-token
    # decode row inside the mixed scheduler launch — the ragged kernel
    # already serves arbitrary-length rows, so verifying K drafts costs
    # ~one decode step of weight streaming and accepts up to K+1 tokens.
    # Accept/reject is fully traced (match-prefix + correction token on
    # device, packed into the existing fetch — zero host syncs, one
    # compiled program for every accept pattern). Greedy acceptance is
    # bit-identical to plain decode. spec_draft_len = drafted tokens per
    # verify row (0 disables the machinery entirely).
    spec_draft_len: int = 4
    # Fleet-wide self-speculation: True speculates for EVERY eligible
    # greedy slot; False speculates only for requests that ask
    # ("speculative": true on /generate). Either way the scheduler
    # throttles drafting to 0 under decode TPOT pressure (speculation
    # accelerates idle fleets and self-disables under load), and a slot
    # whose history has no draft to offer submits a plain decode row —
    # non-repetitive streams pay nothing.
    spec_decode: bool = False
    # Draft-model speculation for the fleet (the decode_draft_speculative
    # flavor): registry name of a small same-tokenizer model whose greedy
    # chain proposes the drafts (device-side, batched over the fleet,
    # sharing the SAME block tables over its own pool leaves) instead of
    # n-gram lookup. A draft already attached via engine.set_draft()
    # takes precedence over loading this name. None = n-gram drafts.
    spec_draft_model: Optional[str] = None
    # Device-derived launch metadata for the speculative mixed launch
    # (engine/paged.DeviceMeta + apply_device_meta): decode/verify rows
    # read their q_start / per-token positions from the device-resident
    # slot state instead of the host position model, so a slot with an
    # unfetched verify row is never frozen — every eligible slot submits
    # a verify row EVERY scheduler step, back to back under lag
    # pipelining, and the packed fetch only confirms emissions. On top,
    # the scheduler sizes each slot's next draft adaptively from its
    # acceptance-rate EWMA (TokenBudgetScheduler.spec_slot_k). False
    # pins the PR-13 skip-until-fetched behavior (host-planned q_start,
    # one verify row per fetch round trip) — kept as the bench.py
    # `spec_lag` baseline.
    spec_device_meta: bool = True
    # SLO-aware KV preemption (engine/continuous.py _preempt_for): when a
    # paged admission still cannot get blocks after the evict-
    # unreferenced-chains retry, the scheduler preempts the lowest-SLO-
    # weight / youngest DECODING request instead of stalling the queue:
    #   "swap"      — push the victim's filled blocks to the host shadow
    #                 (synchronous flush through engine/shadow.py) before
    #                 releasing them, so the resume re-admission restores
    #                 the chain in one scatter and re-prefills only the
    #                 tail; a backlogged copier falls back to
    #                 drop-and-recompute (bit-identical either way);
    #   "recompute" — always drop the KV and re-prefill from the salvage
    #                 record (prompt + fetched tokens) on resume;
    #   "off"       — never preempt (pool exhaustion waits for a release,
    #                 the pre-preemption behavior).
    preempt_policy: str = "swap"
    # Livelock guard: a request preempted this many times becomes immune
    # (it keeps its blocks until completion; admission waits instead).
    max_preemptions_per_req: int = 2
    # Quantized inter-stage transfers (ops/wire_quant.py): "int8"
    # quantizes the [B, T, D] activation immediately before EVERY
    # inter-stage hand-off on an SPMD mesh and dequantizes on landing —
    # the gated microstep ring's ppermute, the 1F1B schedule's two
    # ppermute sites, the sp ring/ulysses chunk hops, and the masked
    # psum broadcasts of the final-stage [B, 1, D] window (int8 data +
    # fp32 per-token-row scales on the wire, EQuARX-style) — cutting the
    # ICI bytes that bound deeper pipelines ~4x at fp32 (~2x at bf16).
    # None (the default) is bit-identical to the unquantized wire on
    # every topology; "int8" is toleranced (greedy token-match-rate
    # gated in tests). The `wire-dtype` HLO rules machine-check that the
    # lowered collective-permutes really carry si8 when this is on.
    pp_wire_quant: Optional[str] = None
    # Paged LoRA adapter serving (engine/adapters.py): number of HBM
    # adapter pages the resident base model carries (0 disables the
    # subsystem entirely — no lora_* leaves are installed and the paged
    # programs trace without the pages operand, lowering byte-identically
    # to the pre-adapter build). Each page holds one adapter's stacked
    # A/B factors for every supported projection at `adapter_rank`; page
    # 0 is the all-zero BASE page (never written, never evicted), so
    # adapter id 0 is the base model by construction. Pages are
    # refcounted and LRU-evicted exactly like KV blocks (BlockAllocator
    # discipline): admission acquires, completion releases, eviction only
    # ever takes refcount-0 residents.
    adapter_slots: int = 0
    # Uniform rank budget of every adapter page: registered adapters of
    # LOWER rank are zero-padded to it (exact — padding contributes
    # nothing to the delta); higher rank is rejected at registration.
    adapter_rank: int = 8
    # Per-tenant prefill-budget weights, ((tenant, weight), ...): within
    # each SLO class's tile grant the chunked-prefill scheduler splits
    # across tenants by these weights (FIFO within a tenant). Unlisted
    # tenants weigh 1.0; empty = every tenant equal.
    tenant_weights: tuple = ()
    # Tenant admission quota: one tenant's queued share of the bounded
    # request queue may not exceed this fraction (beyond a small absolute
    # floor) — the over-quota tenant sheds with 429 + Retry-After before
    # other tenants starve. 1.0 disables the quota.
    tenant_max_queue_share: float = 0.5
    # Launch-level device-time attribution (utils/tracing.py +
    # serving/trace_store.py): fraction of traces whose requests get
    # per-launch dispatch→packed-fetch spans recorded host-side (launch
    # seq keyed — lag-pipelined launches attribute correctly with ZERO
    # extra device syncs; `analysis --hlo` stays clean because nothing
    # here touches compiled code). The decision is a deterministic
    # function of the trace id (tracing.sample_decision), so all
    # replicas agree per trace. 0 (the default) keeps the hot path
    # allocation-free: no profiling structure is ever created.
    trace_sample_rate: float = 0.0

    def __post_init__(self):
        if not (0.0 <= self.trace_sample_rate <= 1.0):
            raise ValueError(
                f"trace_sample_rate must be in [0, 1], got "
                f"{self.trace_sample_rate}"
            )
        if self.pp_wire_quant not in (None, "int8"):
            raise ValueError(
                f"pp_wire_quant must be None or 'int8', got "
                f"{self.pp_wire_quant!r}"
            )
        if self.kv_disk_blocks < 0:
            raise ValueError(
                f"kv_disk_blocks must be >= 0, got {self.kv_disk_blocks}"
            )
        if self.kv_health_digests < 1:
            raise ValueError(
                f"kv_health_digests must be >= 1, got "
                f"{self.kv_health_digests}"
            )
        if self.adapter_slots < 0:
            raise ValueError(
                f"adapter_slots must be >= 0, got {self.adapter_slots}"
            )
        if self.adapter_slots and self.adapter_rank < 1:
            raise ValueError(
                f"adapter_rank must be >= 1, got {self.adapter_rank}"
            )
        if not (0.0 < self.tenant_max_queue_share <= 1.0):
            raise ValueError(
                f"tenant_max_queue_share must be in (0, 1], got "
                f"{self.tenant_max_queue_share}"
            )
        for entry in self.tenant_weights:
            name, w = entry
            if not name or float(w) <= 0:
                raise ValueError(
                    f"tenant_weights entries need a name and a positive "
                    f"weight, got {entry!r}"
                )


def resolve_attn_impl(cfg: "ModelConfig", requested: Optional[str]) -> "ModelConfig":
    """Apply an --attn-impl request to a model config.

    "xla" / "pallas": explicit. "auto": pick the Pallas flash kernel
    (ops/flash_attention.py) when the session is actually on a TPU
    backend — the chunk kernel covers every attention variant now
    (softcap, scale overrides, per-layer window patterns), so legality no
    longer constrains the choice; on CPU the kernel runs in interpret
    mode, orders of magnitude slower than the XLA path, so auto never
    selects it there. None: keep the config's own setting.
    """
    if requested is None:
        return cfg
    if requested in ("xla", "pallas"):
        return cfg.replace(attn_impl=requested)
    if requested != "auto":
        raise ValueError(
            f"attn_impl request must be 'auto', 'xla', or 'pallas'; got "
            f"{requested!r}"
        )
    import jax

    if jax.default_backend() != "tpu":
        return cfg.replace(attn_impl="xla")
    # no legality guard needed: __post_init__ accepts pallas for every
    # attention variant (both kernels take softcap/scale overrides and
    # per-layer windows), so replace() cannot raise here
    return cfg.replace(attn_impl="pallas")


def stage_layer_range(n_layers: int, pp: int, stage: int) -> tuple[int, int]:
    """Contiguous layer range [start, end) owned by `stage`.

    The reference hardcodes 0-11 / 11-22 for TinyLlama's 22 layers
    (/root/reference/Worker1.py:27-28, Worker2.py:26-27); we compute a
    balanced split for ANY pp <= n_layers: the first n_layers % pp stages
    own one extra layer (22/4 -> 6,6,5,5). Stages whose share is short of
    ceil(n_layers/pp) are padded with zero no-op layers at shard time
    (parallel/partition.pad_stacked_layers) so the stacked layer axis still
    shards evenly over the pp mesh axis.
    """
    if not 1 <= pp <= n_layers:
        raise ValueError(f"pp={pp} must be in [1, n_layers={n_layers}]")
    if not 0 <= stage < pp:
        raise ValueError(f"stage={stage} out of range for pp={pp}")
    base, rem = divmod(n_layers, pp)
    start = stage * base + min(stage, rem)
    return start, start + base + (1 if stage < rem else 0)
