"""Replica router tier: an HTTP front door over N independent engine
replicas (ROADMAP "cache-aware horizontal scale-out").

Everything below a replica is fault-contained and observable (PR 5:
supervised scheduler, poison quarantine, SIGTERM drain, liveness/
readiness split) — this is the missing "millions of users" layer that
makes replica death an operational non-event instead of a deployment
outage. Four jobs:

  * PREFIX-AFFINITY ROUTING (Orca-style load balancing + vLLM-style
    cache awareness): the prompt head is hashed at block-prefix chunk
    granularity (engine/block_prefix.chunk_digests — the same chained
    structure as the refcounted block index's keys) and a bounded
    router-side residency map remembers which replica last served each
    chunk chain. Shared-prefix traffic lands where its KV blocks are
    already resident; everything else falls back to least-outstanding.
    A wrong guess costs one cache-cold prefill, never wrong output, so
    the map needs no invalidation protocol.
  * HEALTH-DRIVEN EJECTION: active `GET /ready` probes plus passive
    circuit breaking on consecutive connect/5xx failures. An ejected
    replica receives no traffic until a successful probe moves it to
    HALF_OPEN (trial traffic only when no READY replica remains), and a
    further success readmits it.
  * FAILOVER: a non-streamed request that hits a dead or draining
    replica is transparently re-dispatched to a healthy one — safe
    because zero bytes of the reply have reached the client, the same
    discipline client.py applies to its own retries. Streamed requests
    fail over ONLY on pre-stream rejection; after the first forwarded
    byte the stream is bound to its replica. Retry-After from an
    upstream 429/503 is honored as a per-replica cool-down, and when no
    candidate remains it propagates to the client. X-Request-Id crosses
    the hop both ways; a `router` span is folded into the envelope's
    `timings`.
  * DRAIN-AWARE ROLLING RESTARTS: `POST /admin/rolling-restart` cycles
    ROUTER-SPAWNED replicas one at a time through the PR-5 drain path
    (SIGTERM -> readiness flips -> in-flight work finishes -> clean
    exit), respawns, and waits for `/ready` before touching the next —
    a config/weight rollout never drops a request.
  * KV FABRIC + PREFILL/DECODE DISAGGREGATION (serving/kv_fabric.py;
    ARCHITECTURE.md "KV fabric & disaggregation"): on top of the byte
    affinity map the router keeps a digest->replica residency view in
    TOKEN-digest space (learned from response envelopes' kv_digests and
    /health bootstraps, purged on ejection). A dispatch landing away
    from the prefix's holder carries X-KV-Transfer-* headers so the
    replica pulls the chain over the fabric instead of re-prefilling;
    and when the fleet has prefill- AND decode-class replicas
    (--spawn-prefill/--spawn-decode or --replica-class on the servers),
    fresh long-prompt work runs a TWO-PHASE dispatch — phase 1 prefills
    (+ shadow-flushes) on the prefill tier, phase 2 hands the digest to
    a decode replica for the token loop — so TTFT and TPOT stop
    competing for one step_token_budget. Every handoff failure (dead
    prefill tier, evicted digest, failed fetch) degrades to a normal
    dispatch + local prefill, never an error.

The router is strictly host-side glue: it never imports jax, never
touches an engine, and stays decode-UNREACHABLE in the analysis call
graph (pinned in tests/test_analysis.py, like utils/faults.py).
"""

from __future__ import annotations

import argparse
import collections
import http.client
import json
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..engine.block_prefix import chunk_digests
from ..utils.logging import get_logger, request_id_context
from ..utils.metrics import MetricsRegistry
from ..utils.retry import parse_retry_after
from ..utils.tracing import (
    SpanContext,
    new_request_id,
    parse_traceparent,
    sanitize_request_id,
)
from .trace_store import (
    TraceStore,
    assemble_tree,
    span_tree_total,
    to_chrome_trace,
)

log = get_logger("router")

__version__ = "tpu_pipeline_router_v1"

# replica ejection state machine (ARCHITECTURE.md "Router tier"):
#   READY --(eject_threshold consecutive connect/5xx failures,
#            probe or proxied)--> EJECTED
#   EJECTED --(successful /ready probe)--> HALF_OPEN
#   HALF_OPEN --(successful probe OR successful trial request)--> READY
#   HALF_OPEN --(any failure)--> EJECTED
#   any --(rolling restart picks it)--> DRAINING --(respawn + /ready)-->
#   READY
READY = "ready"
EJECTED = "ejected"
HALF_OPEN = "half_open"
DRAINING = "draining"

# Retry-After (seconds) when the router itself must reject: no healthy
# replica, or rolling-restart races. Matches serving/server.py's default.
RETRY_AFTER_S = 2

# default byte granularity of the affinity hash: ~a 16-token KV block of
# typical English text. Must divide consistently across requests, not
# match the replica's tokenizer exactly — a mismatch only shortens the
# usable chain, it cannot route to wrong output.
AFFINITY_CHUNK_BYTES = 64
AFFINITY_MAX_CHUNKS = 32
# holders remembered per residency digest: enough to spread a hot
# prefix across a small decode tier, small enough that a fleet-wide
# prefix doesn't make every entry fleet-sized
MAX_RESIDENCY_HOLDERS = 4

_FORWARD_ROUTES = ("/generate", "/v1/completions", "/v1/chat/completions")

_KNOWN_ROUTES = frozenset((
    "/", "/health", "/ready", "/stats", "/metrics", "/v1/models",
    "/admin/rolling-restart", "/debug/traces", "/debug/flight",
    *_FORWARD_ROUTES,
))


def _route_label(path: str) -> str:
    if path.startswith("/debug/traces"):
        return "/debug/traces"  # one label for every trace id
    return path if path in _KNOWN_ROUTES else "other"


class Replica:
    """One upstream engine server, plus the router's view of its health."""

    def __init__(self, rid: str, url: str, proc=None, spawn_argv=None,
                 spawn_env=None, replica_class: str = "mixed"):
        self.rid = rid
        self.url = url.rstrip("/")
        # router-spawned replicas carry their subprocess + respawn recipe
        # (rolling restarts need both); URL-joined replicas have neither
        self.proc = proc
        self.spawn_argv = spawn_argv
        self.spawn_env = spawn_env
        # disaggregation class ("prefill" | "decode" | "mixed"): set at
        # spawn (--spawn-prefill/--spawn-decode) or learned from the
        # replica's /health — fresh long-prompt work goes to prefill-
        # class replicas, the token loop to decode/mixed ones
        self.replica_class = replica_class
        self.state = READY  # optimistic; the first probe corrects it
        self.consecutive_failures = 0
        self.outstanding = 0
        # Retry-After honored as a dispatch cool-down (monotonic deadline)
        self.cooldown_until = 0.0
        self.lock = threading.Lock()

    def snapshot(self) -> dict:
        with self.lock:
            return {
                "url": self.url,
                "state": self.state,
                "class": self.replica_class,
                "outstanding": self.outstanding,
                "consecutive_failures": self.consecutive_failures,
                "spawned": self.proc is not None,
            }


class Router:
    """Routing + health logic, independent of the HTTP surface (the
    handler and the CLI both drive this object; tests drive it directly).

    Replica state transitions happen under each replica's lock, so the
    prober thread, handler threads, and the rolling-restart thread can
    all drive the ejection state machine concurrently."""

    def __init__(self, replicas, eject_threshold: int = 3,
                 probe_interval_s: float = 2.0, probe_timeout_s: float = 5.0,
                 affinity_chunk: int = AFFINITY_CHUNK_BYTES,
                 affinity_entries: int = 4096,
                 request_timeout_s: float = 200.0,
                 drain_deadline_s: float = 60.0,
                 failover_attempts: Optional[int] = None,
                 fabric: bool = True,
                 handoff_min_bytes: int = 192,
                 kv_push: bool = True,
                 tenant_max_inflight_share: float = 0.5):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self._by_id = {r.rid: r for r in self.replicas}
        self.eject_threshold = int(eject_threshold)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.affinity_chunk = int(affinity_chunk)
        self.affinity_entries = int(affinity_entries)
        self.request_timeout_s = float(request_timeout_s)
        self.drain_deadline_s = float(drain_deadline_s)
        # KV fabric (serving/kv_fabric.py): attach X-KV-Transfer-* hints
        # so a replica that misses a prefix pulls it from the resident
        # peer, and run the prefill->decode handoff when the fleet has
        # both classes. handoff_min_bytes gates what counts as "fresh
        # long-prompt work" worth a two-phase dispatch.
        self.fabric = bool(fabric)
        self.handoff_min_bytes = int(handoff_min_bytes)
        # proactive chain push: when a prefill-only phase succeeds, the
        # router pre-picks the least-loaded decode replica, names it in
        # X-KV-Push-To, and the prefill replica POSTs the finished chain
        # there before phase 2 dispatches — the decode replica starts
        # with the KV already in its host tier instead of pulling it.
        self.kv_push = bool(kv_push)
        # tenant-aware shedding: one tenant holding more than this share
        # of ALL router-inflight requests is turned away with 429 +
        # Retry-After BEFORE a replica is picked, so a flooding tenant
        # saturates its own quota instead of every replica's admission
        # queue. Requests without a tenant field are never shed here
        # (they count toward the total only). 1.0 disables.
        self.tenant_max_inflight_share = float(tenant_max_inflight_share)
        # guarded-by: _tenant_lock; tenant -> inflight count ("" = the
        # anonymous bucket, tracked so shares are of the true total)
        self._tenant_inflight: dict = {}
        self._tenant_lock = threading.Lock()
        # each request tries at most every replica once by default
        self.failover_attempts = (
            int(failover_attempts) if failover_attempts
            else max(2, len(self.replicas))
        )
        # chunk-chain digest -> (holder replica ids MRU-first, deepest
        # TOKEN digest reported for this chain, or None), LRU-bounded.
        # One entry per digest DEPTH, so a long shared prefix costs
        # several entries — that is the point: a deeper match wins
        # routing. KV is content-addressed, so one digest legitimately
        # lives on several replicas at once (pushes, pulls, repeated
        # prompts); keeping every holder lets pick() spread a hot prefix
        # by load instead of pinning it to the last server. The token
        # digest is the byte->token bridge the fabric needs: the router
        # has no tokenizer, so it can only name a fetchable chain by
        # remembering what a serving replica reported.
        # guarded-by: _res_lock
        self._residency: "collections.OrderedDict[str, tuple]" = (
            collections.OrderedDict()
        )
        # the global digest->holders residency view in TOKEN-digest
        # space (tuple of replica ids, MRU-first): learned from response
        # envelopes (kv_digests) and from replica /health bootstraps
        # (resident_digests), purged with ejections — stale entries must
        # not steer fabric pulls at a corpse
        # guarded-by: _res_lock
        self._kv_residency: "collections.OrderedDict[str, tuple]" = (
            collections.OrderedDict()
        )
        self._res_lock = threading.Lock()
        # guarded-by: _roll_lock
        self.rolling: dict = {"active": False, "done": [], "current": None,
                              "error": None, "warm": {}}
        self._roll_lock = threading.Lock()
        self._closed = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None

        self.metrics = MetricsRegistry()
        # the router's half of the fleet trace: its request/dispatch/
        # retry/handoff spans land here; GET /debug/traces/{id} merges
        # them with every replica's spans into one tree (collect_trace)
        self.trace_store = TraceStore(service="router")
        from .. import __version__ as _dli_version

        # build-identity gauge, same family the engines pre-register
        # (engine/engine.py) — always 1, the labels are the payload; the
        # router never imports jax, so that label reports "none" here
        self.metrics.gauge(
            "dli_build_info",
            "build/version identity (value is always 1; the labels are "
            "the payload — join against any dli_* series)",
            ("version", "jax", "replica_class", "knobs"),
        ).labels(
            version=_dli_version, jax="none", replica_class="router",
            knobs="",
        ).set(1.0)
        self._m_requests = self.metrics.counter(
            "dli_router_requests_total",
            "requests proxied per replica by upstream outcome",
            ("replica", "code"),
        )
        self._m_failovers = self.metrics.counter(
            "dli_router_failovers_total",
            "requests transparently re-dispatched off a dead/draining/"
            "overloaded replica", ("replica",),
        )
        self._m_ejections = self.metrics.counter(
            "dli_router_ejections_total",
            "replicas ejected by the circuit breaker", ("replica",),
        )
        self._m_readmissions = self.metrics.counter(
            "dli_router_readmissions_total",
            "ejected replicas readmitted after half-open success",
            ("replica",),
        )
        self._m_outstanding = self.metrics.gauge(
            "dli_router_outstanding",
            "requests in flight per replica", ("replica",),
        )
        self._m_ready = self.metrics.gauge(
            "dli_router_replica_ready",
            "1 = replica READY for traffic, 0 = ejected/half-open/draining",
            ("replica",),
        )
        self._m_probe = self.metrics.histogram(
            "dli_router_probe_seconds",
            "active /ready probe latency", ("replica",),
        )
        self._m_affinity = self.metrics.counter(
            "dli_router_affinity_total",
            "routing decisions by affinity outcome (hit = residency map "
            "named a dispatchable replica)", ("result",),
        )
        self._m_tenant_shed = self.metrics.counter(
            "dli_tenant_shed_total",
            "requests shed with 429 by the per-tenant inflight quota at "
            "the router edge", ("tenant",),
        )
        self._m_handoffs = self.metrics.counter(
            "dli_router_handoffs_total",
            "prefill->decode disaggregation handoffs by outcome "
            "(handoff = decode replica imported the chain; cold_fallback "
            "= it re-prefilled locally; prefill_failed / no_digests = "
            "phase 1 degraded to a normal dispatch; stream = streamed "
            "phase 2, outcome not observable)", ("outcome",),
        )
        for r in self.replicas:
            self._m_ready.labels(replica=r.rid).set(1.0)
            self._m_outstanding.labels(replica=r.rid).set(0.0)

    # -- health / ejection ---------------------------------------------------
    def _set_ready_gauge(self, rep: Replica):
        self._m_ready.labels(replica=rep.rid).set(
            1.0 if rep.state == READY else 0.0
        )

    def note_failure(self, rep: Replica, why: str = ""):
        """One connect/5xx failure (probe or proxied). Ejects at the
        threshold; a HALF_OPEN replica re-ejects immediately (its trial
        failed — the breaker reopens). Ejection PURGES the replica's
        residency entries: a stale digest steering affinity (or a fabric
        pull) at a corpse costs a failover/cold-prefill on every routed
        request until the entry happens to be overwritten."""
        ejected = False
        with rep.lock:
            if rep.state == DRAINING:
                return  # rolling restart owns this replica's lifecycle
            rep.consecutive_failures += 1
            eject = (
                rep.state == HALF_OPEN
                or (rep.state == READY
                    and rep.consecutive_failures >= self.eject_threshold)
            )
            if eject and rep.state != EJECTED:
                rep.state = EJECTED
                ejected = True
                self._m_ejections.labels(replica=rep.rid).inc()
                log.warning("replica_ejected", replica=rep.rid,
                            failures=rep.consecutive_failures, why=why)
            self._set_ready_gauge(rep)
        if ejected:
            self.purge_residency(rep.rid)

    def note_success(self, rep: Replica):
        """A successful probe or proxied request: reset the breaker; a
        HALF_OPEN replica is readmitted."""
        with rep.lock:
            rep.consecutive_failures = 0
            if rep.state == HALF_OPEN:
                rep.state = READY
                self._m_readmissions.labels(replica=rep.rid).inc()
                log.info("replica_readmitted", replica=rep.rid)
            self._set_ready_gauge(rep)

    def probe_once(self):
        """One active probe sweep: GET /ready on every replica the router
        currently owns traffic for. EJECTED + success -> HALF_OPEN;
        HALF_OPEN + success -> READY (readmission)."""
        for rep in self.replicas:
            if rep.state == DRAINING:
                continue
            t0 = time.perf_counter()
            ok = False
            try:
                req = urllib.request.Request(rep.url + "/ready")
                with urllib.request.urlopen(
                    req, timeout=self.probe_timeout_s
                ) as resp:
                    ok = resp.status == 200
            except (urllib.error.URLError, OSError, ValueError):
                ok = False  # connect failure or a 503 not-ready answer
            self._m_probe.labels(replica=rep.rid).observe(
                time.perf_counter() - t0
            )
            if not ok:
                self.note_failure(rep, why="probe")
                continue
            stepped = False
            with rep.lock:
                if rep.state == EJECTED:
                    # one successful probe only OPENS the breaker halfway;
                    # readmission needs a further success (next sweep, or
                    # a successful trial request)
                    rep.state = HALF_OPEN
                    rep.consecutive_failures = 0
                    stepped = True
                    log.info("replica_half_open", replica=rep.rid)
                    self._set_ready_gauge(rep)
            # READY/HALF_OPEN probe success flows through the same seam
            # as proxied successes (HALF_OPEN -> READY readmission)
            if not stepped and rep.state in (READY, HALF_OPEN):
                self.note_success(rep)

    def start_prober(self):
        def _loop():
            while not self._closed.wait(self.probe_interval_s):
                try:
                    self.probe_once()
                except Exception as e:  # noqa: BLE001 - prober must survive
                    log.error("probe_sweep_failed", error=str(e))

        self._probe_thread = threading.Thread(
            target=_loop, daemon=True, name="router-prober"
        )
        self._probe_thread.start()

    def close(self):
        self._closed.set()

    # -- routing -------------------------------------------------------------
    def _candidates(self, exclude, role: str = "any") -> list:
        """Dispatchable replicas, class-filtered. role="decode" (the
        token loop) prefers decode/mixed replicas so prefill-class ones
        never compete with decode traffic — unless they are ALL that is
        left, because availability beats specialization. role="prefill"
        returns strictly prefill-class replicas (empty = no handoff —
        the caller degrades to a normal dispatch, never an error)."""
        now = time.monotonic()
        ready = [
            r for r in self.replicas
            if r.rid not in exclude and r.state == READY
            and r.cooldown_until <= now
        ]
        if not ready:
            # no READY replica: HALF_OPEN trial traffic is better than a
            # hard 503 — a success readmits, a failure re-ejects
            ready = [
                r for r in self.replicas
                if r.rid not in exclude and r.state == HALF_OPEN
                and r.cooldown_until <= now
            ]
        if role == "decode":
            pref = [r for r in ready if r.replica_class != "prefill"]
            return pref or ready
        if role == "prefill":
            return [r for r in ready if r.replica_class == "prefill"]
        return ready

    def pick(self, affinity_key: str, exclude=(), role: str = "any") -> tuple:
        """(replica, digests) for one dispatch attempt, or (None, digests)
        when nothing is dispatchable. Deepest-residency match wins;
        least-outstanding breaks the miss case."""
        digests = (
            chunk_digests(affinity_key, self.affinity_chunk,
                          AFFINITY_MAX_CHUNKS)
            if affinity_key and self.affinity_chunk >= 1 else []
        )
        cands = self._candidates(exclude, role=role)
        if not cands:
            return None, digests
        by_id = {r.rid: r for r in cands}
        with self._res_lock:
            for d in reversed(digests):
                ent = self._residency.get(d)
                if ent is None:
                    continue
                held = [
                    (by_id[h], i) for i, h in enumerate(ent[0])
                    if h in by_id
                ]
                if held:
                    # a hot prefix resident on several decode replicas
                    # spreads by load instead of pinning to one holder;
                    # equal-load ties keep the MRU holder so a failover
                    # still "moves" residency with the traffic
                    self._m_affinity.labels(result="hit").inc()
                    rep = min(
                        held, key=lambda t: (t[0].outstanding, t[1]),
                    )[0]
                    return rep, digests
        self._m_affinity.labels(result="miss").inc()
        return min(cands, key=lambda r: (r.outstanding, r.rid)), digests

    def record_residency(self, digests, rid: str,
                         token_digest: Optional[str] = None):
        """Remember that `rid` now holds the KV blocks for this chain
        (called with the replica that ACTUALLY served — and with every
        replica a push or pull COPIED the chain to, so one digest keeps
        all its holders, MRU-first, capped at MAX_RESIDENCY_HOLDERS).
        token_digest is the deepest TOKEN-chain digest a replica
        reported for this prompt (its fetchable name on /kv); an update
        without one keeps the previous bridge only when `rid` was
        already a known holder — a brand-new holder's bridge arrives
        with its own envelope."""
        if not digests:
            return
        with self._res_lock:
            for d in digests:
                prev = self._residency.get(d)
                tok = token_digest
                if prev is not None and tok is None and rid in prev[0]:
                    tok = prev[1]
                holders = (rid,)
                if prev is not None:
                    holders += tuple(h for h in prev[0] if h != rid)
                self._residency[d] = (
                    holders[:MAX_RESIDENCY_HOLDERS], tok,
                )
                self._residency.move_to_end(d)
            while len(self._residency) > self.affinity_entries:
                self._residency.popitem(last=False)

    def record_kv_residency(self, token_digests, rid: str,
                            bootstrap: bool = False):
        """Update the token-digest residency view (holders tuple,
        MRU-first, capped at MAX_RESIDENCY_HOLDERS). bootstrap=True (the
        /health resident_digests sweep) appends behind existing holders
        and never reorders — a digest learned from live traffic is
        fresher than a poll."""
        if not token_digests:
            return
        with self._res_lock:
            for d in token_digests:
                prev = self._kv_residency.get(d, ())
                if bootstrap:
                    if rid in prev:
                        continue  # already known; a poll adds nothing
                    holders = prev + (rid,)
                else:
                    holders = (rid,) + tuple(h for h in prev if h != rid)
                self._kv_residency[d] = holders[:MAX_RESIDENCY_HOLDERS]
                self._kv_residency.move_to_end(d)
            while len(self._kv_residency) > self.affinity_entries:
                self._kv_residency.popitem(last=False)

    def purge_residency(self, rid: str):
        """Strip `rid` from every residency entry — byte-affinity AND
        token-digest views — and drop entries it alone held. Called on
        ejection (and rolling-restart kills): a dead replica's digests
        must neither pin affinity nor steer fabric pulls at a corpse
        until overwritten; surviving co-holders keep serving."""
        with self._res_lock:
            for d, (holders, tok) in list(self._residency.items()):
                if rid not in holders:
                    continue
                rest = tuple(h for h in holders if h != rid)
                if rest:
                    self._residency[d] = (rest, tok)
                else:
                    del self._residency[d]
            for d, holders in list(self._kv_residency.items()):
                if rid not in holders:
                    continue
                rest = tuple(h for h in holders if h != rid)
                if rest:
                    self._kv_residency[d] = rest
                else:
                    del self._kv_residency[d]

    def residency_entries(self) -> int:
        with self._res_lock:
            return len(self._residency)

    def kv_residency_entries(self) -> int:
        with self._res_lock:
            return len(self._kv_residency)

    def _kv_hint(self, digests, rep: Replica) -> Optional[dict]:
        """X-KV-Transfer-* headers for dispatching this prompt to `rep`,
        when the residency view knows a DIFFERENT ready replica holding
        the prefix chain (deepest byte digest with a token bridge wins).
        None when rep already holds it, nobody does, or the holder is
        not currently fetchable — a wrong or missing hint costs one cold
        prefill, never wrong output, same contract as affinity."""
        if not self.fabric or not digests:
            return None
        with self._res_lock:
            for d in reversed(digests):
                ent = self._residency.get(d)
                if ent is None or ent[1] is None:
                    continue
                if rep.rid in ent[0]:
                    return None  # the pick already lands on a holder
                peers = [
                    p for p in (self._by_id.get(h) for h in ent[0])
                    if p is not None and p.state == READY
                ]
                if peers:
                    # least-loaded holder serves the pull: the wire cost
                    # lands where it hurts decode batching the least
                    peer = min(
                        peers, key=lambda r: (r.outstanding, r.rid),
                    )
                    return {
                        "X-KV-Transfer-Peer": peer.url,
                        "X-KV-Transfer-Digest": ent[1],
                    }
        return None

    def _envelope_kv_digests(self, rbody: bytes) -> Optional[list]:
        """kv_digests from a replica's JSON envelope (None when absent /
        unparseable — residency learning is best-effort)."""
        if not self.fabric or not rbody:
            return None
        try:
            env = json.loads(rbody)
        except (ValueError, json.JSONDecodeError):
            return None
        out = env.get("kv_digests") if isinstance(env, dict) else None
        return out if isinstance(out, list) and out else None

    # -- tenant admission ----------------------------------------------------
    def tenant_begin(self, tenant: Optional[str]) -> bool:
        """Admission-control one request for `tenant` (None/"" = the
        anonymous bucket). True admits and counts it — the caller MUST
        pair with tenant_end() on every exit path. False sheds: the
        tenant already holds >= max(4, share * total) of the router's
        inflight requests. The floor keeps a quiet router permissive
        (any tenant may hold a few requests before shares bind)."""
        key = tenant or ""
        with self._tenant_lock:
            if key and self.tenant_max_inflight_share < 1.0:
                total = sum(self._tenant_inflight.values())
                cap = max(4, int(total * self.tenant_max_inflight_share))
                if self._tenant_inflight.get(key, 0) >= cap:
                    self._m_tenant_shed.labels(tenant=key).inc()
                    log.info("router_tenant_shed", tenant=key,
                             inflight=self._tenant_inflight.get(key, 0),
                             cap=cap, total=total)
                    return False
            self._tenant_inflight[key] = self._tenant_inflight.get(key, 0) + 1
        return True

    def tenant_end(self, tenant: Optional[str]):
        key = tenant or ""
        with self._tenant_lock:
            n = self._tenant_inflight.get(key, 0) - 1
            if n <= 0:
                self._tenant_inflight.pop(key, None)
            else:
                self._tenant_inflight[key] = n

    # -- upstream calls ------------------------------------------------------
    def _begin(self, rep: Replica):
        with rep.lock:
            rep.outstanding += 1
            self._m_outstanding.labels(replica=rep.rid).set(rep.outstanding)

    def _end(self, rep: Replica):
        with rep.lock:
            rep.outstanding -= 1
            self._m_outstanding.labels(replica=rep.rid).set(rep.outstanding)

    def _proxy(self, rep: Replica, path: str, body: bytes, rid: str,
               timeout: Optional[float] = None, extra_headers=None,
               trace_ctx=None):
        """One POST to one replica. Returns (status, body_bytes, headers);
        HTTP error statuses come back as values, connect-level failures
        raise (urllib.error.URLError / OSError). trace_ctx (a
        tracing.SpanContext) rides as `traceparent` so the replica's
        spans join this trace under the attempt's span."""
        hdrs = {"Content-Type": "application/json", "X-Request-Id": rid}
        if trace_ctx is not None:
            hdrs["traceparent"] = trace_ctx.header()
        if extra_headers:
            hdrs.update(extra_headers)
        req = urllib.request.Request(
            rep.url + path, data=body, headers=hdrs, method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=timeout or self.request_timeout_s
            ) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    def dispatch(self, path: str, body: bytes, affinity_key: str,
                 rid: str, deadline_ms: Optional[float] = None,
                 hint_headers: Optional[dict] = None,
                 trace_ctx=None) -> tuple:
        """Route one NON-STREAMED request with transparent failover.

        Returns (replica_or_None, status, body_bytes, headers, attempts).
        Failover re-dispatches on: connect-level failures (dead replica,
        kill -9 mid-request — zero reply bytes reached the client, so a
        fresh greedy run elsewhere is indistinguishable), 503 (draining /
        restart-looping), and 429 (that replica is full; another may not
        be). It does NOT re-dispatch 4xx (the request is the problem),
        500 (a request-shaped server fault — poison would just take down
        a second fleet), or 504 deadline_exceeded (the request's OWN
        budget is spent — just as spent wherever a retry lands, and
        never a replica-health strike). Upstream Retry-After becomes a
        per-replica cool-down, honored by the next pick().

        deadline_ms: the request's remaining end-to-end budget at
        ingress; each attempt relays what is LEFT via
        X-Request-Deadline-Ms, and a spent budget answers 504 here
        without burning another replica's prefill.

        hint_headers: fixed X-KV-Transfer-* headers (a handoff's phase
        2); when absent, each attempt derives its own fabric hint from
        the residency view, so a replica that misses the prefix pulls
        it from the resident peer instead of re-prefilling.

        trace_ctx: the request's SpanContext. Every attempt records its
        own span — `router.dispatch` for the first, `router.retry` for
        failover hops — and the replica joins the trace UNDER that
        attempt's span via the relayed traceparent, so a failed-over
        request's tree shows exactly which hop served it."""
        t_in = time.monotonic()
        tried: set = set()
        prev: Optional[Replica] = None
        last = (503, json.dumps({
            "error": "Error: no healthy replica", "status": "failed",
            "error_type": "unavailable",
        }).encode(), {"Retry-After": str(RETRY_AFTER_S)})
        for attempt in range(self.failover_attempts):
            extra: dict = {}
            if deadline_ms is not None:
                left = deadline_ms - (time.monotonic() - t_in) * 1e3
                if left <= 0:
                    st, bd, hd = _deadline_exceeded_response()
                    return None, st, bd, hd, len(tried)
                extra["X-Request-Deadline-Ms"] = f"{left:.0f}"
            rep, digests = self.pick(affinity_key, exclude=tried,
                                     role="decode")
            if rep is None:
                break
            hint = (
                hint_headers if hint_headers is not None
                else self._kv_hint(digests, rep)
            )
            if hint:
                extra.update(hint)
            tried.add(rep.rid)
            if prev is not None:
                self._m_failovers.labels(replica=prev.rid).inc()
                log.info("failover", request_id=rid,
                         from_replica=prev.rid, to_replica=rep.rid)
            sp = None
            sub_ctx = None
            if trace_ctx is not None:
                # one span per attempt: the first is the dispatch, every
                # further hop is a retry — the failover trail is readable
                # straight off the assembled tree
                sp = self.trace_store.start_span(
                    "router.dispatch" if attempt == 0 else "router.retry",
                    trace_ctx,
                    attrs={"replica": rep.rid, "attempt": attempt + 1},
                )
                sub_ctx = trace_ctx.child(sp["span_id"])
            self._begin(rep)
            try:
                status, rbody, headers = self._proxy(
                    rep, path, body, rid, extra_headers=extra,
                    trace_ctx=sub_ctx,
                )
            # HTTPException covers IncompleteRead/RemoteDisconnected — a
            # replica kill -9'd MID-RESPONSE surfaces as one of these,
            # and it is exactly the failover case (zero reply bytes have
            # reached the client)
            except (urllib.error.URLError, OSError,
                    http.client.HTTPException) as e:
                self._m_requests.labels(
                    replica=rep.rid, code="connect_error"
                ).inc()
                self.note_failure(rep, why=f"proxy: {e}")
                if sp is not None:
                    self.trace_store.end_span(
                        sp, attrs={"outcome": "connect_error"}
                    )
                prev = rep
                continue
            finally:
                self._end(rep)
            if sp is not None:
                self.trace_store.end_span(sp, attrs={"status": status})
            self._m_requests.labels(replica=rep.rid, code=str(status)).inc()
            if status == 504:
                # deadline_exceeded: a property of the REQUEST's budget,
                # not the replica — no breaker strike, no re-dispatch
                # (the budget is spent wherever a retry would land)
                self.note_success(rep)
                return rep, status, rbody, headers, attempt + 1
            if status in (429, 503):
                ra = parse_retry_after(headers.get("Retry-After"))
                with rep.lock:
                    rep.cooldown_until = time.monotonic() + (
                        ra if ra is not None else float(RETRY_AFTER_S)
                    )
                if status == 503:
                    # draining / dead scheduler: a breaker strike too
                    self.note_failure(rep, why="503")
                prev = rep
                last = (status, rbody, headers)
                continue
            if status >= 500:
                self.note_failure(rep, why=str(status))
                return rep, status, rbody, headers, attempt + 1
            self.note_success(rep)
            # residency moves with the replica that ACTUALLY served —
            # failovers and fabric pulls included. The envelope's
            # kv_digests (when the replica runs the fabric) bridge the
            # byte-affinity chain to a fetchable token digest and feed
            # the token-space residency view.
            toks = self._envelope_kv_digests(rbody)
            self.record_residency(
                digests, rep.rid,
                token_digest=toks[-1] if toks else None,
            )
            if toks:
                self.record_kv_residency(toks, rep.rid)
            return rep, status, rbody, headers, attempt + 1
        return None, last[0], last[1], last[2], len(tried)

    # -- prefill->decode handoff (the disaggregated dispatch) ---------------
    def handoff_topology(self) -> bool:
        """True when the fleet can disaggregate RIGHT NOW: at least one
        dispatchable prefill-class replica and one non-prefill one."""
        return bool(
            self.fabric
            and self._candidates((), role="prefill")
            and any(
                r.replica_class != "prefill"
                for r in self._candidates((), role="decode")
            )
        )

    def maybe_handoff(self, path: str, body: bytes, affinity_key: str,
                      rid: str, deadline_ms: Optional[float] = None,
                      trace_ctx=None) -> Optional[dict]:
        """Phase 1 of the disaggregated dispatch, when it applies: send
        the request to a prefill-class replica with X-KV-Prefill-Only
        (it prefills, shadows, flushes, answers with the prefix's chain
        digests), and return the X-KV-Transfer-* headers phase 2 hands
        to a decode-class replica. None = dispatch normally: not a
        disaggregated topology, prompt too short, prefix already
        resident somewhere (an affinity/fabric hit is strictly better
        than recomputing it on the prefill tier), phase 1 failed (dead
        or overloaded prefill replica), or the replica reported no
        digests. Handoff failure is ALWAYS a degrade, never an error."""
        if (
            not self.fabric or not affinity_key
            or len(affinity_key.encode("utf-8", "ignore"))
            < self.handoff_min_bytes
        ):
            return None
        if deadline_ms is not None and deadline_ms <= 0:
            return None
        digests = (
            chunk_digests(affinity_key, self.affinity_chunk,
                          AFFINITY_MAX_CHUNKS)
            if self.affinity_chunk >= 1 else []
        )
        if digests:
            with self._res_lock:
                ent = self._residency.get(digests[-1])
            if ent is not None and ent[1] is not None:
                # deepest chain already resident with a fetchable name:
                # the ordinary dispatch's per-pick hint serves it
                return None
        pre = self._candidates((), role="prefill")
        if not pre or not any(
            r.replica_class != "prefill"
            for r in self._candidates((), role="decode")
        ):
            return None
        rep = min(pre, key=lambda r: (r.outstanding, r.rid))
        extra = {"X-KV-Prefill-Only": "1"}
        # proactive push: pre-pick the decode replica most likely to run
        # phase 2 (least outstanding now) and have the prefill replica
        # POST the finished chain straight at it — by the time phase 2
        # dispatches, the chain is already in the decode host tier and
        # the pull hint is just a fallback. A wrong guess (load shifted
        # between phases) costs nothing: phase 2 still carries the pull
        # hint, and the pushed copy ages out of the host tier.
        push_to: Optional[Replica] = None
        if self.kv_push:
            dec = [
                r for r in self._candidates((), role="decode")
                if r.replica_class != "prefill"
            ]
            if dec:
                push_to = min(dec, key=lambda r: (r.outstanding, r.rid))
                extra["X-KV-Push-To"] = push_to.url
        if deadline_ms is not None:
            extra["X-Request-Deadline-Ms"] = f"{deadline_ms:.0f}"
        sp = None
        sub_ctx = None
        if trace_ctx is not None:
            # phase 1 of the two-phase dispatch gets its own span; the
            # prefill replica's spans nest under it via the traceparent
            sp = self.trace_store.start_span(
                "router.handoff_prefill", trace_ctx,
                attrs={"replica": rep.rid},
            )
            sub_ctx = trace_ctx.child(sp["span_id"])
        self._begin(rep)
        try:
            status, rbody, _hdrs = self._proxy(
                rep, path, body, rid, extra_headers=extra,
                trace_ctx=sub_ctx,
            )
        except (urllib.error.URLError, OSError,
                http.client.HTTPException) as e:
            self.note_failure(rep, why=f"handoff_prefill: {e}")
            self._m_handoffs.labels(outcome="prefill_failed").inc()
            return None
        finally:
            self._end(rep)
            if sp is not None:
                self.trace_store.end_span(sp)
        self._m_requests.labels(replica=rep.rid, code=str(status)).inc()
        if status != 200:
            # busy/draining/erroring prefill tier: the token-loop
            # dispatch serves the request whole, like a mixed fleet
            if status in (429, 503):
                ra = parse_retry_after(_hdrs.get("Retry-After"))
                with rep.lock:
                    rep.cooldown_until = time.monotonic() + (
                        ra if ra is not None else float(RETRY_AFTER_S)
                    )
            self._m_handoffs.labels(outcome="prefill_failed").inc()
            return None
        self.note_success(rep)
        toks = self._envelope_kv_digests(rbody)
        if not toks:
            # fabric off upstream (config drift) or a prompt with no
            # full block: nothing fetchable, dispatch normally
            self._m_handoffs.labels(outcome="no_digests").inc()
            return None
        self.record_kv_residency(toks, rep.rid)
        if digests:
            self.record_residency(digests, rep.rid, token_digest=toks[-1])
        pushed = 0
        if push_to is not None:
            try:
                env = json.loads(rbody)
                if isinstance(env, dict):
                    pushed = int(env.get("kv_pushed") or 0)
            except (ValueError, TypeError, json.JSONDecodeError):
                pushed = 0
        if pushed > 0:
            # the decode replica holds the chain NOW: record it as a
            # co-holder so pick() lands phase 2 on it (MRU-first — the
            # push is fresher than the prefill replica's copy) and the
            # wire pull never happens
            self._m_handoffs.labels(outcome="pushed").inc()
            self.record_kv_residency(toks, push_to.rid)
            if digests:
                self.record_residency(
                    digests, push_to.rid, token_digest=toks[-1],
                )
        log.info("handoff_prefilled", request_id=rid, replica=rep.rid,
                 digest=toks[-1], pushed_blocks=pushed)
        return {
            "X-KV-Transfer-Peer": rep.url,
            "X-KV-Transfer-Digest": toks[-1],
        }

    def note_handoff_outcome(self, payload):
        """Score a completed phase 2 off its envelope: did the decode
        replica import the chain — pulled over the fabric
        (kv_fabric_blocks) or promoted from a proactive push
        (kv_promoted_blocks) — or re-prefill locally (peer died
        mid-fetch, digest evicted, pool full)?"""
        imported = isinstance(payload, dict) and (
            payload.get("kv_fabric_blocks")
            or payload.get("kv_promoted_blocks")
        )
        self._m_handoffs.labels(
            outcome="handoff" if imported else "cold_fallback"
        ).inc()

    # -- fleet trace / flight assembly ---------------------------------------
    def collect_trace(self, trace_id: str) -> list:
        """The full cross-process span list for `trace_id`: this router's
        own spans plus every replica's (GET /debug/traces/{id} — the flat
        `spans` field, one schema fleet-wide). Unreachable or evicted
        stores degrade to a PARTIAL trace — assemble_tree surfaces the
        orphaned subtrees as extra roots — never an error."""
        spans = self.trace_store.get(trace_id)
        for rep in self.replicas:
            try:
                with urllib.request.urlopen(
                    rep.url + "/debug/traces/"
                    + urllib.parse.quote(trace_id, safe=""),
                    timeout=self.probe_timeout_s,
                ) as resp:
                    payload = json.loads(resp.read())
            except (urllib.error.URLError, OSError, ValueError):
                continue
            got = payload.get("spans") if isinstance(payload, dict) else None
            if isinstance(got, list):
                spans.extend(s for s in got if isinstance(s, dict))
        return spans

    def collect_flight(self) -> dict:
        """Every replica's flight-recorder dump, keyed by replica id
        (the router itself keeps no ring — it is stateless glue)."""
        out = {}
        for rep in self.replicas:
            try:
                with urllib.request.urlopen(
                    rep.url + "/debug/flight",
                    timeout=self.probe_timeout_s,
                ) as resp:
                    out[rep.rid] = json.loads(resp.read())
            except (urllib.error.URLError, OSError, ValueError):
                out[rep.rid] = {"error": "unreachable"}
        return out

    # -- aggregate views -----------------------------------------------------
    def replica_health(self, rep: Replica) -> dict:
        entry = rep.snapshot()
        try:
            with urllib.request.urlopen(
                rep.url + "/health", timeout=self.probe_timeout_s
            ) as resp:
                entry["health"] = json.loads(resp.read())
                entry["reachable"] = True
        except (urllib.error.URLError, OSError, ValueError):
            entry["reachable"] = False
            return entry
        h = entry.get("health") or {}
        # class + residency discovery off the same poll: URL-joined
        # replicas specialize via their own --replica-class, and the
        # kv.resident_digests bootstrap lets the router steer fabric
        # pulls at a replica it has never routed traffic to
        cls = h.get("replica_class")
        if cls in ("prefill", "decode", "mixed"):
            rep.replica_class = cls
        kv = h.get("kv") or {}
        self.record_kv_residency(
            kv.get("resident_digests") or [], rep.rid, bootstrap=True
        )
        return entry

    def discover(self):
        """One /health sweep (class + residency bootstrap), best-effort.
        The CLI runs it at startup; /health aggregation repeats it on
        every poll."""
        for rep in self.replicas:
            self.replica_health(rep)

    def health(self) -> dict:
        replicas = {r.rid: self.replica_health(r) for r in self.replicas}
        n_ready = sum(r.state == READY for r in self.replicas)
        status = (
            "healthy" if n_ready == len(self.replicas)
            else ("degraded" if n_ready else "unhealthy")
        )
        with self._roll_lock:
            rolling = dict(self.rolling)
        return {
            "status": status,
            "role": "router",
            "version": __version__,
            "replicas_total": len(self.replicas),
            "replicas_ready": n_ready,
            "replicas": replicas,
            "rolling_restart": rolling,
        }

    def ready(self) -> bool:
        return any(r.state == READY for r in self.replicas)

    def stats(self) -> dict:
        with self._roll_lock:
            rolling = dict(self.rolling)
        return {
            "replicas": {r.rid: r.snapshot() for r in self.replicas},
            "residency_entries": self.residency_entries(),
            "kv_residency_entries": self.kv_residency_entries(),
            "fabric": self.fabric,
            "disaggregated": self.handoff_topology(),
            "rolling_restart": rolling,
        }

    # -- rolling restart -----------------------------------------------------
    def start_rolling_restart(self) -> dict:
        """Kick the rolling restart on a background thread. Returns a
        rejection dict ({"error": ...}) or the initial progress dict."""
        not_spawned = [r.rid for r in self.replicas if r.proc is None]
        if not_spawned:
            return {
                "error": "rolling restart requires router-spawned replicas "
                         f"(no subprocess for {not_spawned}); restart "
                         "URL-joined replicas out of band — the router's "
                         "probes handle ejection/readmission either way",
            }
        with self._roll_lock:
            if self.rolling["active"]:
                return {"error": "rolling restart already in progress"}
            self.rolling = {"active": True, "done": [], "current": None,
                            "error": None, "warm": {}}
        threading.Thread(
            target=self._rolling_restart, daemon=True, name="rolling-restart"
        ).start()
        with self._roll_lock:
            return dict(self.rolling)

    def _rolling_restart(self):
        try:
            for rep in self.replicas:
                with self._roll_lock:
                    self.rolling["current"] = rep.rid
                self._restart_one(rep)
                with self._roll_lock:
                    self.rolling["done"].append(rep.rid)
            log.info("rolling_restart_done",
                     replicas=[r.rid for r in self.replicas])
        except Exception as e:  # noqa: BLE001 - progress dict carries it
            log.error("rolling_restart_failed", error=str(e))
            with self._roll_lock:
                self.rolling["error"] = str(e)
        finally:
            with self._roll_lock:
                self.rolling["active"] = False
                self.rolling["current"] = None

    def _restart_one(self, rep: Replica):
        """One replica through the PR-5 drain path: stop routing to it,
        SIGTERM (its server flips readiness, finishes in-flight work,
        exits cleanly), respawn, wait for /ready, readmit."""
        with rep.lock:
            rep.state = DRAINING
            self._set_ready_gauge(rep)
        log.info("rolling_restart_draining", replica=rep.rid)
        rep.proc.send_signal(signal.SIGTERM)
        try:
            rep.proc.wait(timeout=self.drain_deadline_s)
        except subprocess.TimeoutExpired:
            # past the drain deadline the replica has broken its own
            # contract; reap it so the port frees for the respawn
            rep.proc.kill()
            rep.proc.wait(timeout=10)
        rep.proc = subprocess.Popen(
            rep.spawn_argv, env=rep.spawn_env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        self._wait_replica_ready(rep)
        # warm-handoff check: a replica started with --restore-dir
        # reloads its drained predecessor's shadowed KV (engine/
        # shadow.py) and reports restored_blocks in its stats — surfaced
        # per replica in /health.rolling_restart.warm so a rollout that
        # silently came back COLD (missing --restore-dir, config drift
        # invalidating the persisted shadow) is visible, not inferred
        # from TTFT regressions later
        warm = self._warm_handoff(rep)
        with self._roll_lock:
            self.rolling.setdefault("warm", {})[rep.rid] = warm
        with rep.lock:
            rep.state = READY
            rep.consecutive_failures = 0
            rep.cooldown_until = 0.0
            self._set_ready_gauge(rep)
        log.info("rolling_restart_replica_ready", replica=rep.rid, warm=warm)

    def _warm_handoff(self, rep: Replica) -> bool:
        """True when the respawned replica restored shadowed KV blocks
        (warm prefix cache); False on a cold start or an unreadable
        stats surface (never raises — warmth is an optimization)."""
        try:
            with urllib.request.urlopen(
                rep.url + "/stats", timeout=self.probe_timeout_s
            ) as resp:
                st = json.loads(resp.read().decode())
        except Exception:  # noqa: BLE001 - diagnostics only
            return False
        shadow = (st.get("continuous") or {}).get("shadow") or {}
        return bool(shadow.get("restored_blocks", 0))

    def _wait_replica_ready(self, rep: Replica, deadline_s: float = 300.0):
        t0 = time.time()
        while time.time() - t0 < deadline_s:
            if rep.proc.poll() is not None:
                raise RuntimeError(
                    f"{rep.rid} exited rc={rep.proc.returncode} during "
                    "rolling restart"
                )
            try:
                with urllib.request.urlopen(
                    rep.url + "/ready", timeout=self.probe_timeout_s
                ) as resp:
                    if resp.status == 200:
                        return
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.2)
        raise RuntimeError(f"{rep.rid} never became ready after respawn")


def _affinity_key(data: dict) -> str:
    """The prompt-head text the residency hash keys on: `prompt` on
    /generate and /v1/completions, the rendered message contents on chat
    (the replica-side chat template is deterministic, so equal message
    lists produce equal prompts — hashing the raw contents keys the same
    equivalence classes). Requests naming an adapter (`adapter` on
    /generate, `model` on the OpenAI routes) get an adapter-tagged key:
    adapter KV is conditioned on the adapter's weights, so the same
    prompt under two adapters must never share an affinity chain —
    mirroring the replica-side BlockPrefixIndex's adapter-rooted
    content keys."""
    adapter = data.get("adapter") or data.get("model")
    prefix = (
        f"\x1dadapter:{adapter}\x1d"
        if isinstance(adapter, str) and adapter else ""
    )
    p = data.get("prompt")
    if isinstance(p, str) and p:
        return prefix + p
    prompts = data.get("prompts")
    if isinstance(prompts, list) and prompts and isinstance(prompts[0], str):
        return prefix + prompts[0]
    msgs = data.get("messages")
    if isinstance(msgs, list):
        return prefix + "\x1e".join(
            str(m.get("role", "")) + ":" + str(m.get("content", ""))
            for m in msgs if isinstance(m, dict)
        )
    return ""


def _deadline_ms(data: dict, headers) -> Optional[float]:
    """The request's end-to-end deadline budget (ms) at router INGRESS:
    an inbound X-Request-Deadline-Ms (an upstream tier already started
    the clock) wins over the body's deadline_ms. The router burns this
    budget across failover attempts and relays the REMAINDER to the
    replica via the same header, so queueing and failover time count
    against the client's deadline instead of silently extending it."""
    hdr = headers.get("X-Request-Deadline-Ms")
    if hdr is not None:
        try:
            return float(hdr)
        except (TypeError, ValueError):
            pass
    raw = data.get("deadline_ms")
    if raw is None:
        return None
    try:
        dl = float(raw)
    except (TypeError, ValueError):
        return None  # the replica's parser owns the 400
    return dl if dl > 0 else None


def _deadline_exceeded_response() -> tuple:
    """(status, body, headers) for a budget spent inside the router —
    the same envelope a replica would emit, so clients see ONE shape."""
    return 504, json.dumps({
        "error": "Error: request exceeded its deadline_ms budget "
        "at the router",
        "status": "failed",
        "error_type": "deadline_exceeded",
    }).encode(), {}


def make_router_handler(router: Router):
    http_requests = router.metrics.counter(
        "dli_http_requests_total", "HTTP responses at the router edge",
        ("route", "method", "status"),
    )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        _rid: Optional[str] = None
        # inbound (traceparent) or freshly-rooted SpanContext, set per
        # POST; echoed as X-Trace-Id so clients can fetch their trace
        _trace_ctx: Optional[SpanContext] = None
        # child context under the router.request span — what rides the
        # traceparent header to replicas on dispatch/handoff/stream
        _span_ctx: Optional[SpanContext] = None

        def _count(self, code: int):
            http_requests.labels(
                route=_route_label(self.path.split("?")[0].rstrip("/") or "/"),
                method=self.command, status=str(code),
            ).inc()

        def _send(self, code: int, payload, content_type="application/json",
                  headers=None):
            body = (
                payload if isinstance(payload, bytes)
                else payload.encode() if isinstance(payload, str)
                else json.dumps(payload).encode()
            )
            self._count(code)
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            if self._rid:
                self.send_header("X-Request-Id", self._rid)
            if self._trace_ctx is not None:
                self.send_header("X-Trace-Id", self._trace_ctx.trace_id)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        # -- GET surface -----------------------------------------------------
        def do_GET(self):
            # keep-alive connections reuse this handler instance: a prior
            # POST's correlation ids must not leak into GET responses
            self._rid = None
            self._trace_ctx = None
            path = self.path.split("?")[0].rstrip("/") or "/"
            if path == "/":
                h = router.stats()
                rows = "".join(
                    f"<tr><td>{rid}</td><td>{s['url']}</td>"
                    f"<td>{s['state']}</td><td>{s['outstanding']}</td></tr>"
                    for rid, s in h["replicas"].items()
                )
                self._send(
                    200,
                    "<html><body style=\"font-family: monospace\">"
                    "<h1>distributed_llm_inference_tpu — router</h1>"
                    "<table border=\"1\" cellpadding=\"4\">"
                    "<tr><th>replica</th><th>url</th><th>state</th>"
                    f"<th>outstanding</th></tr>{rows}</table>"
                    "<p>POST /generate | /v1/completions | "
                    "/v1/chat/completions | /admin/rolling-restart</p>"
                    "</body></html>",
                    content_type="text/html",
                )
            elif path == "/health":
                self._send(200, router.health())
            elif path == "/ready":
                if router.ready():
                    self._send(200, {"ready": True})
                else:
                    self._send(
                        503, {"ready": False, "reason": "no_ready_replica"},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
            elif path == "/stats":
                self._send(200, router.stats())
            elif path == "/metrics":
                self._send(
                    200, router.metrics.render(),
                    content_type="text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/debug/flight":
                # the router keeps no flight recorder of its own
                # (stateless glue) — aggregate the replicas' rings
                self._send(200, {"replicas": router.collect_flight()})
            elif path.startswith("/debug/traces"):
                rest = path[len("/debug/traces"):].lstrip("/")
                if not rest:
                    self._send(200, {
                        "traces": router.trace_store.trace_ids(),
                        "stats": router.trace_store.stats(),
                    })
                    return
                trace_id = urllib.parse.unquote(rest)
                spans = router.collect_trace(trace_id)
                if not spans:
                    self._send(404, {"error": f"unknown trace {trace_id}"})
                    return
                if "format=chrome" in self.path.partition("?")[2]:
                    self._send(200, to_chrome_trace(spans))
                    return
                roots = assemble_tree(spans)
                self._send(200, {
                    "trace_id": trace_id,
                    "spans": spans,
                    "tree": roots,
                    "total_s": span_tree_total(roots),
                })
            elif path == "/v1/models":
                # proxy to any dispatchable replica (model list is
                # identical across a homogeneous fleet)
                rep, _ = router.pick("")
                if rep is None:
                    self._send(
                        503, {"error": "no healthy replica"},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
                    return
                try:
                    with urllib.request.urlopen(
                        rep.url + path, timeout=router.probe_timeout_s
                    ) as resp:
                        self._send(resp.status, resp.read())
                except (urllib.error.URLError, OSError) as e:
                    router.note_failure(rep, why=f"models: {e}")
                    self._send(502, {"error": f"upstream failed: {e}"})
            else:
                self._send(404, {"error": f"no route {path}"})

        # -- POST surface ----------------------------------------------------
        def do_POST(self):
            path = self.path.split("?")[0].rstrip("/")
            self._rid = (
                sanitize_request_id(self.headers.get("X-Request-Id"))
                or new_request_id()
            )
            # join the caller's trace (W3C traceparent) or root a fresh
            # one; a malformed header degrades to a fresh root
            self._trace_ctx = (
                parse_traceparent(self.headers.get("traceparent"))
                or SpanContext.new_root()
            )
            if path == "/admin/rolling-restart":
                res = router.start_rolling_restart()
                self._send(400 if res.get("error") else 202, res)
                return
            if path not in _FORWARD_ROUTES:
                self._send(404, {"error": f"no route {path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) or b"{}"
                data = json.loads(body)
                if not isinstance(data, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, json.JSONDecodeError):
                self._send(400, {"error": "invalid JSON body"})
                return
            tenant = data.get("tenant")
            tenant = tenant if isinstance(tenant, str) and tenant else None
            if not router.tenant_begin(tenant):
                # per-tenant inflight quota: the same overloaded
                # envelope + Retry-After a full replica queue answers,
                # so tenant backoff is server-directed identically
                self._send(
                    429,
                    {
                        "error": "Error: tenant inflight quota exceeded "
                                 "at the router",
                        "status": "failed", "error_type": "overloaded",
                        "tenant": tenant,
                    },
                    headers={"Retry-After": str(RETRY_AFTER_S)},
                )
                return
            try:
                ctx = self._trace_ctx
                with request_id_context(self._rid, ctx.trace_id):
                    # root span of the router hop: every downstream span
                    # (dispatch attempts, handoff, the replica's own
                    # replica.request) nests under it via traceparent
                    with router.trace_store.span(
                        "router.request", ctx,
                        attrs={"request_id": self._rid, "route": path},
                    ) as sp:
                        self._span_ctx = ctx.child(sp["span_id"])
                        self._dispatch_post(path, body, data)
            finally:
                router.tenant_end(tenant)

        def _dispatch_post(self, path: str, body: bytes, data: dict):
            deadline_ms = _deadline_ms(data, self.headers)
            affinity_key = _affinity_key(data)
            t0 = time.perf_counter()
            # disaggregated dispatch: phase 1 (prefill-only on a
            # prefill-class replica) runs BEFORE the stream split, so
            # streamed requests hand off transparently too — the client
            # sees one stream, served by the decode replica. Phase 1's
            # wall time burns the request's own deadline budget.
            hint = router.maybe_handoff(
                path, body, affinity_key, self._rid,
                deadline_ms=deadline_ms, trace_ctx=self._span_ctx,
            )
            if deadline_ms is not None:
                deadline_ms -= (time.perf_counter() - t0) * 1e3
            if data.get("stream") is True or data.get("stream") == "true":
                self._stream(path, body, affinity_key,
                             deadline_ms=deadline_ms, hint_headers=hint)
                return
            rep, status, rbody, headers, attempts = router.dispatch(
                path, body, affinity_key, self._rid,
                deadline_ms=deadline_ms, hint_headers=hint,
                trace_ctx=self._span_ctx,
            )
            fwd = {
                k: v for k, v in headers.items() if k == "Retry-After"
            }
            try:
                payload = json.loads(rbody)
            except (ValueError, json.JSONDecodeError):
                self._send(status, rbody, headers=fwd)
                return
            if hint is not None and status == 200:
                router.note_handoff_outcome(payload)
            if isinstance(payload, dict):
                # fold the router hop into the envelope's contiguous span
                # model: router_s = wall time here minus the replica's own
                # total, so the spans still sum to ≈ end-to-end
                elapsed = time.perf_counter() - t0
                tm = payload.get("timings")
                if isinstance(tm, dict):
                    tm["router_s"] = round(
                        max(0.0, elapsed - float(tm.get("total_s", 0.0))), 6
                    )
                    tm["total_s"] = round(elapsed, 6)
                if rep is not None:
                    payload["replica"] = rep.rid
                if attempts > 1:
                    payload["router_attempts"] = attempts
            self._send(status, payload, headers=fwd)

        def _stream(self, path: str, body: bytes, affinity_key: str,
                    deadline_ms: Optional[float] = None,
                    hint_headers: Optional[dict] = None):
            """Streamed requests: failover ONLY before the upstream
            stream opens; after the first forwarded byte the request is
            bound to its replica (re-dispatching would replay partial
            output — client.py's own stream-retry rule). hint_headers
            carry a handoff's phase-2 fabric hint; without one, each
            attempt derives its own from the residency view."""
            t_in = time.monotonic()
            tried: set = set()
            prev = None
            for _ in range(router.failover_attempts):
                hdrs = {"Content-Type": "application/json",
                        "X-Request-Id": self._rid}
                if self._span_ctx is not None:
                    # streamed attempts join under the router.request
                    # span (which stays open across the whole stream —
                    # do_POST's contextmanager closes it after we return)
                    hdrs["traceparent"] = self._span_ctx.header()
                if deadline_ms is not None:
                    left = deadline_ms - (time.monotonic() - t_in) * 1e3
                    if left <= 0:
                        st, bd, _hd = _deadline_exceeded_response()
                        self._send(st, json.loads(bd))
                        return
                    hdrs["X-Request-Deadline-Ms"] = f"{left:.0f}"
                rep, digests = router.pick(affinity_key, exclude=tried,
                                           role="decode")
                if rep is None:
                    break
                hint = (
                    hint_headers if hint_headers is not None
                    else router._kv_hint(digests, rep)
                )
                if hint:
                    hdrs.update(hint)
                    if hint_headers is not None:
                        # phase-2 envelope is NDJSON/SSE the router never
                        # parses: count the handoff by its own outcome
                        router._m_handoffs.labels(outcome="stream").inc()
                        hint_headers = None  # once per request
                tried.add(rep.rid)
                if prev is not None:
                    router._m_failovers.labels(replica=prev.rid).inc()
                req = urllib.request.Request(
                    rep.url + path, data=body, headers=hdrs, method="POST",
                )
                router._begin(rep)
                try:
                    upstream = urllib.request.urlopen(
                        req, timeout=router.request_timeout_s
                    )
                except urllib.error.HTTPError as e:
                    router._end(rep)
                    router._m_requests.labels(
                        replica=rep.rid, code=str(e.code)
                    ).inc()
                    if e.code in (429, 503):
                        ra = parse_retry_after(e.headers.get("Retry-After"))
                        with rep.lock:
                            rep.cooldown_until = time.monotonic() + (
                                ra if ra is not None else float(RETRY_AFTER_S)
                            )
                        if e.code == 503:
                            router.note_failure(rep, why="503")
                        prev = rep
                        continue  # pre-stream rejection: zero output sent
                    self._send(
                        e.code, e.read(),
                        headers={
                            k: v for k, v in e.headers.items()
                            if k == "Retry-After"
                        },
                    )
                    return
                except (urllib.error.URLError, OSError,
                        http.client.HTTPException) as e:
                    router._end(rep)
                    router._m_requests.labels(
                        replica=rep.rid, code="connect_error"
                    ).inc()
                    router.note_failure(rep, why=f"stream: {e}")
                    prev = rep
                    continue  # connect failure: stream never opened
                try:
                    router._m_requests.labels(
                        replica=rep.rid, code=str(upstream.status)
                    ).inc()
                    self._count(upstream.status)
                    self.send_response(upstream.status)
                    self.send_header(
                        "Content-Type",
                        upstream.headers.get(
                            "Content-Type", "application/x-ndjson"
                        ),
                    )
                    if self._rid:
                        self.send_header("X-Request-Id", self._rid)
                    if self._trace_ctx is not None:
                        self.send_header(
                            "X-Trace-Id", self._trace_ctx.trace_id
                        )
                    self.end_headers()
                    router.record_residency(digests, rep.rid)
                    while True:
                        try:
                            chunk = upstream.read(4096)
                        except (urllib.error.URLError, OSError,
                                http.client.HTTPException) as e:
                            # mid-stream upstream death: partial output
                            # is already with the client — NEVER
                            # re-dispatched; the truncated stream is the
                            # client's failure signal
                            router.note_failure(rep, why=f"mid_stream: {e}")
                            return
                        if not chunk:
                            break
                        try:
                            self.wfile.write(chunk)
                            self.wfile.flush()
                        except (BrokenPipeError, ConnectionResetError):
                            return  # client went away, replica innocent
                    router.note_success(rep)
                finally:
                    router._end(rep)
                    upstream.close()
                return
            self._send(
                503,
                {"error": "Error: no healthy replica", "status": "failed",
                 "error_type": "unavailable"},
                headers={"Retry-After": str(RETRY_AFTER_S)},
            )

    return Handler


class RouterServer:
    """Owns the HTTP listener + the Router; start()/shutdown() for tests,
    serve_forever() for the CLI."""

    def __init__(self, router: Router, host: str = "0.0.0.0",
                 port: int = 8000):
        self.router = router
        self.httpd = ThreadingHTTPServer(
            (host, port), make_router_handler(router)
        )
        self.port = self.httpd.server_address[1]

    def start(self) -> threading.Thread:
        self.router.start_prober()
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        return t

    def serve_forever(self):
        from ..utils.logging import configure

        configure()
        self.router.start_prober()
        self.install_signal_handlers()
        log.info(
            "router_serving", port=self.port,
            replicas=[r.url for r in self.router.replicas],
        )
        print(
            f"🔀 router on :{self.port} over "
            f"{len(self.router.replicas)} replicas — /generate /health "
            f"/ready /metrics /admin/rolling-restart"
        )
        self.httpd.serve_forever()

    def install_signal_handlers(self):
        def _on_term(signum, frame):
            threading.Thread(target=self.shutdown, daemon=True).start()

        signal.signal(signal.SIGTERM, _on_term)

    def shutdown(self):
        self.router.close()
        self.httpd.shutdown()
        self.httpd.server_close()
        # forward the shutdown to router-spawned replicas (their own
        # SIGTERM handler runs the PR-5 graceful drain)
        for rep in self.router.replicas:
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.send_signal(signal.SIGTERM)


def _free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn_replicas(n: int, spawn_args, host: str = "127.0.0.1",
                   ready_deadline_s: float = 300.0, env=None,
                   replica_class: str = "mixed",
                   name_prefix: str = "r") -> list:
    """Spawn N engine servers as subprocesses on free ports and wait for
    every /ready. Each replica remembers its argv/env so rolling restarts
    can respawn it identically. replica_class != "mixed" appends
    --replica-class to every spawn (and tags the router-side Replica), so
    --spawn-prefill/--spawn-decode build a disaggregated fleet from one
    argument string."""
    import os

    replicas = []
    for i in range(n):
        port = _free_port(host)
        argv = [
            sys.executable, "-m",
            "distributed_llm_inference_tpu.serving.server",
            "--host", host, "--port", str(port), *spawn_args,
        ]
        if replica_class != "mixed":
            argv += ["--replica-class", replica_class]
        spawn_env = dict(os.environ if env is None else env)
        proc = subprocess.Popen(
            argv, env=spawn_env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        replicas.append(Replica(
            f"{name_prefix}{i}", f"http://{host}:{port}", proc=proc,
            spawn_argv=argv, spawn_env=spawn_env,
            replica_class=replica_class,
        ))
    deadline = time.time() + ready_deadline_s
    for rep in replicas:
        while True:
            if rep.proc.poll() is not None:
                raise SystemExit(
                    f"replica {rep.rid} exited rc={rep.proc.returncode} "
                    "during startup"
                )
            try:
                with urllib.request.urlopen(
                    rep.url + "/ready", timeout=5
                ) as resp:
                    if resp.status == 200:
                        break
            except (urllib.error.URLError, OSError):
                pass
            if time.time() > deadline:
                raise SystemExit(f"replica {rep.rid} never became ready")
            time.sleep(0.2)
        print(f"✅ replica {rep.rid} ready at {rep.url}")
    return replicas


def main(argv: Optional[list] = None):
    ap = argparse.ArgumentParser(
        description="distributed_llm_inference_tpu replica router"
    )
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument(
        "--replicas", default=None, metavar="URL,URL",
        help="join already-running engine servers (comma-separated base "
             "URLs). Rolling restarts need --spawn replicas; URL-joined "
             "ones are probed/ejected/readmitted but restarted out of band",
    )
    ap.add_argument(
        "--spawn", type=int, default=0, metavar="N",
        help="spawn N engine-server replicas as subprocesses on free "
             "ports (each gets --spawn-args), wait for every /ready, "
             "and SIGTERM them on router shutdown",
    )
    ap.add_argument(
        "--spawn-prefill", type=int, default=0, metavar="N",
        help="spawn N PREFILL-class replicas (--spawn-args plus "
             "--replica-class prefill): they take fresh long-prompt "
             "work and hand the finished prefix to a decode-class "
             "replica by chunk digest over the KV fabric",
    )
    ap.add_argument(
        "--spawn-decode", type=int, default=0, metavar="N",
        help="spawn N DECODE-class replicas (--spawn-args plus "
             "--replica-class decode): they run the token loops, "
             "pulling handed-off prefixes over the KV fabric",
    )
    ap.add_argument(
        "--no-fabric", action="store_true",
        help="disable KV-fabric hints and prefill->decode handoffs at "
             "the router (replicas may still serve /kv to each other "
             "out of band)",
    )
    ap.add_argument(
        "--handoff-min-bytes", type=int, default=192, metavar="BYTES",
        help="smallest prompt (bytes) worth a two-phase prefill->decode "
             "handoff; shorter prompts go straight to the decode tier",
    )
    ap.add_argument(
        "--no-kv-push", action="store_true",
        help="disable the proactive chain push at the prefill->decode "
             "handoff (X-KV-Push-To); phase 2 then always PULLS the "
             "chain from the prefill replica on demand",
    )
    ap.add_argument(
        "--spawn-args", default="", metavar="ARGS",
        help="argument string passed to every spawned replica's server "
             "CLI, e.g. \"--model tinyllama-1.1b --continuous 4 --warmup\"",
    )
    ap.add_argument("--probe-interval", type=float, default=2.0,
                    metavar="SECONDS",
                    help="active /ready probe period per replica")
    ap.add_argument("--probe-timeout", type=float, default=5.0)
    ap.add_argument(
        "--eject-threshold", type=int, default=3, metavar="N",
        help="consecutive connect/5xx failures (probe or proxied) before "
             "a replica is ejected; readmission is via half-open probes",
    )
    ap.add_argument(
        "--affinity-chunk", type=int, default=AFFINITY_CHUNK_BYTES,
        metavar="BYTES",
        help="prompt-head hash granularity for prefix-affinity routing "
             "(~ one KV block of text; 0 disables affinity)",
    )
    ap.add_argument("--affinity-entries", type=int, default=4096,
                    help="residency-map LRU bound (chunk-chain digests)")
    ap.add_argument("--request-timeout", type=float, default=200.0)
    ap.add_argument(
        "--drain-deadline", type=float, default=60.0, metavar="SECONDS",
        help="per-replica drain budget during a rolling restart (SIGTERM "
             "-> graceful drain; past this the replica is killed)",
    )
    ap.add_argument(
        "--failover-attempts", type=int, default=0, metavar="N",
        help="max replicas one request may try (0 = one try per replica)",
    )
    ap.add_argument(
        "--tenant-share", type=float, default=0.5, metavar="F",
        help="per-tenant inflight quota as a fraction of ALL router-"
             "inflight requests: a tenant at max(4, F * total) sheds "
             "with 429 + Retry-After before a replica is picked "
             "(requests without a 'tenant' field are never shed; 1.0 "
             "disables)",
    )
    args = ap.parse_args(argv)

    replicas = []
    if args.spawn > 0:
        replicas.extend(
            spawn_replicas(args.spawn, shlex.split(args.spawn_args))
        )
    if args.spawn_prefill > 0:
        replicas.extend(spawn_replicas(
            args.spawn_prefill, shlex.split(args.spawn_args),
            replica_class="prefill", name_prefix="p",
        ))
    if args.spawn_decode > 0:
        replicas.extend(spawn_replicas(
            args.spawn_decode, shlex.split(args.spawn_args),
            replica_class="decode", name_prefix="d",
        ))
    if args.replicas:
        for i, url in enumerate(u for u in args.replicas.split(",") if u):
            replicas.append(Replica(f"u{i}", url.strip()))
    if not replicas:
        raise SystemExit(
            "router needs --spawn/--spawn-prefill/--spawn-decode N "
            "and/or --replicas URL,URL"
        )
    router = Router(
        replicas,
        eject_threshold=args.eject_threshold,
        probe_interval_s=args.probe_interval,
        probe_timeout_s=args.probe_timeout,
        affinity_chunk=args.affinity_chunk,  # 0 = pure least-outstanding
        affinity_entries=args.affinity_entries,
        request_timeout_s=args.request_timeout,
        drain_deadline_s=args.drain_deadline,
        failover_attempts=args.failover_attempts or None,
        fabric=not args.no_fabric,
        handoff_min_bytes=args.handoff_min_bytes,
        kv_push=not args.no_kv_push,
        tenant_max_inflight_share=args.tenant_share,
    )
    # learn URL-joined replicas' classes + bootstrap digest residency
    # off one /health sweep (spawned replicas carry their class already)
    router.discover()
    try:
        RouterServer(router, args.host, args.port).serve_forever()
    finally:
        for rep in replicas:
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.send_signal(signal.SIGTERM)


if __name__ == "__main__":
    main()
