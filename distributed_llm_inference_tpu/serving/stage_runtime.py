"""Multi-process MPMD pipeline: one stage of layers per host process.

This is the deployment shape the source paper actually ran — an
orchestrator driving Worker1/Worker2 over HTTP, each worker holding a
contiguous slice of the model — grown into a supervised runtime. Where
`parallel/pipeline.py` keeps the whole pipeline inside ONE process as a
shard_map program (stages are mesh shards, hand-offs are ppermute), here
every stage is its OWN PROCESS with its own params slice and KV cache,
and the 1F1B wavefront (parallel/schedule.mpmd_1f1b_order) spans
processes over a pluggable stage transport:

  * `HttpStageTransport` — the CPU-CI loopback and the cross-machine
    DCN plane: npz activation windows over `POST /stage/step`, with the
    shared retry discipline (utils/retry.py), per-call deadlines, W3C
    `traceparent` propagation into each stage's span store, and
    deterministic fault points (`stage_send`/`stage_recv` in
    utils/faults.py) on both ends of every hop. With
    `wire_quant="int8"` the hidden-state bodies ship int8 rows + fp32
    per-row scales (ops/wire_quant.quantize_rows — the same EQuARX
    recipe as the in-process pp wire), and every crossing lands on
    `dli_pp_wire_bytes_total{path="stage"}` through the accounted
    links `stage-activation-dcn` / `stage-result-dcn`
    (analysis/comms.py WIRE_LINKS).
  * `DeviceStageTransport` — the real-hardware path: jax.distributed
    device-to-device transfers. Gated: constructing it off a
    multi-process jax.distributed fleet raises with guidance, so every
    test (and this whole module) runs in tier-1 on CPU.

Fault containment is per STAGE, composing with the supervisor (PR 5)
and warm-recovery (PR 9) disciplines at process granularity:

  * each stage serves `GET /stage/heartbeat` (a monotonic sequence
    number); the controller's monitor thread polls it and classifies a
    peer as live / wedged (HTTP unresponsive past the timeout while the
    process is alive) / dead (process exited or connection refused).
    Liveness feeds the frontend's `/ready` + `/health` (so the router's
    prober ejects and readmits the whole pipeline exactly like a
    replica) and the flight recorder.
  * a stage crash (kill -9 mid-decode) triggers fleet-wide salvage:
    survivors flush their shadow, the supervisor respawns the dead
    stage (restart budget bounds crash loops), the new process
    warm-restores per-request KV from `--restore-dir` (block-aligned
    boundary captures, engine/shadow.py's discipline at stage
    granularity), and the controller replays each in-flight request's
    token window [restored_pos, fed) through the WHOLE chain —
    survivors deterministically overwrite identical KV, the restored
    stage fills its gap — so greedy output is bit-identical to a
    fault-free run and a warm restore recomputes < block_size tokens
    per request.
  * `POST /admin/rolling-restart` (frontend) cycles one stage at a
    time through drain -> respawn -> `/ready` with dispatch paused only
    during each swap window: zero dropped requests under live load.

Because each stage process serves its own HTTP plane and owns its own
cache, the `--continuous`-style admission restriction documented in
serving/multihost.py does not apply here: arrival timing only ever
matters on the CONTROLLER, and stages see an explicit, replayable
(request_id, pos, window) stream.
"""

from __future__ import annotations

import argparse
import hashlib
import io
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..config import ModelConfig
from ..models import api as M
from ..models.registry import get_model_config
from ..utils import faults
from ..utils.logging import get_logger
from ..utils.metrics import MetricsRegistry
from ..utils.retry import RETRY_STATUSES, retry_delay
from ..utils.tokenizer import ByteTokenizer
from ..utils.tracing import (
    FlightRecorder, SpanContext, new_request_id, parse_traceparent,
)
from .trace_store import TraceStore

log = get_logger("stage_runtime")

RETRY_AFTER_S = 2
DEFAULT_BLOCK = 16
DEFAULT_MAX_REQUESTS = 8
DEFAULT_HB_INTERVAL_S = 0.25
DEFAULT_HB_TIMEOUT_S = 2.0
DEFAULT_STEP_DEADLINE_S = 30.0
DEFAULT_SALVAGE_TIMEOUT_S = 60.0


def _npz_bytes(arrays: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _npz_load(data: bytes) -> dict:
    with np.load(io.BytesIO(data)) as z:
        return {k: z[k] for k in z.files}


def _shadow_name(request_id: str) -> str:
    return hashlib.sha1(request_id.encode()).hexdigest()[:16] + ".npz"


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- stage worker: one process's slice of the model ---------------------------

class _ReqState:
    """One request's per-stage state. Mutated only by the stage worker
    under its lock."""

    __slots__ = ("cache", "pos", "flushed", "restored_from")

    def __init__(self, cache, pos: int = 0, flushed: int = 0,
                 restored_from: int = -1):
        self.cache = cache
        self.pos = pos
        self.flushed = flushed
        self.restored_from = restored_from


class SlotsFull(RuntimeError):
    """The stage's request-slot pool is exhausted (429 to the wire)."""


class StageWorker:
    """The model half of one stage process: a contiguous [lo, hi) layer
    slice, per-request KV caches, and block-aligned shadow capture.

    Every stage inits the FULL param pytree from the shared seed and
    keeps only its slice (plus embed on stage 0 and the norm/head on the
    last stage) — so any respawn of any stage reconstructs bit-identical
    weights with no checkpoint plumbing, which is what makes the salvage
    replay deterministic."""

    def __init__(self, cfg: ModelConfig, stage: int, n_stages: int, *,
                 seed: int = 0, max_seq: Optional[int] = None,
                 max_requests: int = DEFAULT_MAX_REQUESTS,
                 block_size: int = DEFAULT_BLOCK,
                 restore_dir: Optional[str] = None):
        from ..parallel.schedule import plan_stages

        import jax

        self.cfg = cfg
        self.stage = int(stage)
        self.n_stages = int(n_stages)
        ranges = plan_stages(cfg.n_layers, n_stages)
        self.lo, self.hi = ranges[self.stage]
        self.is_first = self.stage == 0
        self.is_last = self.stage == n_stages - 1
        self.max_seq = int(max_seq or cfg.max_seq_len)
        self.max_requests = int(max_requests)
        self.block_size = int(block_size)
        self.restore_dir = restore_dir
        self._shadow_base = (
            os.path.join(restore_dir, f"stage{self.stage}")
            if restore_dir else None
        )

        full = M.init_params(cfg, jax.random.PRNGKey(seed))
        self.layers = jax.tree.map(lambda a: a[self.lo:self.hi],
                                   full["layers"])
        head = {}
        if self.is_first or (self.is_last and cfg.tie_embeddings):
            head["embed"] = full["embed"]
        if self.is_last:
            head["final_norm"] = full["final_norm"]
            if not cfg.tie_embeddings:
                head["lm_head"] = full["lm_head"]
        self.head = head
        del full

        self._lock = threading.RLock()
        self._requests: dict = {}  # guarded-by: _lock
        self._restored: dict = {}  # guarded-by: _lock
        if self._shadow_base:
            os.makedirs(self._shadow_base, exist_ok=True)
            self._restore_all()

    # -- restore / shadow ----------------------------------------------------

    def _restore_all(self):
        """Reload every per-request shadow found in this stage's restore
        dir: the warm-recovery half of a respawn. Called from __init__
        only (no concurrent readers yet)."""
        for fname in sorted(os.listdir(self._shadow_base)):
            if not fname.endswith(".npz"):
                continue
            path = os.path.join(self._shadow_base, fname)
            try:
                z = _npz_load(open(path, "rb").read())
                rid = str(z["request_id"])
                pos = int(z["pos"])
            except Exception as e:  # corrupt shadow: cold-start that rid
                log.warning("shadow_unreadable", stage=self.stage,
                            file=fname, err=str(e))
                continue
            cache = M.init_kv_cache(self.cfg, 1, self.max_seq,
                                    n_layers=self.hi - self.lo)
            if pos > 0:
                import jax.numpy as jnp

                k = jnp.asarray(z["k"], self.cfg.jnp_dtype)
                v = jnp.asarray(z["v"], self.cfg.jnp_dtype)
                cache = {
                    "k": cache["k"].at[:, :, :, :pos, :].set(k),
                    "v": cache["v"].at[:, :, :, :pos, :].set(v),
                }
            with self._lock:
                self._requests[rid] = _ReqState(
                    cache, pos=pos, flushed=pos, restored_from=pos
                )
                self._restored[rid] = pos

    def _shadow_write(self, request_id: str, st: _ReqState, upto: int):
        """Persist [0, upto) of this request's K/V planes atomically.
        Caller holds the lock (writes are ordered per request)."""
        if not self._shadow_base or upto <= 0:
            return
        import jax

        k = np.asarray(jax.device_get(st.cache["k"][:, :, :, :upto, :]))
        v = np.asarray(jax.device_get(st.cache["v"][:, :, :, :upto, :]))
        path = os.path.join(self._shadow_base, _shadow_name(request_id))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_npz_bytes({
                "request_id": np.str_(request_id),
                "pos": np.int64(upto), "k": k, "v": v,
            }))
        os.replace(tmp, path)
        st.flushed = upto

    def flush(self):
        """Persist every active request at its EXACT position (drain /
        salvage flush — graceful, so the replay window is empty)."""
        with self._lock:
            items = list(self._requests.items())
            for rid, st in items:
                if st.pos > st.flushed:
                    # jaxlint: disable=blocking-under-lock -- the worker lock IS this stage's serialization point; flush must see a quiesced cache
                    self._shadow_write(rid, st, st.pos)

    # -- compute -------------------------------------------------------------

    def step(self, request_id: str, pos: int, tokens=None, h=None) -> dict:
        """Run this stage's layer slice over one activation window.

        `pos` is CALLER-OWNED: the controller names the absolute write
        position of the window's first token, which is what makes
        salvage replay and post-restore overwrite idempotent (same
        (request_id, pos, window) in -> same cache out, bit-for-bit).
        Returns {"h": np.ndarray} for a non-last stage, {"token": int}
        (greedy argmax at the window's final position) for the last."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            st = self._requests.get(request_id)
            if st is None:
                if len(self._requests) >= self.max_requests:
                    raise SlotsFull(
                        f"stage {self.stage}: all {self.max_requests} "
                        f"request slots busy"
                    )
                st = _ReqState(M.init_kv_cache(
                    self.cfg, 1, self.max_seq, n_layers=self.hi - self.lo
                ))
                self._requests[request_id] = st
            if self.is_first:
                x = M.embed(self.cfg, self.head,
                            jnp.asarray(tokens, jnp.int32), pos)
            else:
                x = jnp.asarray(h, self.cfg.jnp_dtype)
            T = int(x.shape[1])
            if pos + T > self.max_seq:
                raise ValueError(
                    f"stage {self.stage}: window [{pos}, {pos + T}) "
                    f"exceeds max_seq {self.max_seq}"
                )
            out, st.cache = M.forward_layers(
                self.cfg, self.layers, x, st.cache, pos
            )
            st.pos = pos + T
            boundary = (st.pos // self.block_size) * self.block_size
            if boundary > st.flushed:
                # jaxlint: disable=blocking-under-lock -- the worker lock IS this stage's serialization point (the engine-lock argument at stage granularity); the boundary capture is part of the step
                self._shadow_write(request_id, st, boundary)
            if self.is_last:
                logits = M.unembed(self.cfg, self.head, out[:, -1:, :])
                return {"token": int(jnp.argmax(logits[0, -1]))}
            # jaxlint: disable=blocking-under-lock -- the worker lock IS this stage's serialization point; the fetch is the step's result
            return {"h": np.asarray(jax.device_get(out))}

    def close(self, request_id: str):
        """Free the request's slot and delete its shadow (a completed
        request must not resurrect on the next respawn)."""
        with self._lock:
            self._requests.pop(request_id, None)
            self._restored.pop(request_id, None)
        if self._shadow_base:
            try:
                os.remove(os.path.join(
                    self._shadow_base, _shadow_name(request_id)
                ))
            except FileNotFoundError:
                pass

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "stage": self.stage,
                "n_stages": self.n_stages,
                "layers": [self.lo, self.hi],
                "active": len(self._requests),
                "kv_slots": {
                    "total": self.max_requests,
                    "free": self.max_requests - len(self._requests),
                },
                "positions": {r: s.pos for r, s in self._requests.items()},
                "restored": dict(self._restored),
            }


# -- stage HTTP server --------------------------------------------------------

def serve_stage(worker: StageWorker, port: int, *,
                wire_quant: Optional[str] = None) -> ThreadingHTTPServer:
    """Build (not start) the stage process's HTTP plane."""
    registry = MetricsRegistry()
    http_requests = registry.counter(
        "dli_http_requests_total", "stage-plane responses by route/status",
        ("route", "status"),
    )
    traces = TraceStore(service=f"stage{worker.stage}")
    state = {
        "draining": False,  # guarded-by: _state_lock
        "hb_seq": 0,        # guarded-by: _state_lock
    }
    state_lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # stage stderr stays machine-readable
            pass

        def _count(self, code: int):
            http_requests.labels(
                route=self.path.split("?")[0], status=str(code)
            ).inc()

        def _send(self, code: int, payload, content_type="application/json",
                  headers=None):
            body = (
                payload if isinstance(payload, bytes)
                else json.dumps(payload).encode()
            )
            self._count(code)
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(n) if n else b""

        def do_GET(self):
            path = self.path.split("?")[0]
            if path == "/stage/heartbeat":
                # the wedge drill's injection point: a stage_recv rule
                # matching "heartbeat:" stalls/fails liveness itself
                try:
                    faults.check(
                        "stage_recv", tag=f"heartbeat:stage{worker.stage}"
                    )
                except faults.FaultError as e:
                    self._send(503, {"error": str(e)},
                               headers={"Retry-After": str(RETRY_AFTER_S)})
                    return
                with state_lock:
                    state["hb_seq"] += 1
                    seq = state["hb_seq"]
                self._send(200, {"stage": worker.stage, "seq": seq})
            elif path == "/ready":
                with state_lock:
                    draining = state["draining"]
                if draining:
                    self._send(503, {"ready": False, "draining": True},
                               headers={"Retry-After": str(RETRY_AFTER_S)})
                else:
                    self._send(200, {"ready": True, "stage": worker.stage})
            elif path == "/health":
                snap = worker.snapshot()
                with state_lock:
                    snap["draining"] = state["draining"]
                    snap["heartbeat_seq"] = state["hb_seq"]
                self._send(200, snap)
            elif path == "/metrics":
                self._send(200, registry.render().encode(),
                           content_type="text/plain; version=0.0.4")
            elif path == "/debug/traces":
                self._send(200, {
                    tid: traces.get(tid) for tid in traces.trace_ids()
                })
            else:
                self._send(404, {"error": f"unknown route {path}"})

        def do_POST(self):
            path = self.path.split("?")[0]
            if path == "/stage/step":
                self._step()
            elif path == "/stage/flush":
                worker.flush()
                self._send(200, {"flushed": True})
            elif path == "/stage/close":
                req = json.loads(self._body() or b"{}")
                worker.close(str(req.get("request_id", "")))
                self._send(200, {"closed": True})
            elif path == "/admin/drain":
                with state_lock:
                    state["draining"] = True
                worker.flush()
                self._send(200, {"draining": True})
            else:
                self._send(404, {"error": f"unknown route {path}"})

        def _step(self):
            rid = self.headers.get("X-Stage-Request-Id", "")
            pos = int(self.headers.get("X-Stage-Pos", "0"))
            quant = self.headers.get("X-Stage-Quant", "")
            body = self._body()
            with state_lock:
                draining = state["draining"]
            if draining:
                self._send(503, {"error_type": "draining"},
                           headers={"Retry-After": str(RETRY_AFTER_S)})
                return
            # receive-side fault point BEFORE any compute or state touch
            try:
                faults.check(
                    "stage_recv", tag=f"{rid}:step:stage{worker.stage}"
                )
            except faults.TransientFault as e:
                self._send(503, {"error": str(e)},
                           headers={"Retry-After": str(RETRY_AFTER_S)})
                return
            except faults.FatalFault as e:
                self._send(500, {"error": str(e)})
                return
            ctx = parse_traceparent(self.headers.get("traceparent"))
            ctx = ctx or SpanContext.new_root()
            try:
                with traces.span("stage.step", ctx,
                                 {"stage": worker.stage, "pos": pos}):
                    arrays = _npz_load(body)
                    if "tokens" in arrays:
                        out = worker.step(rid, pos, tokens=arrays["tokens"])
                    else:
                        if quant == "int8":
                            h = (arrays["q"].astype(np.float32)
                                 * arrays["s"][..., None])
                        else:
                            h = arrays["h"]
                        out = worker.step(rid, pos, h=h)
            except SlotsFull as e:
                self._send(429, {"error_type": "overloaded",
                                 "error": str(e)},
                           headers={"Retry-After": str(RETRY_AFTER_S)})
                return
            except Exception as e:  # surface, don't kill the handler thread
                self._send(500, {"error_type": "internal",
                                 "error": f"{type(e).__name__}: {e}"})
                return
            if "token" in out:
                self._send(200, {"token": out["token"]})
                return
            if quant == "int8":
                from ..ops.wire_quant import quantize_rows

                q, s = quantize_rows(out["h"])
                payload = _npz_bytes({
                    "q": np.asarray(q), "s": np.asarray(s),
                })
            else:
                payload = _npz_bytes({"h": out["h"]})
            self._send(200, payload,
                       content_type="application/octet-stream")

    srv = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    srv.daemon_threads = True
    return srv


def _watch_parent(srv: ThreadingHTTPServer, ppid: int):
    """A stage must not outlive its supervisor. A SIGKILLed controller
    never gets to reap its fleet, so every stage watches its parent pid:
    reparenting (getppid() changes) means the supervisor is gone, and the
    stage shuts its plane down instead of serving as an orphan forever."""
    while True:
        time.sleep(2.0)
        if os.getppid() != ppid:
            log.info("stage_orphaned", was_ppid=ppid)
            srv.shutdown()
            return


def stage_main(args) -> int:
    """CLI entry for one stage process (see main() for the flags)."""
    faults.arm_from_env()
    cfg = get_model_config(args.model)
    worker = StageWorker(
        cfg, args.stage, args.stages, seed=args.seed,
        max_seq=args.max_seq or None, max_requests=args.max_requests,
        block_size=args.block_size, restore_dir=args.restore_dir,
    )
    srv = serve_stage(worker, args.port, wire_quant=args.wire_quant)
    log.info("stage_serving", stage=args.stage, stages=args.stages,
             lo=worker.lo, hi=worker.hi, port=args.port)
    threading.Thread(
        target=_watch_parent, args=(srv, os.getppid()), daemon=True,
    ).start()
    try:
        srv.serve_forever(poll_interval=0.1)
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return 0


# -- stage transport ----------------------------------------------------------

class StageStepError(RuntimeError):
    """A chain hop failed (after transport-level retries). `.stage` names
    the hop so the controller can classify/salvage."""

    def __init__(self, stage: int, msg: str):
        super().__init__(msg)
        self.stage = stage


class HttpStageTransport:
    """The DCN stage plane: npz windows over POST /stage/step with the
    shared retry/backoff discipline, deadlines, traceparent propagation,
    deterministic fault points, optional int8 wire quantization, and
    accounted wire bytes."""

    def __init__(self, *, wire_quant: Optional[str] = None,
                 deadline_s: float = DEFAULT_STEP_DEADLINE_S,
                 registry: Optional[MetricsRegistry] = None):
        if wire_quant not in (None, "int8"):
            raise ValueError(f"wire_quant must be None or 'int8', "
                             f"got {wire_quant!r}")
        self.wire_quant = wire_quant
        self.deadline_s = float(deadline_s)
        self.registry = registry or MetricsRegistry()
        self._wire_bytes = self.registry.counter(
            "dli_pp_wire_bytes_total",
            "inter-stage activation bytes shipped on the pp/sp wire, by "
            "transfer family", ("path",),
        )

    def _account_link(self, name: str, nbytes: int):
        """Runtime byte accounting for one accounted WIRE_LINKS row —
        the literal first argument at each call site below IS the
        contract analysis/comms.link_call_sites verifies (same seam as
        parallel/pipeline.py's static accounting and kv_fabric's
        runtime counts)."""
        del name  # the literal is for the comms-contract checker
        self._wire_bytes.labels(path="stage").inc(nbytes)

    def _request(self, url: str, data: Optional[bytes], headers: dict,
                 timeout_s: float, method: str = "POST"):
        req = urllib.request.Request(url, data=data, headers=headers,
                                     method=method)
        return urllib.request.urlopen(req, timeout=timeout_s)

    def get_json(self, addr: str, path: str, timeout_s: float = 5.0) -> dict:
        with self._request(f"http://{addr}{path}", None, {}, timeout_s,
                           method="GET") as resp:
            return json.loads(resp.read().decode())

    def post_json(self, addr: str, path: str, obj: dict,
                  timeout_s: float = 10.0) -> dict:
        body = json.dumps(obj).encode()
        with self._request(
            f"http://{addr}{path}", body,
            {"Content-Type": "application/json"}, timeout_s,
        ) as resp:
            return json.loads(resp.read().decode())

    def step(self, addr: str, stage: int, request_id: str, pos: int, *,
             tokens=None, h=None, ctx: Optional[SpanContext] = None,
             deadline_s: Optional[float] = None) -> dict:
        """One hop: ship the window to `stage`, return {"h": ...} or
        {"token": int}. Retries 429/503 with the shared backoff until
        the deadline; any other failure raises StageStepError."""
        faults.check("stage_send", tag=f"{request_id}:step:stage{stage}")
        if tokens is not None:
            body = _npz_bytes({"tokens": np.asarray(tokens, np.int32)})
            quant = ""
        elif self.wire_quant == "int8":
            from ..ops.wire_quant import quantize_rows

            q, s = quantize_rows(np.asarray(h, np.float32))
            body = _npz_bytes({"q": np.asarray(q), "s": np.asarray(s)})
            quant = "int8"
        else:
            body = _npz_bytes({"h": np.asarray(h, np.float32)})
            quant = ""
        if h is not None:
            self._account_link("stage-activation-dcn", len(body))
        headers = {
            "Content-Type": "application/octet-stream",
            "X-Stage-Request-Id": request_id,
            "X-Stage-Pos": str(pos),
        }
        if quant:
            headers["X-Stage-Quant"] = quant
        if ctx is not None:
            headers["traceparent"] = ctx.header()
        deadline = time.monotonic() + (
            self.deadline_s if deadline_s is None else deadline_s
        )
        attempt = 0
        while True:
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise StageStepError(
                    stage, f"stage {stage} step deadline exceeded"
                )
            try:
                with self._request(f"http://{addr}/stage/step", body,
                                   headers, budget) as resp:
                    raw = resp.read()
                    ctype = resp.headers.get("Content-Type", "")
                break
            except urllib.error.HTTPError as e:
                retry_after = e.headers.get("Retry-After") \
                    if e.headers else None
                e.close()
                if e.code not in RETRY_STATUSES:
                    raise StageStepError(
                        stage, f"stage {stage} step failed: HTTP {e.code}"
                    )
                delay = min(
                    retry_delay(attempt, retry_after),
                    max(0.0, deadline - time.monotonic()),
                )
                time.sleep(delay)
                attempt += 1
            except (urllib.error.URLError, socket.timeout,
                    ConnectionError, OSError) as e:
                raise StageStepError(
                    stage, f"stage {stage} unreachable: {e}"
                )
        faults.check("stage_recv", tag=f"{request_id}:reply:stage{stage}")
        if ctype.startswith("application/json"):
            out = json.loads(raw.decode())
            if "token" in out:
                self._account_link("stage-result-dcn", len(raw))
            return out
        self._account_link("stage-activation-dcn", len(raw))
        arrays = _npz_load(raw)
        if "q" in arrays:
            h = arrays["q"].astype(np.float32) * arrays["s"][..., None]
            return {"h": h}
        return {"h": arrays["h"]}


class DeviceStageTransport:
    """The real-hardware stage plane: jax.distributed device-to-device
    transfers between stage processes (no host round-trip, no npz).

    Gated on an initialized multi-process jax.distributed fleet — on a
    single-process CPU run (CI, dev boxes) constructing it raises with
    the HTTP loopback as the guidance, so the entire MPMD surface stays
    testable in tier-1."""

    def __init__(self):
        import jax

        if jax.process_count() <= 1:
            raise RuntimeError(
                "DeviceStageTransport needs an initialized multi-process "
                "jax.distributed fleet (jax.process_count() > 1); on a "
                "single process use HttpStageTransport — the CPU-CI "
                "loopback with the same contract"
            )
        raise NotImplementedError(
            "device-to-device stage transfers are pending the TPU "
            "bringup of this runtime; HttpStageTransport carries the "
            "full contract (deadlines, retry, salvage) over DCN"
        )


# -- supervisor: spawn/respawn stage processes --------------------------------

class StageSupervisor:
    """Owns the stage subprocesses: spawn from a recorded argv recipe,
    reap, respawn (the router's replica-respawn discipline at stage
    granularity), with a restart budget bounding crash loops."""

    def __init__(self, model: str, n_stages: int, ports, *,
                 seed: int = 0, max_seq: int = 0,
                 max_requests: int = DEFAULT_MAX_REQUESTS,
                 block_size: int = DEFAULT_BLOCK,
                 restore_dir: Optional[str] = None,
                 wire_quant: Optional[str] = None,
                 restart_budget: int = 3, env: Optional[dict] = None):
        self.model = model
        self.n_stages = int(n_stages)
        self.ports = list(ports)
        if len(self.ports) != self.n_stages:
            raise ValueError("need one port per stage")
        self.restart_budget = int(restart_budget)
        self.env = dict(env) if env else None
        self._argv_extra = []
        if max_seq:
            self._argv_extra += ["--max-seq", str(max_seq)]
        if restore_dir:
            self._argv_extra += ["--restore-dir", restore_dir]
        if wire_quant:
            self._argv_extra += ["--wire-quant", wire_quant]
        self._argv_extra += [
            "--seed", str(seed), "--max-requests", str(max_requests),
            "--block-size", str(block_size),
        ]
        self._lock = threading.Lock()
        self._procs: dict = {}     # guarded-by: _lock
        self._restarts: dict = {}  # guarded-by: _lock

    def addr(self, stage: int) -> str:
        return f"127.0.0.1:{self.ports[stage]}"

    def spawn_argv(self, stage: int) -> list:
        return [
            sys.executable, "-m",
            "distributed_llm_inference_tpu.serving.stage_runtime",
            "--stage", str(stage), "--stages", str(self.n_stages),
            "--model", self.model, "--port", str(self.ports[stage]),
        ] + self._argv_extra

    def spawn(self, stage: int) -> subprocess.Popen:
        proc = subprocess.Popen(
            self.spawn_argv(stage), env=self.env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        with self._lock:
            self._procs[stage] = proc
        return proc

    def spawn_all(self):
        for s in range(self.n_stages):
            self.spawn(s)

    def proc(self, stage: int) -> Optional[subprocess.Popen]:
        with self._lock:
            return self._procs.get(stage)

    def proc_alive(self, stage: int) -> bool:
        p = self.proc(stage)
        return p is not None and p.poll() is None

    def stop(self, stage: int, *, kill: bool = False,
             timeout_s: float = 10.0):
        p = self.proc(stage)
        if p is None:
            return
        if p.poll() is None:
            if kill:
                p.kill()
            else:
                p.terminate()
        try:
            p.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=timeout_s)

    def respawn(self, stage: int) -> subprocess.Popen:
        """Reap whatever is left of the stage and start a fresh process
        from the recorded recipe. Raises once the restart budget for
        this stage is exhausted (a stage that dies on every respawn is a
        poisoned deployment, not a transient)."""
        with self._lock:
            used = self._restarts.get(stage, 0)
            if used >= self.restart_budget:
                raise RuntimeError(
                    f"stage {stage} restart budget exhausted "
                    f"({used}/{self.restart_budget})"
                )
            self._restarts[stage] = used + 1
        self.stop(stage, kill=True)
        return self.spawn(stage)

    def shutdown(self):
        for s in range(self.n_stages):
            self.stop(s, kill=True, timeout_s=5.0)


# -- controller ---------------------------------------------------------------

class _CtrlReq:
    """Controller-side request state: the authoritative token stream
    (prompt + accepted generations) and how much of it every stage has
    ingested — exactly the info salvage replay needs."""

    __slots__ = ("toks", "fed", "prompt_len", "ctx", "done")

    def __init__(self, toks, prompt_len: int, ctx: SpanContext):
        self.toks = list(toks)
        self.fed = 0
        self.prompt_len = prompt_len
        self.ctx = ctx
        self.done = False


class MPMDPipeline:
    """The orchestrator: drives token windows through the stage chain,
    monitors heartbeats, and runs salvage / rolling restarts.

    Drivers (one per in-flight request, e.g. frontend handler threads)
    call start()/step_once()/finish(); overlap across requests IS the
    1F1B wavefront — each stage serializes its own compute, so request B
    occupies stage 0 while request A is on stage 1
    (parallel/schedule.mpmd_1f1b_order is the closed form of this
    ordering). Maintenance (salvage, rolling restart) takes a
    leadership flag, clears the dispatch gate, does its HTTP work with
    NO lock held, and reopens the gate — drivers just wait on the gate
    and retry, which is what makes a stage swap invisible to callers."""

    def __init__(self, supervisor: StageSupervisor, *,
                 transport: Optional[HttpStageTransport] = None,
                 tokenizer=None, eos_id: Optional[int] = None,
                 hb_interval_s: float = DEFAULT_HB_INTERVAL_S,
                 hb_timeout_s: float = DEFAULT_HB_TIMEOUT_S,
                 salvage_timeout_s: float = DEFAULT_SALVAGE_TIMEOUT_S,
                 auto_salvage: bool = False,
                 flight: Optional[FlightRecorder] = None):
        self.sup = supervisor
        self.n_stages = supervisor.n_stages
        self.transport = transport or HttpStageTransport()
        self.tokenizer = tokenizer or ByteTokenizer()
        self.eos_id = (self.tokenizer.eos_token_id
                       if eos_id is None else int(eos_id))
        self.hb_interval_s = float(hb_interval_s)
        self.hb_timeout_s = float(hb_timeout_s)
        self.salvage_timeout_s = float(salvage_timeout_s)
        self.auto_salvage = bool(auto_salvage)
        self.flight = flight or FlightRecorder()

        self._state_lock = threading.Lock()
        self._requests: dict = {}   # guarded-by: _state_lock
        self._liveness: dict = {}   # guarded-by: _state_lock
        self._maint = False         # guarded-by: _state_lock
        self._inflight = 0          # guarded-by: _state_lock
        self._last_salvage: dict = {}  # guarded-by: _state_lock
        self._running = threading.Event()
        self._running.set()
        self._stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start_fleet(self, *, ready_timeout_s: float = 60.0):
        """Spawn every stage and wait for /ready; then start the
        heartbeat monitor."""
        self.sup.spawn_all()
        for s in range(self.n_stages):
            self._wait_ready(s, ready_timeout_s)
        self.start_monitor()

    def start_monitor(self):
        t = threading.Thread(target=self._monitor, daemon=True,
                             name="stage-heartbeat-monitor")
        self._monitor_thread = t
        t.start()

    def shutdown(self):
        self._stop.set()
        t = self._monitor_thread
        if t is not None:
            t.join(timeout=5.0)
        self.sup.shutdown()

    def _wait_ready(self, stage: int, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        addr = self.sup.addr(stage)
        while time.monotonic() < deadline:
            try:
                out = self.transport.get_json(addr, "/ready", timeout_s=2.0)
                if out.get("ready"):
                    return
            except Exception:
                pass
            if not self.sup.proc_alive(stage):
                raise RuntimeError(
                    f"stage {stage} exited before becoming ready"
                )
            time.sleep(0.1)
        raise TimeoutError(f"stage {stage} not ready in {timeout_s}s")

    # -- liveness ------------------------------------------------------------

    def probe(self, stage: int) -> str:
        """One heartbeat probe -> 'live' | 'wedged' | 'dead'."""
        if not self.sup.proc_alive(stage):
            return "dead"
        try:
            self.transport.get_json(self.sup.addr(stage),
                                    "/stage/heartbeat",
                                    timeout_s=self.hb_timeout_s)
            return "live"
        except Exception:
            # unreachable: the process died under us, or it is alive but
            # not answering within the timeout (wedged)
            return "dead" if not self.sup.proc_alive(stage) else "wedged"

    def _monitor(self):
        while not self._stop.wait(self.hb_interval_s):
            for s in range(self.n_stages):
                status = self.probe(s)
                with self._state_lock:
                    prev = self._liveness.get(s, "live")
                    self._liveness[s] = status
                    maint = self._maint
                if status != prev:
                    self.flight.record("stage_liveness", stage=s,
                                       status=status, prev=prev)
                if status != "live" and prev == "live":
                    self.flight.record("heartbeat_lost", stage=s,
                                       status=status)
                if status == "dead" and self.auto_salvage and not maint:
                    self._ensure_salvaged(s)

    def liveness(self) -> dict:
        with self._state_lock:
            return dict(self._liveness)

    def ready(self) -> bool:
        """Pipeline readiness: every stage live, no maintenance window
        open. This is what the frontend's /ready serves — the router's
        prober ejects/readmits the pipeline through it."""
        with self._state_lock:
            if self._maint:
                return False
            states = [self._liveness.get(s, "live")
                      for s in range(self.n_stages)]
        return all(st == "live" for st in states)

    # -- request surface -----------------------------------------------------

    def start(self, prompt: str, *, request_id: Optional[str] = None) -> str:
        """Admit one request: prefill the prompt through the chain and
        accept the first greedy token. Returns the request id."""
        rid = request_id or new_request_id()
        toks = self.tokenizer.encode(prompt)
        ctx = SpanContext.new_root()
        req = _CtrlReq(toks, len(toks), ctx)
        with self._state_lock:
            self._requests[rid] = req
        first = self._chain_step(rid, req.toks, 0)
        with self._state_lock:
            req.fed = req.prompt_len
            req.toks.append(first)
            req.done = first == self.eos_id
        return rid

    def step_once(self, rid: str) -> Optional[int]:
        """One greedy decode step; None once the request is finished."""
        with self._state_lock:
            req = self._requests.get(rid)
        if req is None:
            raise KeyError(f"unknown request {rid!r}")
        if req.done:
            return None
        pos = req.fed
        tok = self._chain_step(rid, req.toks[pos:pos + 1], pos)
        with self._state_lock:
            req.fed = pos + 1
            req.toks.append(tok)
            req.done = tok == self.eos_id
        return tok

    def finish(self, rid: str) -> dict:
        """Release the request's slots on every stage and return its
        transcript."""
        with self._state_lock:
            req = self._requests.pop(rid, None)
        if req is None:
            raise KeyError(f"unknown request {rid!r}")
        for s in range(self.n_stages):
            try:
                self.transport.post_json(self.sup.addr(s), "/stage/close",
                                         {"request_id": rid})
            except Exception as e:
                log.warning("close_failed", rid=rid, stage=s, err=str(e))
        gen = req.toks[req.prompt_len:]
        if gen and gen[-1] == self.eos_id:
            gen = gen[:-1]
        return {
            "request_id": rid,
            "tokens": gen,
            "text": self.tokenizer.decode(gen),
        }

    def generate(self, prompt: str, max_new_tokens: int,
                 *, request_id: Optional[str] = None) -> dict:
        """Greedy end-to-end generation (the frontend's /generate)."""
        rid = self.start(prompt, request_id=request_id)
        for _ in range(max_new_tokens - 1):
            if self.step_once(rid) is None:
                break
        return self.finish(rid)

    # -- the chain -----------------------------------------------------------

    def _chain_once(self, rid: str, window, pos: int,
                    ctx: Optional[SpanContext]):
        """Drive one window through every stage, no retries. Returns the
        last stage's greedy token."""
        payload: dict = {"tokens": np.asarray([window], np.int32)}
        for s in range(self.n_stages):
            out = self.transport.step(
                self.sup.addr(s), s, rid, pos,
                tokens=payload.get("tokens"), h=payload.get("h"), ctx=ctx,
            )
            payload = out
        return payload["token"]

    def _chain_step(self, rid: str, window, pos: int) -> int:
        """One scheduled window: waits out maintenance windows, runs the
        chain, and on failure classifies the fleet (dead stage ->
        salvage; transient -> backoff) and retries. This loop is why a
        kill -9 or a dropped hop never surfaces to the caller."""
        with self._state_lock:
            req = self._requests.get(rid)
        ctx = req.ctx if req is not None else None
        deadline = time.monotonic() + self.salvage_timeout_s
        attempt = 0
        while True:
            self._running.wait(timeout=self.salvage_timeout_s)
            try:
                with self._state_lock:
                    self._inflight += 1
                try:
                    return self._chain_once(rid, window, pos, ctx)
                finally:
                    with self._state_lock:
                        self._inflight -= 1
            except (StageStepError, faults.FaultError) as e:
                stage = getattr(e, "stage", None)
                self.flight.record("step_failed", rid=rid,
                                   stage=-1 if stage is None else stage,
                                   err=str(e)[:160])
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"request {rid}: step at pos {pos} failed past "
                        f"the salvage deadline: {e}"
                    )
                dead = self._find_dead_stage()
                if dead is not None:
                    self._ensure_salvaged(dead)
                else:
                    time.sleep(retry_delay(attempt, None, base_s=0.05,
                                           cap_s=1.0))
                attempt += 1

    def _find_dead_stage(self) -> Optional[int]:
        for s in range(self.n_stages):
            if self.probe(s) == "dead":
                return s
        return None

    # -- maintenance: salvage + rolling restart ------------------------------

    def _take_maintenance(self) -> bool:
        with self._state_lock:
            if self._maint:
                return False
            self._maint = True
        self._running.clear()
        return True

    def _release_maintenance(self):
        with self._state_lock:
            self._maint = False
        self._running.set()

    def _wait_inflight_drained(self, timeout_s: float = 10.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._state_lock:
                n = self._inflight
            if n == 0:
                return
            time.sleep(0.01)

    def _ensure_salvaged(self, stage: int):
        """Fleet-wide salvage of a dead stage. Leader does the work;
        concurrent callers just wait for the dispatch gate to reopen
        (their step retry loop re-runs the failed window afterwards)."""
        if not self._take_maintenance():
            self._running.wait(timeout=self.salvage_timeout_s)
            return
        t0 = time.monotonic()
        self.flight.record("salvage_start", stage=stage)
        try:
            self._wait_inflight_drained()
            # 1. survivors flush their shadow (bounds THEIR replay
            #    window if the fault cascades)
            for s in range(self.n_stages):
                if s == stage:
                    continue
                try:
                    self.transport.post_json(self.sup.addr(s),
                                             "/stage/flush", {})
                except Exception as e:
                    log.warning("salvage_flush_failed", stage=s, err=str(e))
            # 2. respawn the dead stage (warm-restores from restore_dir)
            self.sup.respawn(stage)
            self.flight.record("stage_respawn", stage=stage)
            self._wait_ready(stage, self.salvage_timeout_s)
            health = self.transport.get_json(self.sup.addr(stage),
                                             "/health")
            restored = {str(k): int(v)
                        for k, v in (health.get("restored") or {}).items()}
            # 3. drop resurrected state for requests no longer in flight
            with self._state_lock:
                active = dict(self._requests)
            for rid in restored:
                if rid not in active:
                    try:
                        self.transport.post_json(
                            self.sup.addr(stage), "/stage/close",
                            {"request_id": rid},
                        )
                    except Exception:
                        pass
            # 4. replay each in-flight request's missing window through
            #    the WHOLE chain: survivors overwrite identical KV, the
            #    restored stage fills its gap — bit-identical by
            #    construction
            recomputed = {}
            for rid, req in active.items():
                p_r = min(restored.get(rid, 0), req.fed)
                if p_r < req.fed:
                    self._chain_once(rid, req.toks[p_r:req.fed], p_r,
                                     req.ctx)
                recomputed[rid] = req.fed - p_r
            with self._state_lock:
                self._liveness[stage] = "live"
                self._last_salvage = {
                    "stage": stage,
                    "secs": round(time.monotonic() - t0, 3),
                    "tokens_recomputed": recomputed,
                }
            self.flight.record(
                "salvage_done", stage=stage,
                secs=round(time.monotonic() - t0, 3),
                recomputed=sum(recomputed.values()),
            )
        finally:
            self._release_maintenance()

    def last_salvage(self) -> dict:
        with self._state_lock:
            return dict(self._last_salvage)

    def rolling_restart(self) -> dict:
        """Cycle every stage through drain -> respawn -> /ready, one at
        a time, pausing dispatch only during each swap window. In-flight
        requests stall briefly at the gate and resume — zero drops."""
        report = []
        for s in range(self.n_stages):
            while not self._take_maintenance():
                self._running.wait(timeout=self.salvage_timeout_s)
            t0 = time.monotonic()
            try:
                self._wait_inflight_drained()
                try:
                    self.transport.post_json(self.sup.addr(s),
                                             "/admin/drain", {})
                except Exception as e:
                    log.warning("rolling_drain_failed", stage=s, err=str(e))
                self.sup.stop(s)
                self.sup.spawn(s)
                self._wait_ready(s, self.salvage_timeout_s)
                health = self.transport.get_json(self.sup.addr(s),
                                                 "/health")
                restored = {
                    str(k): int(v)
                    for k, v in (health.get("restored") or {}).items()
                }
                with self._state_lock:
                    active = dict(self._requests)
                for rid in restored:
                    if rid not in active:
                        try:
                            self.transport.post_json(
                                self.sup.addr(s), "/stage/close",
                                {"request_id": rid},
                            )
                        except Exception:
                            pass
                recomputed = 0
                for rid, req in active.items():
                    p_r = min(restored.get(rid, 0), req.fed)
                    if p_r < req.fed:
                        self._chain_once(rid, req.toks[p_r:req.fed], p_r,
                                         req.ctx)
                    recomputed += req.fed - p_r
                with self._state_lock:
                    self._liveness[s] = "live"
                secs = round(time.monotonic() - t0, 3)
                self.flight.record("rolling_stage_done", stage=s,
                                   secs=secs, recomputed=recomputed)
                report.append({"stage": s, "secs": secs,
                               "tokens_recomputed": recomputed})
            finally:
                self._release_maintenance()
        self.flight.record("rolling_restart_done",
                           stages=len(report))
        return {"stages": report}

    def health(self) -> dict:
        per_stage = []
        for s in range(self.n_stages):
            entry: dict = {"stage": s,
                           "status": self.liveness().get(s, "unknown")}
            try:
                entry.update(self.transport.get_json(
                    self.sup.addr(s), "/health", timeout_s=2.0,
                ))
            except Exception as e:
                entry["error"] = str(e)
            per_stage.append(entry)
        with self._state_lock:
            active = len(self._requests)
            maint = self._maint
        return {
            "n_stages": self.n_stages,
            "ready": self.ready(),
            "maintenance": maint,
            "active_requests": active,
            "last_salvage": self.last_salvage(),
            "stages": per_stage,
        }


# -- frontend: the pipeline's public HTTP face --------------------------------

def serve_frontend(pipe: MPMDPipeline, port: int) -> ThreadingHTTPServer:
    """Thin HTTP front for the controller: /generate, /ready, /health,
    /metrics, /debug/flight, /admin/rolling-restart. It speaks the same
    readiness protocol as serving/server.py, so the router tier probes,
    ejects, and readmits an MPMD pipeline like any replica."""
    registry = pipe.transport.registry
    http_requests = registry.counter(
        "dli_frontend_requests_total",
        "frontend responses by route/status", ("route", "status"),
    )

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _count(self, code: int):
            http_requests.labels(
                route=self.path.split("?")[0], status=str(code)
            ).inc()

        def _send(self, code: int, payload,
                  content_type="application/json", headers=None):
            body = (
                payload if isinstance(payload, bytes)
                else json.dumps(payload).encode()
            )
            self._count(code)
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.split("?")[0]
            if path == "/ready":
                if pipe.ready():
                    self._send(200, {"ready": True})
                else:
                    self._send(503, {"ready": False,
                                     "liveness": pipe.liveness()},
                               headers={"Retry-After": str(RETRY_AFTER_S)})
            elif path == "/health":
                self._send(200, pipe.health())
            elif path == "/metrics":
                self._send(200, registry.render().encode(),
                           content_type="text/plain; version=0.0.4")
            elif path == "/debug/flight":
                self._send(200, pipe.flight.dump())
            else:
                self._send(404, {"error": f"unknown route {path}"})

        def do_POST(self):
            path = self.path.split("?")[0]
            n = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(n) if n else b"{}"
            if path == "/generate":
                try:
                    req = json.loads(body or b"{}")
                    # "max_tokens" is the key the main server's /generate
                    # takes; honor it here too so clients can't silently
                    # fall through to the default
                    out = pipe.generate(
                        str(req.get("prompt", "")),
                        int(req.get("max_new_tokens",
                                    req.get("max_tokens", 16))),
                    )
                    self._send(200, out)
                except Exception as e:
                    self._send(500, {"error_type": "internal",
                                     "error": f"{type(e).__name__}: {e}"})
            elif path == "/admin/rolling-restart":
                try:
                    self._send(200, pipe.rolling_restart())
                except Exception as e:
                    self._send(500, {"error_type": "internal",
                                     "error": f"{type(e).__name__}: {e}"})
            else:
                self._send(404, {"error": f"unknown route {path}"})

    srv = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    srv.daemon_threads = True
    return srv


def frontend_main(args) -> int:
    import signal

    faults.arm_from_env()
    # the frontend OWNS the stage subprocesses: a SIGTERM must unwind
    # through the finally below so pipe.shutdown() reaps them (otherwise
    # `kill <frontend>` orphans one process per stage)
    def _on_sigterm(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_sigterm)
    ports = ([int(p) for p in args.stage_ports.split(",")]
             if args.stage_ports
             else [free_port() for _ in range(args.stages)])
    sup = StageSupervisor(
        args.model, args.stages, ports, seed=args.seed,
        max_seq=args.max_seq, max_requests=args.max_requests,
        block_size=args.block_size, restore_dir=args.restore_dir,
        wire_quant=args.wire_quant,
    )
    pipe = MPMDPipeline(
        sup,
        transport=HttpStageTransport(wire_quant=args.wire_quant),
        auto_salvage=True,
    )
    pipe.start_fleet()
    srv = serve_frontend(pipe, args.port)
    log.info("frontend_serving", port=args.port, stages=args.stages,
             stage_ports=ports)
    try:
        srv.serve_forever(poll_interval=0.1)
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
        pipe.shutdown()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="stage_runtime",
        description="MPMD pipeline: stage process or 2+-stage frontend",
    )
    ap.add_argument("--frontend", action="store_true",
                    help="run the controller + HTTP frontend "
                         "(spawns the stage fleet)")
    ap.add_argument("--stage", type=int, default=0,
                    help="this process's stage index (stage mode)")
    ap.add_argument("--stages", type=int, required=True)
    ap.add_argument("--model", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--stage-ports", default="",
                    help="comma-separated stage ports (frontend mode; "
                         "default: ephemeral)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-seq", type=int, default=0)
    ap.add_argument("--max-requests", type=int,
                    default=DEFAULT_MAX_REQUESTS)
    ap.add_argument("--block-size", type=int, default=DEFAULT_BLOCK)
    ap.add_argument("--restore-dir", default=None)
    ap.add_argument("--wire-quant", choices=["int8"], default=None)
    args = ap.parse_args(argv)
    if args.frontend:
        return frontend_main(args)
    return stage_main(args)


if __name__ == "__main__":
    raise SystemExit(main())
