"""OpenAI-compatible serving surface: /v1/completions, /v1/chat/completions,
/v1/models.

Beyond-reference feature (the reference only serves its own ad-hoc
/generate schema, /root/reference/orchestration.py:331-356): any
OpenAI-SDK client can point its `base_url` at this server. This module is
pure translation — OpenAI request JSON -> engine kwargs, engine envelope ->
OpenAI response JSON (including SSE streaming chunks); it owns no model or
engine state, so the serving edge stays a single source of truth.

Mapping notes:
  * OpenAI has no top-k; the engine's top_k=0 disables that filter (the
    temperature/top_p semantics match the reference's sampling stack).
  * temperature == 0 means deterministic in OpenAI terms -> greedy argmax.
  * /v1/completions is raw continuation (no chat template);
    /v1/chat/completions renders the message list through the model
    family's template (engine/chat.format_chat_messages).
  * `response_format` on /v1/chat/completions ({"type": "json_object"} or
    {"type": "json_schema", "json_schema": {"schema": ...}}) compiles to a
    grammar constraint (constrain/) — the completion is guaranteed to
    parse as JSON (and validate against the schema subset) by traced
    token masking, not prompting.
  * Unsupported OpenAI params (best_of>1, suffix, echo outside the
    scoring form) are rejected with a 400 error object rather than
    silently ignored — silent acceptance would change sampling semantics
    behind the client's back.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Optional

# clients may omit max_tokens entirely; OpenAI's completions default
DEFAULT_MAX_TOKENS = 16


class OpenAIError(ValueError):
    """Carries an OpenAI-schema error body + HTTP status."""

    def __init__(self, message: str, status: int = 400,
                 err_type: str = "invalid_request_error",
                 param: Optional[str] = None):
        super().__init__(message)
        self.status = status
        self.body = {
            "error": {
                "message": message,
                "type": err_type,
                "param": param,
                "code": None,
            }
        }


def error_for_envelope(result: dict) -> "OpenAIError":
    """Engine failure envelope -> OpenAI error object (same status codes as
    the native /generate route)."""
    et = result.get("error_type")
    msg = result.get("error", "internal error")
    if et == "invalid_request":
        return OpenAIError(msg)
    if et == "timeout":
        return OpenAIError(msg, status=503, err_type="timeout_error")
    if et == "deadline_exceeded":
        # the request's own deadline_ms budget expired: 504, and the
        # router/clients must NOT retry (the budget is spent wherever
        # the retry lands)
        return OpenAIError(msg, status=504, err_type="timeout_error")
    if et == "cancelled":
        # client went away (or explicitly cancelled): nobody is waiting
        # for this body; 499 (nginx convention) so logs/metrics can tell
        # it from server faults, and the router never re-dispatches it
        return OpenAIError(msg, status=499, err_type="cancelled")
    if et == "overloaded":
        return OpenAIError(msg, status=429, err_type="overloaded_error")
    return OpenAIError(msg, status=500, err_type="server_error")


def _reject_unsupported(data: dict, *, chat: bool):
    def as_num(name, default, cast):
        v = data.get(name)
        if v is None:
            return default
        try:
            return cast(v)
        except (TypeError, ValueError):
            raise OpenAIError(
                f"{name} must be a number, got {v!r}", param=name
            ) from None

    n = as_num("n", 1, int)
    if not 1 <= n <= 16:
        raise OpenAIError("n must be between 1 and 16", param="n")
    if not chat and as_num("best_of", 1, int) != 1:
        raise OpenAIError("best_of > 1 is not supported", param="best_of")
    if not chat and data.get("echo"):
        # echo is supported ONLY in the scoring form (echo + logprobs +
        # an EXPLICIT max_tokens 0 — the lm-eval loglikelihood pattern).
        # An omitted max_tokens means "generate the default and echo",
        # which is not supported — reject rather than silently score.
        lp = data.get("logprobs")
        mt = as_num("max_tokens", None, int)
        if mt is None:
            mt = as_num("max_completion_tokens", None, int)
        if lp is None or lp is False or mt != 0:
            raise OpenAIError(
                "echo is only supported for scoring: echo=true with "
                "logprobs set and an explicit max_tokens=0", param="echo",
            )
    if not chat and data.get("suffix"):
        raise OpenAIError("suffix is not supported", param="suffix")
    for p in ("frequency_penalty", "presence_penalty"):
        v = as_num(p, 0.0, float)
        if not -2.0 <= v <= 2.0:
            # the OpenAI-documented range; values beyond it are almost
            # always a units mistake (e.g. a repetition_penalty sent here)
            raise OpenAIError(
                f"{p} must be between -2.0 and 2.0", param=p,
            )
    return n


def _common_kwargs(data: dict, cap: int, default_max: int = None) -> dict:
    """Shared OpenAI -> engine parameter translation. default_max: budget
    when the client omits max_tokens (legacy completions default is 16;
    chat defaults to the server cap — OpenAI's chat default is 'up to the
    context limit', and 16-token chat replies surprise every SDK user)."""
    if default_max is None:
        default_max = DEFAULT_MAX_TOKENS
    try:
        # explicit nulls fall through to the next source (clients migrating
        # to max_completion_tokens often send "max_tokens": null alongside)
        max_tokens = data.get("max_tokens")
        if max_tokens is None:
            max_tokens = data.get("max_completion_tokens")
        max_tokens = default_max if max_tokens is None else int(max_tokens)
        t = data.get("temperature")
        temperature = 1.0 if t is None else float(t)  # OpenAI: null = default
        tp = data.get("top_p")
        top_p = 1.0 if tp is None else float(tp)
        seed = data.get("seed")
        seed = int(seed) if seed is not None else None
        rep = float(data.get("repetition_penalty", 1.0))  # extension
        min_p = float(data.get("min_p", 0.0))  # extension
        freq = float(data.get("frequency_penalty") or 0.0)
        pres = float(data.get("presence_penalty") or 0.0)
    except (TypeError, ValueError) as e:
        raise OpenAIError(f"bad parameter: {e}") from None
    if temperature < 0:
        raise OpenAIError("temperature must be >= 0", param="temperature")
    if max_tokens < 1:
        # OpenAI rejects a zero/negative budget; the engine would silently
        # re-clamp it to 1 and bill a token the client asked not to pay for
        raise OpenAIError("max_tokens must be >= 1", param="max_tokens")
    kwargs = dict(
        max_tokens=min(max_tokens, cap),
        temperature=temperature if temperature > 0 else 1.0,
        top_k=0,  # OpenAI has no top-k filter
        top_p=top_p,
        greedy=temperature == 0.0,
        chat=False,  # chat routes pre-render the template themselves
        seed=int(seed) if seed is not None else None,
        min_p=min_p,
        repetition_penalty=rep,
        frequency_penalty=freq,
        presence_penalty=pres,
    )
    slo = data.get("slo_class")
    if slo is not None:
        # extension field (engine/scheduler.py SLO classes): admission
        # priority / prefill-budget share / shed policy on the continuous
        # fleet. The server validates the name against the configured
        # classes (unknown -> 400); here only the shape is checked.
        if not isinstance(slo, str):
            raise OpenAIError("slo_class must be a string",
                              param="slo_class")
        kwargs["slo_class"] = slo
    tenant = data.get("tenant")
    if tenant is not None:
        # extension field (multi-tenant serving): the fairness /
        # queue-quota identity on the continuous fleet — tenant-weighted
        # token apportionment within each SLO class, per-tenant queue
        # quota shed, per-tenant TTFT/TPOT EWMAs. Free-form label; no
        # server-side registry to validate against.
        if not isinstance(tenant, str) or not tenant:
            raise OpenAIError("tenant must be a non-empty string",
                              param="tenant")
        kwargs["tenant"] = tenant
    dl = data.get("deadline_ms")
    if dl is not None:
        # extension field: end-to-end deadline in milliseconds. Expiry
        # anywhere along the pipeline (queued, mid-prefill, mid-decode)
        # fails the request with a deadline_exceeded envelope (HTTP 504)
        # and frees its resources at the next launch boundary; the
        # router forwards the REMAINING budget via X-Request-Deadline-Ms.
        try:
            dl = float(dl)
        except (TypeError, ValueError):
            raise OpenAIError("deadline_ms must be a number",
                              param="deadline_ms") from None
        if dl <= 0:
            raise OpenAIError("deadline_ms must be > 0",
                              param="deadline_ms")
        kwargs["deadline_ms"] = dl
    stop = data.get("stop")
    if stop is not None:
        if isinstance(stop, str):
            stop = [stop]
        if not (isinstance(stop, list) and all(isinstance(s, str) for s in stop)):
            raise OpenAIError("stop must be a string or list of strings",
                              param="stop")
        if stop:
            kwargs["stop"] = stop
    lb = data.get("logit_bias")
    if lb:
        if not isinstance(lb, dict):
            raise OpenAIError("logit_bias must be an object of "
                              "token_id -> bias", param="logit_bias")
        try:
            lb = {int(k): float(v) for k, v in lb.items()}
        except (TypeError, ValueError):
            raise OpenAIError("logit_bias keys must be token ids and "
                              "values numbers", param="logit_bias") from None
        if any(not -100.0 <= v <= 100.0 for v in lb.values()):
            raise OpenAIError("logit_bias values must be in [-100, 100]",
                              param="logit_bias")
        kwargs["logit_bias"] = lb
    return kwargs


def _response_format_constraint(rf) -> Optional[dict]:
    """OpenAI `response_format` -> the engine's constraint spec, or None
    for type "text". Malformed objects are 400s — a silently-ignored
    response_format would hand the client unvalidated output under a
    guaranteed-JSON contract, the worst possible failure mode."""
    if not isinstance(rf, dict):
        raise OpenAIError("response_format must be an object",
                          param="response_format")
    t = rf.get("type")
    if t in (None, "text"):
        return None
    if t == "json_object":
        return {"json_object": True}
    if t == "json_schema":
        js = rf.get("json_schema")
        if not isinstance(js, dict):
            raise OpenAIError(
                "response_format.json_schema must be an object with a "
                "'schema' member", param="response_format",
            )
        schema = js.get("schema")
        if not isinstance(schema, dict):
            raise OpenAIError(
                "response_format.json_schema.schema must be a schema "
                "object", param="response_format",
            )
        return {"json_schema": schema}
    raise OpenAIError(f"unsupported response_format type {t!r}",
                      param="response_format")


def _check_n(n: int, prompts: list, kwargs: dict, stream: bool):
    """n > 1 serves as a ragged fleet of the same prompt — combinations
    the fleet cannot honor are rejected rather than silently degraded."""
    if n == 1:
        return
    if len(prompts) > 1:
        raise OpenAIError("n > 1 requires a single prompt", param="n")
    if stream:
        raise OpenAIError("n > 1 cannot be streamed", param="n")
    if kwargs.get("logprobs"):
        raise OpenAIError("n > 1 with logprobs is not supported", param="n")
    if kwargs.get("logit_bias"):
        raise OpenAIError("n > 1 with logit_bias is not supported", param="n")


def parse_completion(data: dict, cap: int):
    """POST /v1/completions body -> (prompts: list[str], kwargs, meta)."""
    n = _reject_unsupported(data, chat=False)
    prompt = data.get("prompt")
    if prompt is None:
        raise OpenAIError("you must provide a prompt", param="prompt")
    prompts = [prompt] if isinstance(prompt, str) else prompt
    if not (isinstance(prompts, list) and prompts
            and all(isinstance(p, str) and p for p in prompts)):
        raise OpenAIError(
            "prompt must be a non-empty string or list of non-empty strings",
            param="prompt",
        )
    if data.get("response_format") is not None:
        # structured output is a chat-completions feature (matching the
        # OpenAI surface); silent acceptance here would change sampling
        # semantics behind the client's back
        raise OpenAIError(
            "response_format is only supported on /v1/chat/completions",
            param="response_format",
        )
    meta = {"stream": bool(data.get("stream", False)), "n": n,
            "echo_score": bool(data.get("echo"))}
    if meta["echo_score"]:
        if meta["stream"] or n != 1 or len(prompts) != 1:
            raise OpenAIError(
                "echo scoring takes a single prompt, n=1, no streaming",
                param="echo",
            )
        # legacy logprobs int = top-N alternatives per position (lm-eval
        # reads them for is_greedy); OpenAI caps N at 5
        lp = data.get("logprobs")
        meta["score_top_n"] = min(int(lp), 5) if lp is not True else 0
        return prompts, {"max_tokens": 0}, meta
    kwargs = _common_kwargs(data, cap)
    lp = data.get("logprobs")
    if lp is not None and lp is not False:
        # legacy completions logprobs is an int (top-N); only the chosen
        # tokens' logprobs are produced here (top_logprobs omitted) — and
        # logprobs: 0 still means "return the chosen tokens' logprobs"
        if meta["stream"]:
            raise OpenAIError(
                "logprobs are not available on streamed responses",
                param="logprobs",
            )
        kwargs["logprobs"] = True
    _check_n(n, prompts, kwargs, meta["stream"])
    return prompts, kwargs, meta


def parse_chat(data: dict, render, cap: int):
    """POST /v1/chat/completions body -> (raw_prompt, kwargs, meta).

    render: message-list -> prompt string (the engine's render_chat, so
    cfg.chat_template — including "hf" jinja templates — applies here
    identically to the native route)."""
    n = _reject_unsupported(data, chat=True)
    messages = data.get("messages")
    if not (isinstance(messages, list) and messages
            and all(isinstance(m, dict) for m in messages)):
        raise OpenAIError("messages must be a non-empty list of objects",
                          param="messages")
    try:
        prompt = render(messages)
    except ValueError as e:
        raise OpenAIError(str(e), param="messages") from None
    kwargs = _common_kwargs(data, cap, default_max=cap)
    rf = data.get("response_format")
    if rf is not None:
        con = _response_format_constraint(rf)
        if con is not None:
            kwargs["constraint"] = con
    meta = {"stream": bool(data.get("stream", False)), "n": n}
    if data.get("top_logprobs"):
        # alternatives-per-position are not produced; silent empty lists
        # would masquerade as "no alternatives existed"
        raise OpenAIError("top_logprobs is not supported",
                          param="top_logprobs")
    if data.get("logprobs"):
        if meta["stream"]:
            raise OpenAIError(
                "logprobs are not available on streamed responses",
                param="logprobs",
            )
        kwargs["logprobs"] = True
    _check_n(n, [prompt], kwargs, meta["stream"])
    return prompt, kwargs, meta


def _finish_reason(entry: dict, requested_max: int) -> str:
    # the engine reports why generation ended (judged against its CLAMPED
    # budget, which this layer cannot reconstruct); the request-shaped
    # fallback covers older envelopes without the key
    fr = entry.get("finish_reason")
    if fr in ("stop", "length"):
        return fr
    if entry.get("stopped"):
        return "stop"
    return "length" if entry.get("tokens_generated", 0) >= requested_max else "stop"


def _usage(entries: list, prompt_once: bool = False) -> dict:
    # prompt_once: n>1 choices share one prompt — OpenAI bills it once
    if prompt_once and entries:
        pt = entries[0].get("prompt_tokens", 0)
    else:
        pt = sum(e.get("prompt_tokens", 0) for e in entries)
    ct = sum(e.get("tokens_generated", 0) for e in entries)
    return {"prompt_tokens": pt, "completion_tokens": ct,
            "total_tokens": pt + ct}


def _logprobs_obj(entry: dict) -> Optional[dict]:
    lps = entry.get("token_logprobs")
    if lps is None:
        return None
    return {"token_logprobs": lps,
            "tokens": entry.get("token_strings"),
            "top_logprobs": None,
            "text_offset": None}


def _observability_fields(request_id, timings, trace_id=None) -> dict:
    """Extension keys carried on every non-streaming response: the
    request_id (also echoed as the X-Request-Id header), the fleet
    trace_id (also the X-Trace-Id header — fetch the assembled tree at
    GET /debug/traces/{trace_id}), and the trace's stage breakdown.
    Extra top-level keys are OpenAI-SDK-safe (clients ignore unknown
    fields)."""
    out = {}
    if request_id:
        out["request_id"] = request_id
    if trace_id:
        out["trace_id"] = trace_id
    if timings:
        out["timings"] = timings
    return out


def completion_response(entries: list, model: str, kwargs: dict,
                        prompt_once: bool = False,
                        request_id: Optional[str] = None,
                        timings: Optional[dict] = None,
                        kv_extra: Optional[dict] = None,
                        trace_id: Optional[str] = None) -> dict:
    """Engine success envelope(s) -> one text_completion response.

    kv_extra: KV-fabric extension fields (kv_digests / kv_fabric_blocks /
    prefill_only) lifted from the engine envelope — OpenAI clients ignore
    unknown top-level keys, while the router reads them to learn
    digest->replica residency and score prefill->decode handoffs on the
    OpenAI routes exactly as on /generate (handoff-transparent
    streaming: phase 1 is forced non-streamed server-side, phase 2
    streams from the decode replica through the unchanged SSE path)."""
    choices = []
    for i, e in enumerate(entries):
        c = {
            "index": i,
            "text": e.get("response", ""),
            "finish_reason": _finish_reason(e, kwargs["max_tokens"]),
        }
        lp = _logprobs_obj(e)
        if lp is not None:
            c["logprobs"] = lp
        choices.append(c)
    return {
        "id": f"cmpl-{uuid.uuid4().hex[:24]}",
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": choices,
        "usage": _usage(entries, prompt_once),
        **_observability_fields(request_id, timings, trace_id),
        **(kv_extra or {}),
    }


def chat_response(entries: list, model: str, kwargs: dict,
                  prompt_once: bool = False,
                  request_id: Optional[str] = None,
                  timings: Optional[dict] = None,
                  kv_extra: Optional[dict] = None,
                  trace_id: Optional[str] = None) -> dict:
    choices = []
    for i, entry in enumerate(entries):
        choice = {
            "index": i,
            "message": {"role": "assistant",
                        "content": entry.get("response", "")},
            "finish_reason": _finish_reason(entry, kwargs["max_tokens"]),
        }
        lp = _logprobs_obj(entry)
        if lp is not None:
            # chat schema nests token logprobs under content
            toks = lp["tokens"] or [""] * len(lp["token_logprobs"] or [])
            choice["logprobs"] = {
                "content": [
                    {"token": t, "logprob": x, "top_logprobs": []}
                    for t, x in zip(toks, lp["token_logprobs"] or [])
                ]
            }
        choices.append(choice)
    return {
        "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": choices,
        "usage": _usage(entries, prompt_once),
        **_observability_fields(request_id, timings, trace_id),
        **(kv_extra or {}),
    }


def echo_score_response(result: dict, model: str) -> dict:
    """engine.score envelope -> OpenAI echoed text_completion (the
    loglikelihood-scoring reply: text = the prompt, logprobs over every
    prompt token, first entry None)."""
    return {
        "id": f"cmpl-{uuid.uuid4().hex[:24]}",
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "text": result["prompt"],
            "finish_reason": "length",
            "logprobs": {
                "tokens": result["token_strings"],
                "token_logprobs": result["token_logprobs"],
                # [None, {token: lp, ...}, ...] when top-N was requested
                # (lm-eval reads these for is_greedy)
                "top_logprobs": result.get("top_logprobs"),
                "text_offset": None,
            },
        }],
        "usage": {
            "prompt_tokens": result["prompt_tokens"],
            "completion_tokens": 0,
            "total_tokens": result["prompt_tokens"],
        },
    }


def models_response(model: str, created: int, adapters=()) -> dict:
    """The base model plus every registered runtime LoRA adapter —
    adapters are addressable as `model` on the OpenAI routes, so they
    must be discoverable where SDK clients look for model ids. `root`
    marks which base weights an adapter entry rides (vLLM convention)."""
    data = [{
        "id": model,
        "object": "model",
        "created": created,
        "owned_by": "distributed_llm_inference_tpu",
    }]
    for name in adapters:
        data.append({
            "id": name,
            "object": "model",
            "created": created,
            "owned_by": "distributed_llm_inference_tpu",
            "root": model,
        })
    return {"object": "list", "data": data}


# -- SSE streaming ----------------------------------------------------------


def sse(obj: Any) -> bytes:
    return b"data: " + json.dumps(obj).encode() + b"\n\n"


SSE_DONE = b"data: [DONE]\n\n"


def stream_events(events, model: str, kwargs: dict, chat: bool):
    """Adapt the continuous engine's NDJSON event stream ({"delta": ...}*,
    then the final envelope with done: true) into OpenAI SSE chunk dicts.

    Yields (bytes, final_envelope_or_None); the caller writes the bytes and
    can inspect the final envelope for error status. A failed request
    yields an OpenAI error payload as the terminal SSE event (the HTTP 200
    is already on the wire — OpenAI streams report late errors in-band).
    """
    rid = (f"chatcmpl-{uuid.uuid4().hex[:24]}" if chat
           else f"cmpl-{uuid.uuid4().hex[:24]}")
    obj = "chat.completion.chunk" if chat else "text_completion"
    created = int(time.time())

    def chunk(delta_text: Optional[str], finish: Optional[str]) -> dict:
        if chat:
            delta = {} if delta_text is None else {"content": delta_text}
            choice = {"index": 0, "delta": delta, "finish_reason": finish}
        else:
            choice = {"index": 0, "text": delta_text or "",
                      "finish_reason": finish}
        return {"id": rid, "object": obj, "created": created, "model": model,
                "choices": [choice]}

    if chat:
        yield sse(chunk(None, None) | {
            "choices": [{"index": 0, "delta": {"role": "assistant"},
                         "finish_reason": None}],
        }), None
    final = None
    streamed = ""
    for ev in events:
        if ev.get("done"):
            final = ev
            break
        d = ev.get("delta")
        if d:
            streamed += d
            yield sse(chunk(d, None)), None
    if final is None or final.get("status") != "success":
        err = error_for_envelope(final or {"error": "stream ended early"})
        yield sse(err.body), final
        yield SSE_DONE, final
        return
    # a request the continuous engine served via its solo fallback (seeded /
    # logprobs / speculative) emits no per-chunk deltas — only the final
    # envelope carries text. Flush whatever the deltas didn't cover so the
    # client always receives the full completion.
    response = final.get("response", "")
    if response.startswith(streamed) and len(response) > len(streamed):
        yield sse(chunk(response[len(streamed):], None)), None
    out = chunk(None, _finish_reason(final, kwargs["max_tokens"]))
    out["usage"] = _usage([final])
    yield sse(out), final
    yield SSE_DONE, final
