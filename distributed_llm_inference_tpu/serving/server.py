"""HTTP serving surface (reference L4).

Same API shape as the reference's Flask app
(/root/reference/orchestration.py:231-356): `POST /generate` (prompt,
max_tokens default 20 clamped to a cap, temperature default 0.7; top_k=50 /
top_p=0.9 defaults), `GET /health`, `GET /workers`, `GET /` HTML status page
— but on the stdlib ThreadingHTTPServer (no Flask/ngrok dependency), and
`/workers` reports pipeline-stage health from the mesh instead of polling
remote Flask processes over HTTP (the stages live inside this process's
compiled program; there is no remote worker to poll — that is the point).

HTTP survives only at this serving edge; it never sits between stages.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from . import kv_fabric as kvf

__version__ = "tpu_pipeline_v1"

# Reference defaults: orchestration.py:339-347 (max_tokens default 20, cap
# 30) and 353-354 (top_k 50, top_p 0.9). The cap is configurable here.
DEFAULT_MAX_TOKENS = 20
DEFAULT_TEMPERATURE = 0.7
DEFAULT_TOP_K = 50
DEFAULT_TOP_P = 0.9


def _parse_bool(v, name: str) -> bool:
    """Strict JSON-ish bool: bool(\"false\") is True, which would silently
    invert the caller's intent — reject non-bool junk with a 400 instead."""
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        low = v.strip().lower()
        if low in ("true", "1", "yes"):
            return True
        if low in ("false", "0", "no"):
            return False
    raise ValueError(f"{name} must be a boolean, got {v!r}")


def _status_html(engine) -> str:
    h = engine.health()
    stages = engine.backend.health()
    rows = "".join(
        f"<tr><td>stage {s['stage']}</td><td>{', '.join(s['devices'])}</td>"
        f"<td>{s.get('layers', '-')}</td><td>{s['status']}</td></tr>"
        for s in stages
    )
    return f"""<html><head><title>distributed_llm_inference_tpu</title></head>
<body style="font-family: monospace; margin: 2em;">
<h1>distributed_llm_inference_tpu — orchestrator</h1>
<p>status: <b>{h['status']}</b> | model: <b>{h['model']}</b> |
backend: <b>{h['backend']}</b> | stages: <b>{h['n_stages']}</b> |
requests served: <b>{h['requests_served']}</b></p>
<table border="1" cellpadding="4">
<tr><th>stage</th><th>devices</th><th>layers</th><th>status</th></tr>
{rows}
</table>
<p>POST /generate {{"prompt": ..., "max_tokens": ..., "temperature": ...}}
| GET /health | GET /workers</p>
</body></html>"""


class _Profiler:
    """jax.profiler trace capture behind HTTP (SURVEY.md §5 tracing note:
    the reference's only 'profiling' is wall-clock prints,
    /root/reference/orchestration.py:82,201). Traces are viewable in
    TensorBoard / Perfetto.

    Clients name a subdirectory, not a path: traces always land under
    `base` — otherwise POST /profiler/start would be an arbitrary
    filesystem-write primitive for anyone who can reach the port."""

    def __init__(self, base: str = "/tmp/jax-traces"):
        self._lock = threading.Lock()
        self.base = base
        self.dir: Optional[str] = None

    def _resolve(self, name: str) -> str:
        import os

        name = name or "trace"
        if os.path.isabs(name) or ".." in name.split("/"):
            raise ValueError(f"trace_dir must be a relative subdir name, got {name!r}")
        out = os.path.normpath(os.path.join(self.base, name))
        if not (out + "/").startswith(os.path.normpath(self.base) + "/"):
            raise ValueError(f"trace_dir escapes base: {name!r}")
        return out

    def start(self, trace_dir: str) -> dict:
        import jax

        with self._lock:
            if self.dir is not None:
                return {"error": f"trace already running to {self.dir}"}
            try:
                resolved = self._resolve(trace_dir)
                jax.profiler.start_trace(resolved)
            except Exception as e:
                return {"error": f"profiler start failed: {e}"}
            self.dir = resolved
            return {"status": "tracing", "trace_dir": resolved}

    def stop(self) -> dict:
        import jax

        with self._lock:
            if self.dir is None:
                return {"error": "no trace running"}
            out = self.dir
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                # JAX may still be mid-trace: keep self.dir so state stays
                # truthful ('trace already running' on a retried /start)
                # and tell the caller how to recover
                return {
                    "error": f"profiler stop failed: {e}; trace state is "
                    "unknown — retry /profiler/stop or restart the server",
                    "trace_dir": out,
                }
            self.dir = None
            return {"status": "stopped", "trace_dir": out}


# the fixed route set for the http counter's `route` label: anything else
# collapses to "other" so an attacker probing random paths cannot grow the
# label cardinality (the registry's own series cap is the second fence)
_KNOWN_ROUTES = frozenset((
    "/", "/health", "/ready", "/workers", "/stats", "/metrics", "/v1/models",
    "/generate", "/v1/completions", "/v1/chat/completions",
    "/profiler/start", "/profiler/stop", "/debug/traces", "/debug/flight",
))

# Retry-After (seconds) sent with every drain/overload rejection — the
# client's bounded-retry backoff honors it (client.py)
RETRY_AFTER_S = 2


def _route_label(path: str) -> str:
    if path == "/kv" or path.startswith("/kv/"):
        return "/kv"  # one label for every digest (bounded cardinality)
    if path.startswith("/debug/traces"):
        return "/debug/traces"  # one label for every trace id
    return path if path in _KNOWN_ROUTES else "other"


def make_handler(engine, max_tokens_cap: int, profiler: Optional[_Profiler] = None,
                 queue=None, continuous=None, state=None,
                 wedge_unready_s: float = 10.0):
    from ..utils.logging import request_id_context
    from ..utils.tracing import (
        SpanContext,
        new_request_id,
        parse_traceparent,
        sanitize_request_id,
    )
    from . import openai_api as oai
    from .trace_store import assemble_tree, span_tree_total, to_chrome_trace

    profiler = profiler or _Profiler()
    if state is None:  # embedding callers without an InferenceServer
        state = _ServerState()
    started_at = int(time.time())
    # configured SLO classes (engine/scheduler.py): the serving edge
    # validates request slo_class fields against them (unknown -> 400)
    from ..engine.scheduler import parse_slo_classes

    slo_classes = parse_slo_classes(engine.engine_cfg)
    # runtime LoRA adapter pool (engine/adapters.py), if configured —
    # requests select a registered adapter by name (`adapter` on
    # /generate, `model` on the OpenAI routes); unknown names are 400s
    # at this edge, before admission
    adapters = getattr(engine, "adapters", None)
    # HTTP request/error counter by route + status — every response path
    # (JSON, HTML, SSE, NDJSON) passes through exactly one counting point
    http_requests = engine.metrics.counter(
        "dli_http_requests_total", "HTTP responses",
        ("route", "method", "status"),
    )
    # scoring requests bypass the queue/continuous ladder (they are not
    # generations), so they need their own backpressure: a small bound on
    # concurrent scorers — overflow sheds with 429 instead of piling
    # threads on the engine lock
    score_slots = threading.BoundedSemaphore(4)

    class Handler(BaseHTTPRequestHandler):
        # quiet default request logging; serving logs are structured
        def log_message(self, fmt, *args):
            pass

        _rid: Optional[str] = None  # set per POST; echoed as X-Request-Id
        # inbound (traceparent header) or freshly-rooted SpanContext; set
        # per POST, echoed as X-Trace-Id so callers can find their trace
        _trace_ctx: Optional[SpanContext] = None

        def _count(self, code: int):
            http_requests.labels(
                route=_route_label(self.path.split("?")[0].rstrip("/") or "/"),
                method=self.command, status=str(code),
            ).inc()

        def _send(self, code: int, payload: Any, content_type="application/json",
                  headers=None):
            body = (
                payload if isinstance(payload, bytes)
                else payload.encode() if isinstance(payload, str)
                else json.dumps(payload).encode()
            )
            self._count(code)
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            if self._rid:
                self.send_header("X-Request-Id", self._rid)
            if self._trace_ctx is not None:
                self.send_header("X-Trace-Id", self._trace_ctx.trace_id)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _readiness(self) -> tuple:
            """(ready, reason): liveness is /health's job; THIS is the
            load-balancer signal — False while draining, while the
            continuous scheduler is restart-looping or dead, and while
            an abandoned deadline-overrun device call has been wedged
            past --wedge-unready (the router tier's probes eject the
            replica off this; /health keeps answering 200 so the
            process is not reaped — a wedge can still drain)."""
            if state.draining:
                return False, "draining"
            if wedge_unready_s and hasattr(engine, "max_wedged_age"):
                age = engine.max_wedged_age()
                if age is not None and age > wedge_unready_s:
                    return False, "wedged"
            if continuous is not None and not continuous.ready:
                return False, (
                    "scheduler_dead"
                    if continuous.stats()["supervisor"]["dead"]
                    else "scheduler_restarting"
                )
            return True, None

        def do_GET(self):
            # reset per-request correlation state: keep-alive connections
            # reuse this handler instance, and a prior POST's ids must not
            # leak into this response's headers
            self._rid = None
            self._trace_ctx = None
            path = self.path.split("?")[0].rstrip("/") or "/"
            if path == "/":
                self._send(200, _status_html(engine), content_type="text/html")
            elif path == "/health":
                h = engine.health()
                ready, why = self._readiness()
                # reference shape: status/role/model/version
                # (orchestration.py:297-304) + our backend detail.
                # LIVENESS stays 200 even while draining/restart-looping —
                # readiness is the separate /ready signal (and the `ready`
                # field here), so an LB can stop routing without the
                # process being reaped mid-drain.
                out = {
                    "status": h["status"],
                    "ready": ready,
                    **({"ready_reason": why} if why else {}),
                    "role": "orchestrator",
                    # disaggregation class (--replica-class): the router
                    # learns prefill/decode/mixed from here, so URL-joined
                    # replicas specialize without any spawn-time wiring
                    "replica_class": engine.engine_cfg.replica_class,
                    "model": h["model"],
                    "version": __version__,
                    "backend": h["backend"],
                    "n_stages": h["n_stages"],
                    "requests_served": h["requests_served"],
                    "stats": h["stats"],
                }
                if continuous is not None and continuous.fabric_serving:
                    # residency bootstrap: resident chain digests (MRU
                    # first, capped) so the router can steer fabric
                    # pulls at this replica without ever having routed
                    # traffic to it
                    out["kv"] = {
                        "fabric": True,
                        "block_size": continuous.kv_block_size,
                        # capped MRU-first (--kv-health-digests): the
                        # disk tier makes the full resident set
                        # unbounded, bootstrap payloads must stay O(1)
                        "resident_digests": continuous.fabric_digests(),
                    }
                self._send(200, out)
            elif path == "/ready":
                # load-balancer readiness probe: 200/503 is the whole
                # contract (k8s readinessProbe-friendly)
                ready, why = self._readiness()
                if ready:
                    self._send(200, {"ready": True})
                else:
                    self._send(
                        503, {"ready": False, "reason": why},
                        headers={"Retry-After": str(RETRY_AFTER_S)},
                    )
            elif path == "/workers":
                # reference shape: {"worker_1": "online", ...}
                # (orchestration.py:306-329); stages are in-process mesh
                # slices, so liveness == device presence. Single source:
                # engine.workers(), re-keyed to the reference's 1-based names.
                stages = list(engine.workers()["workers"].values())
                results = {
                    f"worker_{s['stage'] + 1}": s["status"] for s in stages
                }
                results["detail"] = stages
                self._send(200, results)
            elif path == "/stats":
                s = engine.stats()
                if continuous is not None:
                    s["continuous"] = continuous.stats()
                if queue is not None:
                    s["queue"] = {
                        "depth": queue.depth(),
                        "coalesced_batches": queue.coalesced_batches,
                    }
                self._send(200, s)
            elif path == "/metrics":
                # Prometheus text exposition over the SAME registry /stats
                # reads (utils/metrics.py); warmup traffic never reaches
                # _record_sample, so it is excluded from both views
                self._send(
                    200, engine.metrics.render(),
                    content_type="text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/v1/models":
                self._send(
                    200, oai.models_response(
                        engine.cfg.name, started_at,
                        adapters=adapters.names() if adapters else (),
                    )
                )
            elif path == "/debug/flight":
                # live flight-recorder view: the SAME bounded ring the
                # continuous supervisor dumps into crash reports (and
                # persists next to --restore-dir on a crash)
                flight = getattr(engine, "flight", None)
                self._send(
                    200,
                    flight.dump() if flight is not None
                    else {"capacity": 0, "recorded_total": 0, "events": []},
                )
            elif path == "/debug/traces" or path.startswith("/debug/traces/"):
                # this process's span store: the bare route lists known
                # trace ids; /debug/traces/{id} returns that trace's spans
                # plus the locally-assembled tree (the router concatenates
                # the flat `spans` lists from every replica to build the
                # full cross-process view); ?format=chrome emits Chrome
                # trace-event JSON loadable in Perfetto
                store = getattr(engine, "trace_store", None)
                if store is None:
                    self._send(404, {"error": "no trace store"})
                    return
                trace_id = path[len("/debug/traces/"):] if path.startswith(
                    "/debug/traces/"
                ) else ""
                if not trace_id:
                    self._send(200, {
                        "traces": store.trace_ids(), "stats": store.stats(),
                    })
                elif "format=chrome" in self.path.partition("?")[2]:
                    self._send(200, to_chrome_trace(store.get(trace_id)))
                else:
                    spans = store.get(trace_id)
                    tree = assemble_tree(spans)
                    self._send(200, {
                        "trace_id": trace_id,
                        "service": store.service,
                        "spans": spans,
                        "tree": tree,
                        "total_s": round(span_tree_total(tree), 6),
                    })
            elif path.startswith("/kv/"):
                # the KV fabric's serving half (serving/kv_fabric.py):
                # the resident shadow chain ending at this chunk digest,
                # wire-encoded. A miss — unknown digest, LRU-evicted, or
                # fabric disabled — is a 404 the fetching peer treats as
                # "prefill locally", never an error. The fetching peer's
                # X-Request-Id is echoed back and its traceparent joins
                # this serve to the same trace as its fabric.pull span.
                self._rid = sanitize_request_id(
                    self.headers.get("X-Request-Id")
                )
                ctx = parse_traceparent(self.headers.get("traceparent"))
                self._trace_ctx = ctx
                digest = path[len("/kv/"):]
                t0 = time.time()
                want_stream = self.headers.get("X-KV-Stream") in (
                    "1", "true"
                )
                tier = (
                    continuous.fabric_digest_tier(digest)
                    if continuous is not None else None
                ) or "host"
                if want_stream and continuous is not None:
                    # streamed serve: length-prefixed one-block frames,
                    # encoded lazily (O(1) time-to-first-byte), each
                    # carrying its running parent-chained digest so the
                    # peer verifies chunk-at-a-time and overlaps its
                    # pool scatters with the wire
                    res = continuous.fabric_chain_stream(digest)
                    if ctx is not None:
                        engine.trace_store.add_span(
                            ctx.trace_id, "kv.serve", t0, time.time(),
                            parent_id=ctx.span_id,
                            attrs={
                                "digest": digest[:16],
                                "hit": res is not None,
                                "streamed": True, "tier": tier,
                            },
                        )
                    if res is None:
                        self._send(404, {
                            "error": f"no resident chain for digest "
                                     f"{digest[:64]!r}",
                        })
                        return
                    n_chunks, tier, frames = res
                    # manual write path (like the NDJSON stream): no
                    # Content-Length — frames land as they encode
                    self._count(200)
                    self.send_response(200)
                    self.send_header("Content-Type", kvf.STREAM_CONTENT_TYPE)
                    self.send_header(
                        "X-KV-Block-Size", str(continuous.kv_block_size)
                    )
                    self.send_header("X-KV-Chain-Len", str(n_chunks))
                    self.send_header("X-KV-Tier", tier)
                    self.send_header("Connection", "close")
                    self.end_headers()
                    try:
                        for frame in frames:
                            self.wfile.write(frame)
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError, OSError):
                        pass  # peer gave up mid-pull: its problem only
                    return
                chain = (
                    continuous.fabric_chain(digest)
                    if continuous is not None else None
                )
                if ctx is not None:
                    engine.trace_store.add_span(
                        ctx.trace_id, "kv.serve", t0, time.time(),
                        parent_id=ctx.span_id,
                        attrs={
                            "digest": digest[:16],
                            "hit": chain is not None,
                            "streamed": False, "tier": tier,
                        },
                    )
                if chain is None:
                    self._send(404, {
                        "error": f"no resident chain for digest "
                                 f"{digest[:64]!r}",
                    })
                else:
                    self._send(
                        200, chain,
                        content_type="application/octet-stream",
                        headers={
                            "X-KV-Block-Size": str(continuous.kv_block_size),
                            "X-KV-Tier": tier,
                        },
                    )
            else:
                self._send(404, {"error": f"no route {path}"})

        def _deadline_ms(self, data: dict):
            """The request's end-to-end deadline budget in ms, or None.
            X-Request-Deadline-Ms (the router's remaining-budget relay)
            overrides the body's deadline_ms; both must be positive
            numbers (a non-positive header means the budget is already
            spent upstream — keep it, the engine fail-fasts it)."""
            hdr = self.headers.get("X-Request-Deadline-Ms")
            if hdr is not None:
                try:
                    return float(hdr)
                except (TypeError, ValueError):
                    pass  # junk header: fall back to the body field
            raw = data.get("deadline_ms")
            if raw is None:
                return None
            dl = float(raw)  # ValueError -> the route's 400 handler
            if dl <= 0:
                raise ValueError("deadline_ms must be > 0")
            return dl

        def _read_json(self):
            """Parse the request body; None (after a 400 reply) on bad JSON."""
            try:
                length = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError):
                self._send(400, {"error": "invalid JSON body"})
                return None

        def _kv_headers(self) -> tuple:
            """(kv_hint, prefill_only, kv_push_to) — the router's
            disaggregation headers. X-KV-Transfer-Peer +
            X-KV-Transfer-Digest name where this prompt's prefix chain
            is resident (the engine pulls it over the fabric at
            admission); X-KV-Prefill-Only marks phase 1 of a
            prefill->decode handoff (prefill + shadow-flush, one token,
            never streamed); X-KV-Push-To names the decode replica the
            router pre-picked, so phase 1 PUSHES the finished chain
            (POST /kv) before answering — phase 2's admission finds it
            resident with no pull round-trip. All no-ops without
            --continuous."""
            peer = self.headers.get("X-KV-Transfer-Peer")
            digest = self.headers.get("X-KV-Transfer-Digest")
            hint = (
                {"peer": peer, "digest": digest}
                if continuous is not None and peer and digest else None
            )
            prefill_only = (
                continuous is not None
                and self.headers.get("X-KV-Prefill-Only") in ("1", "true")
            )
            push_to = (
                self.headers.get("X-KV-Push-To")
                if continuous is not None and prefill_only else None
            )
            return hint, prefill_only, push_to

        # -- OpenAI-compatible surface (serving/openai_api.py) -----------

        def _run_single(self, prompt: str, kwargs: dict) -> dict:
            """One prompt through the same dispatch ladder as /generate:
            continuous fleet > bounded queue > bare engine. This is the
            replica's span-recording point: the whole dispatch runs under
            a `replica.request` span, the finished envelope's contiguous
            stage timings re-export as its child spans (uniform across
            all three ladder rungs), and the child context rides kwargs
            into the continuous engine so its launch-attribution spans
            nest under the same parent."""
            ctx = self._trace_ctx
            store = getattr(engine, "trace_store", None)
            if ctx is None or store is None:  # embedding callers
                return self._dispatch(prompt, kwargs)
            with store.span("replica.request", ctx, attrs={
                "request_id": kwargs.get("request_id"),
            }) as sp:
                kwargs["trace_ctx"] = ctx.child(sp["span_id"])
                result = self._dispatch(prompt, kwargs)
                sp["attrs"]["status"] = result.get("status")
                self._stage_spans(store, sp, result)
            return result

        def _stream_span(self, kwargs: dict):
            """Open the replica.request span for a STREAMED request and
            thread the child context into kwargs. The span outlives this
            frame by design — ownership transfers to the stream loop,
            whose finally calls end_span (the explicit-pair form the
            span-store docstring reserves for exactly this case)."""
            ctx = self._trace_ctx
            store = getattr(engine, "trace_store", None)
            if ctx is None or store is None:
                return None
            sp = store.start_span("replica.request", ctx, attrs={
                "request_id": kwargs.get("request_id"), "stream": True,
            })
            kwargs["trace_ctx"] = ctx.child(sp["span_id"])
            return sp

        def _dispatch(self, prompt: str, kwargs: dict) -> dict:
            if continuous is not None:
                return continuous.submit(prompt, **kwargs)
            if queue is not None:
                return queue.submit(prompt, **kwargs)
            kwargs.pop("trace_ctx", None)  # no bare-engine seam for it
            return engine.generate(prompt, **kwargs)

        @staticmethod
        def _stage_spans(store, parent: dict, result: dict):
            """Re-export the envelope's contiguous `timings` breakdown
            (utils/tracing.Trace: spans sum to ≈ total by construction)
            as child spans of `parent`, laid end to end from the request
            span's start — the per-stage view (queue_wait / admission /
            prefill / decode / detokenize) lands in the assembled fleet
            trace without a second engine-side recording hook."""
            timings = result.get("timings")
            if not isinstance(timings, dict):
                return
            t = parent["t0"]
            for key, dur in timings.items():
                if key == "total_s" or not key.endswith("_s"):
                    continue
                try:
                    dur = float(dur)
                except (TypeError, ValueError):
                    continue
                store.add_span(
                    parent["trace_id"], f"stage.{key[:-2]}", t, t + dur,
                    parent_id=parent["span_id"],
                )
                t += dur

        def _openai_stream(self, prompt: str, kwargs: dict, chat: bool):
            """SSE streaming: real per-chunk deltas on a --continuous
            server, single-chunk emulation otherwise (still valid SSE, so
            OpenAI-SDK streaming clients work against any server config)."""
            sp = None
            if continuous is not None:
                # real streaming records its request span here (the
                # non-continuous emulation goes through _run_single's)
                sp = self._stream_span(kwargs)
                events = continuous.stream(prompt, **kwargs)
            else:
                def _one_shot():
                    result = self._run_single(prompt, kwargs)
                    if result.get("status") == "success":
                        yield {"delta": result.get("response", "")}
                    yield {**result, "done": True}

                events = _one_shot()
            self._count(200)
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            if self._rid:
                self.send_header("X-Request-Id", self._rid)
            if self._trace_ctx is not None:
                self.send_header("X-Trace-Id", self._trace_ctx.trace_id)
            self.end_headers()
            try:
                for payload, _final in oai.stream_events(
                    events, engine.cfg.name, kwargs, chat=chat
                ):
                    self.wfile.write(payload)
                    self.wfile.flush()
            except OSError:
                # vanished SSE client (BrokenPipe/ConnectionReset and the
                # platform-specific OSError spellings): closing the event
                # generator routes into the engine's cancellation path —
                # continuous.stream's finally flips the cancel flag, the
                # worker kills the slot and frees its blocks at the next
                # launch boundary instead of decoding the dead client's
                # full max_new_tokens budget (regression-pinned in
                # tests/test_preemption.py)
                if hasattr(events, "close"):
                    events.close()  # cancel: frees the decode slot
            finally:
                if sp is not None:
                    engine.trace_store.end_span(sp)

        def _openai(self, path: str, data: dict):
            chat = path == "/v1/chat/completions"
            envelope = None  # the engine envelope carrying request_id/timings
            try:
                if chat:
                    prompt, kwargs, meta = oai.parse_chat(
                        data, engine.render_chat, max_tokens_cap,
                    )
                    prompts = [prompt]
                else:
                    prompts, kwargs, meta = oai.parse_completion(
                        data, max_tokens_cap
                    )
                if (
                    kwargs.get("slo_class") is not None
                    and kwargs["slo_class"] not in slo_classes
                ):
                    # same validation as /generate: an unknown class is a
                    # caller bug, never a silent fallback to the default
                    raise oai.OpenAIError(
                        f"unknown slo_class {kwargs['slo_class']!r}; "
                        f"configured: {sorted(slo_classes)}",
                        param="slo_class",
                    )
                req_model = data.get("model")
                if (
                    adapters is not None
                    and isinstance(req_model, str)
                    and req_model
                    and req_model != engine.cfg.name
                ):
                    # `model` resolves to a registered runtime adapter
                    # (the base model's own name keeps meaning the base).
                    # With a pool attached, an unknown model id is a
                    # caller bug — 400, never a silent base fallback.
                    # Without a pool, `model` stays informational, as
                    # before.
                    if not adapters.is_registered(req_model):
                        raise oai.OpenAIError(
                            f"model {req_model!r} is neither the base "
                            f"model {engine.cfg.name!r} nor a registered "
                            f"adapter; see GET /v1/models",
                            param="model",
                        )
                    kwargs["adapter"] = req_model
                hdr_dl = self.headers.get("X-Request-Deadline-Ms")
                if hdr_dl is not None:
                    # router relay of the REMAINING end-to-end budget:
                    # wins over the body's own deadline_ms
                    try:
                        kwargs["deadline_ms"] = float(hdr_dl)
                    except (TypeError, ValueError):
                        pass
                kwargs["request_id"] = self._rid
                kv_hint, prefill_only, kv_push_to = self._kv_headers()
                if kv_hint is not None:
                    kwargs["kv_hint"] = kv_hint
                if prefill_only:
                    # handoff phase 1 (see /generate): never streamed —
                    # the decode-class replica streams phase 2, so SSE
                    # clients see one transparent stream either way
                    kwargs["prefill_only"] = True
                    meta["stream"] = False
                    if kv_push_to:
                        kwargs["kv_push_to"] = kv_push_to
                if meta.get("echo_score"):
                    # echo + logprobs + max_tokens=0: teacher-forced
                    # scoring of the prompt itself (lm-eval pattern)
                    if not score_slots.acquire(blocking=False):
                        raise oai.OpenAIError(
                            "too many concurrent scoring requests",
                            status=429, err_type="overloaded_error",
                        )
                    try:
                        result = engine.score(
                            prompts[0], top_n=meta.get("score_top_n", 0)
                        )
                    finally:
                        score_slots.release()
                    if result.get("status") != "success":
                        raise oai.error_for_envelope(result)
                    self._send(200, oai.echo_score_response(
                        result, engine.cfg.name
                    ))
                    return
                if meta["stream"]:
                    if len(prompts) != 1:
                        raise oai.OpenAIError(
                            "streaming requires a single prompt", param="stream"
                        )
                    self._openai_stream(prompts[0], kwargs, chat=chat)
                    return
                n = meta.get("n", 1)
                if n > 1:
                    # n choices = one ragged fleet of the same prompt
                    # (categorical draws are independent per row)
                    prompts = prompts * n
                if len(prompts) == 1:
                    result = self._run_single(prompts[0], kwargs)
                    if result.get("status") != "success":
                        raise oai.error_for_envelope(result)
                    entries = [result]
                    envelope = result
                else:
                    if kwargs.get("logprobs"):
                        raise oai.OpenAIError(
                            "logprobs requires a single string prompt",
                            param="logprobs",
                        )
                    batch = (
                        queue.submit_batch(prompts, **kwargs)
                        if queue is not None
                        else engine.generate_batch(prompts, **kwargs)
                    )
                    if batch.get("status") != "success":
                        raise oai.error_for_envelope(batch)
                    entries = batch["results"]
                    envelope = batch
            except oai.OpenAIError as e:
                self._send(e.status, e.body)
                return
            except (TypeError, ValueError) as e:
                # defense in depth: any param-shape error that escaped the
                # parsers still answers 400, never a dropped connection
                self._send(400, oai.OpenAIError(f"bad parameter: {e}").body)
                return
            prompt_once = meta.get("n", 1) > 1
            build = oai.chat_response if chat else oai.completion_response
            # KV-fabric fields ride the OpenAI envelope as extension
            # keys (clients ignore unknown fields): the router learns
            # residency / scores handoffs identically on every route
            kv_extra = {
                k: envelope[k]
                for k in ("kv_digests", "kv_fabric_blocks",
                          "kv_promoted_blocks", "prefill_only",
                          "kv_pushed")
                if isinstance(envelope, dict) and k in envelope
            }
            self._send(
                200,
                # adapter-resolved requests echo the adapter id as the
                # model (vLLM convention): the client asked for that id
                # and /v1/models lists it
                build(entries, kwargs.get("adapter") or engine.cfg.name,
                      kwargs,
                      prompt_once=prompt_once,
                      request_id=envelope.get("request_id", self._rid),
                      timings=envelope.get("timings"),
                      kv_extra=kv_extra or None,
                      trace_id=(self._trace_ctx.trace_id
                                if self._trace_ctx is not None else None)),
            )

        def do_POST(self):
            path = self.path.split("?")[0].rstrip("/")
            # accept a client-supplied X-Request-Id (sanitized) for
            # cross-service correlation, else mint one; echoed on every
            # response header and in the JSON envelope
            self._rid = (
                sanitize_request_id(self.headers.get("X-Request-Id"))
                or new_request_id()
            )
            # join the caller's trace (router/client `traceparent`) or
            # root a fresh one; every log record inside the request then
            # carries both ids (utils/logging request_id_context)
            self._trace_ctx = (
                parse_traceparent(self.headers.get("traceparent"))
                or SpanContext.new_root()
            )
            with request_id_context(self._rid, self._trace_ctx.trace_id):
                self._do_POST(path)

        def _do_POST(self, path: str):
            if state.draining and path in (
                "/generate", "/v1/completions", "/v1/chat/completions"
            ):
                # graceful drain: admission closed at the edge (in-flight
                # work keeps finishing); Retry-After tells well-behaved
                # clients when to try the next replica
                self._send(
                    503,
                    {
                        "error": "Error: server draining",
                        "status": "failed", "error_type": "draining",
                    },
                    headers={"Retry-After": str(RETRY_AFTER_S)},
                )
                return
            if path in ("/v1/completions", "/v1/chat/completions"):
                data = self._read_json()
                if data is not None:
                    self._openai(path, data)
                return
            if path == "/profiler/start":
                data = self._read_json()
                if data is None:
                    return
                # default is a subdir NAME under the profiler base, not a path
                res = profiler.start(data.get("trace_dir", "trace"))
                self._send(400 if "error" in res else 200, res)
                return
            if path == "/profiler/stop":
                res = profiler.stop()
                self._send(400 if "error" in res else 200, res)
                return
            if path == "/kv":
                # the KV fabric's push half: a peer's proactive chain
                # push at the prefill->decode handoff. The payload is
                # validated against its OWN content key (the digest is
                # recomputed from its tokens) and landed in the host
                # shadow tier; a payload failing validation is a 400 the
                # pusher treats as "the pull fallback will cover it".
                if continuous is None or not continuous.fabric_serving:
                    self._send(404, {"error": "kv fabric not serving"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except (TypeError, ValueError):
                    length = 0
                if length <= 0:
                    self._send(400, {"error": "empty /kv push"})
                    return
                body = self.rfile.read(length)
                res = continuous.fabric_accept_push(body)
                if res is None:
                    self._send(400, {"error": "push payload failed "
                                              "content-key validation"})
                else:
                    self._send(200, res)
                return
            if path != "/generate":
                self._send(404, {"error": f"no route {path}"})
                return
            data = self._read_json()
            if data is None:
                return
            prompt = data.get("prompt", "")
            prompts = data.get("prompts")
            if not prompt and not prompts:
                # reference: 400 "No prompt provided" (orchestration.py:343)
                self._send(400, {"error": "No prompt provided"})
                return
            try:
                max_tokens = min(int(data.get("max_tokens", DEFAULT_MAX_TOKENS)), max_tokens_cap)
                seed = data.get("seed")
                kwargs = dict(
                    request_id=self._rid,
                    max_tokens=max_tokens,
                    temperature=float(data.get("temperature", DEFAULT_TEMPERATURE)),
                    top_k=int(data.get("top_k", DEFAULT_TOP_K)),
                    top_p=float(data.get("top_p", DEFAULT_TOP_P)),
                    greedy=_parse_bool(data.get("greedy", False), "greedy"),
                    chat=_parse_bool(data.get("chat", True), "chat"),
                    seed=int(seed) if seed is not None else None,
                    # HF-parity extensions (0.0 / 1.0 = off)
                    min_p=float(data.get("min_p", 0.0)),
                    repetition_penalty=float(
                        data.get("repetition_penalty", 1.0)
                    ),
                    # OpenAI penalties over generated-token counts (0 = off)
                    frequency_penalty=float(
                        data.get("frequency_penalty", 0.0)
                    ),
                    presence_penalty=float(
                        data.get("presence_penalty", 0.0)
                    ),
                )
                raw_dl = self._deadline_ms(data)
                if raw_dl is not None:
                    # end-to-end deadline: expiry anywhere (queued,
                    # mid-prefill, mid-decode) returns a 504
                    # deadline_exceeded envelope and frees the request's
                    # blocks/slot at the next launch boundary. The header
                    # form (X-Request-Deadline-Ms, set by the router with
                    # the REMAINING budget) wins over the body field.
                    kwargs["deadline_ms"] = raw_dl
                raw_slo = data.get("slo_class")
                if raw_slo is not None:
                    # SLO class (engine/scheduler.py): admission priority,
                    # prefill-budget share, and shed policy on the
                    # continuous fleet; class-aware Retry-After on 429s.
                    # Unknown names are a caller bug -> 400.
                    if (
                        not isinstance(raw_slo, str)
                        or raw_slo not in slo_classes
                    ):
                        raise ValueError(
                            f"unknown slo_class {raw_slo!r}; configured: "
                            f"{sorted(slo_classes)}"
                        )
                    kwargs["slo_class"] = raw_slo
                raw_tenant = data.get("tenant")
                if raw_tenant is not None:
                    # multi-tenant identity (engine/scheduler.py):
                    # tenant-weighted apportionment within each SLO
                    # class, per-tenant queue quota shed, per-tenant
                    # TTFT/TPOT EWMAs. Free-form label.
                    if not isinstance(raw_tenant, str) or not raw_tenant:
                        raise ValueError(
                            "tenant must be a non-empty string"
                        )
                    kwargs["tenant"] = raw_tenant
                raw_adapter = data.get("adapter")
                if raw_adapter is not None and raw_adapter != engine.cfg.name:
                    # runtime LoRA adapter selection (engine/adapters.py):
                    # the request's decode rows ride the named adapter's
                    # device page inside the one compiled mixed program.
                    # The base model's own name means "no adapter" so
                    # callers can pass their model id unconditionally.
                    if not isinstance(raw_adapter, str):
                        raise ValueError("adapter must be a string")
                    if adapters is None:
                        raise ValueError(
                            "adapter serving is not configured: start "
                            "with --adapter-slots (and --continuous + "
                            "--kv-pool-blocks)"
                        )
                    if not adapters.is_registered(raw_adapter):
                        raise ValueError(
                            f"unknown adapter {raw_adapter!r}; "
                            f"registered: {adapters.names()}"
                        )
                    kwargs["adapter"] = raw_adapter
                nbeams = data.get("num_beams")
                if nbeams is not None and int(nbeams) > 1:
                    # deterministic beam search (HF num_beams semantics);
                    # beam requests run solo (pure max-score search)
                    kwargs["num_beams"] = int(nbeams)
                    kwargs["length_penalty"] = float(
                        data.get("length_penalty", 1.0)
                    )
                    kwargs["early_stopping"] = _parse_bool(
                        data.get("early_stopping", False), "early_stopping"
                    )
                raw_bias = data.get("logit_bias")
                if raw_bias is not None:
                    # {token_id: bias} added to the raw logits every sample
                    # (OpenAI semantics; the engine validates ids/backend)
                    if not isinstance(raw_bias, dict):
                        raise ValueError("logit_bias must be an object of "
                                         "token_id -> bias")
                    kwargs["logit_bias"] = {
                        int(k): float(v) for k, v in raw_bias.items()
                    }
                raw_con = data.get("constraint")
                if raw_con is not None:
                    # grammar-constrained structured output (constrain/):
                    # {"regex": ...} | {"choices": [...]} |
                    # {"json_schema": {...}} | {"json_object": true}.
                    # Spec validation happens engine-side
                    # (parse_constraint_spec) -> invalid_request 400.
                    if not isinstance(raw_con, dict):
                        raise ValueError(
                            "constraint must be an object with one of "
                            "'regex', 'choices', 'json_schema', "
                            "'json_object'"
                        )
                    kwargs["constraint"] = raw_con
                raw_stop = data.get("stop")
                if raw_stop is not None:
                    # OpenAI-style textual stop sequences: one string or a
                    # list of strings
                    if isinstance(raw_stop, str):
                        raw_stop = [raw_stop]
                    if not (
                        isinstance(raw_stop, list)
                        and all(isinstance(s, str) for s in raw_stop)
                    ):
                        raise ValueError("stop must be a string or list of strings")
                    kwargs["stop"] = raw_stop
                kv_hint, prefill_only, kv_push_to = self._kv_headers()
                if kv_hint is not None:
                    kwargs["kv_hint"] = kv_hint
                if prefill_only:
                    # handoff phase 1: prefill + shadow flush + one
                    # token; the router discards the token and hands the
                    # prefix digest to a decode-class replica — so the
                    # body's stream flag is ignored here (the STREAM
                    # happens on the decode replica, transparently)
                    kwargs["prefill_only"] = True
                    if kv_push_to:
                        kwargs["kv_push_to"] = kv_push_to
                if not prefill_only and _parse_bool(
                    data.get("stream", False), "stream"
                ):
                    # NDJSON token streaming: one {"delta": ...} line per
                    # decode chunk, final line = the standard envelope with
                    # "done": true. Requires --continuous (the solo engine
                    # decodes entirely on-device; there is nothing to
                    # stream per-token).
                    if continuous is None or prompts is not None:
                        self._send(400, {
                            "error": "streaming requires --continuous and a "
                            "single 'prompt'",
                        })
                        return
                    kwargs["debug"] = _parse_bool(data.get("debug", False), "debug")
                    kwargs["speculative"] = _parse_bool(
                        data.get("speculative", False), "speculative"
                    )
                    kwargs["logprobs"] = _parse_bool(
                        data.get("logprobs", False), "logprobs"
                    )
                    self._count(200)
                    self.send_response(200)
                    self.send_header("Content-Type", "application/x-ndjson")
                    if self._rid:
                        self.send_header("X-Request-Id", self._rid)
                    if self._trace_ctx is not None:
                        self.send_header(
                            "X-Trace-Id", self._trace_ctx.trace_id
                        )
                    self.end_headers()
                    sp = self._stream_span(kwargs)
                    gen = continuous.stream(prompt, **kwargs)
                    try:
                        for ev in gen:
                            self.wfile.write(json.dumps(ev).encode() + b"\n")
                            self.wfile.flush()
                    except OSError:
                        # client went away mid-stream: closing the
                        # generator cancels the request — the engine kills
                        # its slot at the next chunk boundary so the fleet
                        # serves queued work instead of a dead socket
                        gen.close()
                    finally:
                        if sp is not None:
                            engine.trace_store.end_span(sp)
                    return
                if prompts is not None:
                    # batched form: "prompts": [...] -> one fleet, N results
                    if not isinstance(prompts, list):
                        raise ValueError("prompts must be a list of strings")
                    if kwargs.get("logit_bias"):
                        raise ValueError(
                            "logit_bias requires a single 'prompt'"
                        )
                    if kwargs.get("num_beams", 1) > 1:
                        raise ValueError(
                            "num_beams requires a single 'prompt'"
                        )
                    if queue is not None:
                        # same bounded backpressure as singles; full -> 429
                        result = queue.submit_batch(prompts, **kwargs)
                    else:
                        result = engine.generate_batch(prompts, **kwargs)
                else:
                    # debug=true adds top-5 first-token predictions
                    # (reference's debug prints, orchestration.py:172-178)
                    kwargs["debug"] = _parse_bool(data.get("debug", False), "debug")
                    # speculative=true: greedy prompt-lookup speculation
                    # (faster on repetitive text; argmax-equivalent — exact
                    # in fp32, bf16 may resolve numerical near-ties
                    # differently)
                    kwargs["speculative"] = _parse_bool(
                        data.get("speculative", False), "speculative"
                    )
                    # logprobs=true: per-generated-token log-probabilities
                    # (raw model distribution; single-device backend)
                    kwargs["logprobs"] = _parse_bool(
                        data.get("logprobs", False), "logprobs"
                    )
                    # the same dispatch ladder as the OpenAI routes —
                    # continuous (in-flight batching, engine/continuous.py)
                    # > bounded queue (serving/queue.py) > bare engine —
                    # via the one span-recording point, so /generate and
                    # /v1/* requests trace identically
                    result = self._run_single(prompt, kwargs)
            except (TypeError, ValueError) as e:
                self._send(400, {"error": f"bad parameter: {e}"})
                return
            err_type = result.get("error_type")
            headers = None
            if result.get("status") == "success":
                code = 200
            elif err_type == "invalid_request":
                code = 400
            elif err_type == "deadline_exceeded":
                # the request's OWN deadline_ms budget expired: 504, and
                # nobody — router included — may retry it (the budget is
                # just as spent wherever the retry lands)
                code = 504
            elif err_type == "cancelled":
                # client went away (or the stream was torn down): 499
                # (nginx convention) so access logs can tell a dead
                # client from a server fault; never router-retried
                code = 499
            elif err_type in ("timeout", "unavailable", "draining"):
                # timeout: deadline exceeded (reference's per-hop failure,
                # orchestration.py:118,131). unavailable: the continuous
                # scheduler exhausted its restart budget. draining: raced
                # the drain flag inside the engine — all service-
                # unavailable, all retryable elsewhere.
                code = 503
                if err_type != "timeout":
                    headers = {"Retry-After": str(RETRY_AFTER_S)}
            elif err_type == "overloaded":
                # bounded queue full (serving/queue.py or the continuous
                # admission queue): shed load, with the queue-depth-derived
                # Retry-After hint so overload backoff is server-directed
                # exactly like the drain path's
                code = 429
                headers = {
                    "Retry-After": str(
                        result.get("retry_after_s", RETRY_AFTER_S)
                    )
                }
            else:
                # includes "poison": the request itself crashed the
                # scheduler K times — a server-side fault answer, and the
                # one 5xx a client must NOT blindly retry
                code = 500
            self._send(code, result, headers=headers)

    return Handler


class _ServerState:
    """Mutable flags shared between the server object and its handler
    class (the handler closes over this; InferenceServer.drain flips it)."""

    __slots__ = ("draining",)

    def __init__(self):
        self.draining = False


class InferenceServer:
    """Owns the HTTP server + engine; start()/shutdown() for embedding in
    tests, serve_forever() for the CLI (which installs the SIGTERM →
    graceful-drain handler)."""

    def __init__(self, engine, host: str = "0.0.0.0", port: int = 5000,
                 max_tokens_cap: int = 30, queue=None, continuous=None,
                 drain_deadline_s: float = 30.0,
                 wedge_unready_s: float = 10.0):
        self.engine = engine
        self.queue = queue
        self.continuous = continuous
        self.drain_deadline_s = float(drain_deadline_s)
        self.state = _ServerState()
        self.httpd = ThreadingHTTPServer(
            (host, port),
            make_handler(engine, max_tokens_cap, queue=queue,
                         continuous=continuous, state=self.state,
                         wedge_unready_s=wedge_unready_s),
        )
        self.port = self.httpd.server_address[1]

    def start(self) -> threading.Thread:
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        return t

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Graceful drain, the SIGTERM path: flip readiness (new requests
        get 503 + Retry-After, /ready goes 503), let queued + in-flight
        work finish up to the deadline, then stop the HTTP server and
        close the engines. Ordering matters: edge first (no new
        admissions), then the batching layers (their own queues), then
        the bare engine's in-flight lock. Returns True when everything
        finished inside the deadline."""
        deadline = (
            self.drain_deadline_s if deadline_s is None else float(deadline_s)
        )
        t0 = time.time()
        self.state.draining = True
        ok = True

        def left() -> float:
            return max(0.0, deadline - (time.time() - t0))

        if self.continuous is not None:
            ok = self.continuous.drain(left()) and ok
        if self.queue is not None:
            ok = self.queue.drain(left()) and ok
        if hasattr(self.engine, "drain"):  # MirroredEngine proxies lack it
            ok = self.engine.drain(left()) and ok
        self.engine.metrics.histogram(
            "dli_drain_duration_seconds",
            "graceful-drain wall time (SIGTERM / drain())", ("component",),
        ).labels(component="server").observe(time.time() - t0)
        from ..utils.logging import get_logger

        get_logger("server").info(
            "drained", ok=ok, seconds=round(time.time() - t0, 3)
        )
        self.shutdown()
        return ok

    def install_signal_handlers(self):
        """SIGTERM → graceful drain (must run on the main thread; the
        handler only spawns the drain thread, so it returns immediately).
        The second SIGTERM is left at default disposition semantics: the
        drain already owns shutdown, and repeated signals must not stack
        drain threads."""
        import signal

        def _on_term(signum, frame):
            if self.state.draining:
                return  # drain already in flight
            self.state.draining = True  # flip readiness before the thread spawns
            threading.Thread(
                target=self.drain, name="sigterm-drain", daemon=True
            ).start()

        signal.signal(signal.SIGTERM, _on_term)

    def serve_forever(self):
        from ..utils.logging import configure, get_logger

        configure()  # JSON-lines handler; entry-point-only (library-safe)
        self.install_signal_handlers()
        get_logger("server").info(
            "serving", port=self.port,
            routes=["/generate", "/health", "/ready", "/workers", "/stats",
                    "/metrics", "/profiler/*", "/debug/traces",
                    "/debug/flight"],
        )
        print(f"🚀 serving on :{self.port} — /generate /health /ready /workers /metrics /")
        self.httpd.serve_forever()
        # serve_forever returns when drain()/shutdown() stopped the
        # listener — SIGTERM ends as a clean exit 0

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self.queue is not None:
            self.queue.close()
        if self.continuous is not None:
            self.continuous.close()


# every tokenizer format the converter carries into a store: BPE json,
# config, GPT-2 vocab/merges, and sentencepiece .model (Llama-2-style
# dirs ship ONLY tokenizer.model — missing it here would silently serve
# byte-garbled text, the exact failure strict loading exists to prevent)
_TOKENIZER_FILES = (
    "tokenizer.json", "tokenizer_config.json", "vocab.json", "tokenizer.model",
)


def _has_tokenizer_files(path: str) -> bool:
    import os

    return any(os.path.exists(os.path.join(path, f)) for f in _TOKENIZER_FILES)


def _load_checkpoint(args, mesh_cfg):
    """(cfg, params) for --checkpoint: a local store dir (manifest.json) or
    a HF checkpoint dir (config.json + safetensors).

    On a multi-device mesh a store restores directly into mesh-sharded
    arrays (models/checkpoint.load_params_sharded) — each host reads only
    its shards' pages off mmap. quant/LoRA need host-side full params
    first (quantize/merge run before placement), so those paths take the
    full load. This is the serving entry the reference's whole design is
    for: real TinyLlama weights behind /generate
    (/root/reference/orchestration.py:34-47)."""
    import os

    path = args.checkpoint
    if os.path.exists(os.path.join(path, "manifest.json")):
        from ..models.checkpoint import load_params, load_params_sharded

        sharded_ok = (
            mesh_cfg.n_devices > 1 and args.quant is None and args.lora is None
        )
        if sharded_ok:
            from ..parallel.mesh import build_mesh

            cfg, params = load_params_sharded(path, build_mesh(mesh_cfg))
        else:
            cfg, params = load_params(path)
        if args.dtype and args.dtype != cfg.dtype:
            raise SystemExit(
                f"--dtype {args.dtype} conflicts with the checkpoint's "
                f"recorded dtype {cfg.dtype!r}; re-convert with --dtype "
                f"{args.dtype} instead"
            )
        return cfg, params
    if os.path.exists(os.path.join(path, "config.json")):
        from ..models.convert import load_hf_checkpoint

        return load_hf_checkpoint(path, dtype=args.dtype or "bfloat16")
    raise SystemExit(
        f"--checkpoint {path}: neither a local store (manifest.json) nor "
        f"a HF checkpoint dir (config.json + *.safetensors)"
    )


def main(argv: Optional[list] = None):
    import os

    # Honor an explicit JAX_PLATFORMS env var over any site-package pin:
    # this environment's axon site hook force-registers the TPU plugin as
    # "axon,cpu" at interpreter start, so a `JAX_PLATFORMS=cpu` launch
    # (tests, CI, a host without the tunnel) would still try — and hang
    # on — the TPU backend. A pre-backend-init config update wins.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass  # backend already initialized by the embedding caller

    from ..config import EngineConfig, MeshConfig
    from ..runtime import create_engine

    ap = argparse.ArgumentParser(description="distributed_llm_inference_tpu server")
    ap.add_argument("--model", default="tinyllama-1.1b")
    ap.add_argument(
        "--checkpoint", default=None, metavar="DIR",
        help="serve REAL weights: a local checkpoint store dir "
             "(models/checkpoint.py; produced by `python -m "
             "distributed_llm_inference_tpu.models.convert`) or a "
             "HuggingFace checkpoint dir (config.json + *.safetensors). "
             "Overrides --model; on a multi-device mesh a store loads "
             "shard-by-shard off mmap so no host materializes the full "
             "model (the reference re-downloads the whole model on every "
             "worker, /root/reference/Worker1.py:60-77)",
    )
    ap.add_argument(
        "--tokenizer", default=None, metavar="PATH",
        help="HF tokenizer dir/name to serve with (loaded strict: a bad "
             "path fails startup instead of silently degrading to the "
             "byte-level fallback). Defaults to tokenizer files found in "
             "--checkpoint DIR, else the offline byte tokenizer",
    )
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=5000)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1, help="pipeline stages")
    ap.add_argument(
        "--microbatches", type=int, default=1, metavar="M",
        help="M > 1 serves the zero-bubble 1F1B schedule (BASELINE config "
             "5): batched requests split into M microbatches chasing each "
             "other around the pp ring (needs --pp >= 2 and M >= pp); solo "
             "requests ride the batched path",
    )
    ap.add_argument("--sp", type=int, default=1, help="context-parallel ring size")
    ap.add_argument(
        "--sp-strategy", default="ring", choices=["ring", "ulysses"],
        help="long-context prefill strategy over the sp axis: 'ring' "
             "(K/V rotate via ppermute) or 'ulysses' (two all-to-alls "
             "re-shard sequence<->heads; needs heads divisible by sp)",
    )
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--ep", type=int, default=1, help="expert-parallel width (MoE)")
    ap.add_argument("--dtype", default=None, choices=[None, "float32", "bfloat16"])
    ap.add_argument(
        "--attn-impl", default=None, choices=[None, "auto", "xla", "pallas"],
        help="attention implementation: 'pallas' = the flash kernel "
             "(ops/flash_attention.py), 'xla' = einsum + mask (XLA fuses "
             "it), 'auto' = pallas when legal for the model AND running "
             "on TPU (CPU interpret mode is never auto-selected); default "
             "keeps the model config's setting (xla)",
    )
    ap.add_argument(
        "--lora", default=None, metavar="DIR",
        help="PEFT-format LoRA adapter directory to merge into the base "
             "weights at load (W + alpha/r * BA; before quantization) — "
             "the SINGLE-adapter fast path: zero per-step delta cost, "
             "but the whole server speaks that one adapter. Serve many "
             "adapters concurrently with --adapter-slots/--adapter "
             "instead (the same adapter cannot be used both ways)",
    )
    ap.add_argument(
        "--adapter-slots", type=int, default=0, metavar="N",
        help="runtime LoRA adapter pool (engine/adapters.py): reserve N "
             "device pages of paged A/B factors next to the resident "
             "base weights; requests select a registered adapter by "
             "name ('adapter' on /generate, 'model' on the OpenAI "
             "routes) and decode through ONE compiled program whatever "
             "the adapter mix. Needs --continuous + --kv-pool-blocks "
             "(the ragged paged fleet); 0 = disabled",
    )
    ap.add_argument(
        "--adapter-rank", type=int, default=8, metavar="R",
        help="pool page rank: every registered adapter is zero-padded "
             "to rank R (registration rejects adapters with a larger "
             "trained rank)",
    )
    ap.add_argument(
        "--adapter", action="append", default=None, metavar="NAME=DIR",
        help="register a PEFT-format LoRA adapter directory under NAME "
             "at startup (repeatable); requests then address it by "
             "name. Requires --adapter-slots; more adapters than slots "
             "is fine — pages are refcounted and LRU-swapped on demand",
    )
    ap.add_argument(
        "--tenant-weight", action="append", default=None, metavar="NAME=W",
        help="per-tenant fairness weight on the continuous fleet "
             "(repeatable): within each SLO class, queued tenants split "
             "the class's token budget in proportion to their weights "
             "(unlisted tenants weigh 1.0); requests carry their tenant "
             "in the 'tenant' field",
    )
    ap.add_argument(
        "--tenant-queue-share", type=float, default=0.5, metavar="F",
        help="per-tenant admission-queue quota as a fraction of the "
             "continuous queue bound: one tenant's queued requests "
             "beyond max(4, F * queue-bound) shed with 429 + "
             "Retry-After so a flooding tenant cannot starve the "
             "others' admission; 1.0 disables the quota",
    )
    ap.add_argument(
        "--draft-model", default=None, metavar="NAME",
        help="attach a smaller same-tokenizer model as a speculative "
             "draft: greedy requests with \"speculative\": true verify "
             "the draft's proposals (several tokens per target forward "
             "on text the draft predicts well; single chip or a pp mesh "
             "— the ring runs the draft replicated)",
    )
    ap.add_argument(
        "--quant", default=None, choices=[None, "int8", "int4"],
        help="weight-only quantization: int8 halves decode HBM bytes/token "
             "(~1.6-1.7x measured decode speedup on v5e; llama family); "
             "int4 halves the WEIGHT FOOTPRINT again (packed nibbles, "
             "group-wise scales) — the capacity pick for fitting bigger "
             "models; int8 decodes faster",
    )
    ap.add_argument(
        "--kv-quant", default=None, choices=[None, "int8"],
        help="KV-CACHE quantization: int8 K/V with per-(token, head) "
             "scales halves cache HBM — 2x the --continuous slots or "
             "context window at the same budget (llama family; EVERY "
             "topology: single chip, pp/tp/dp/1F1B meshes, --sp rings; "
             "composes with --prefix-cache, --kv-pool-blocks — an int8 "
             "block pool stacks both HBM levers — and --attn-impl "
             "pallas, whose kernels dequantize in their prologues)",
    )
    ap.add_argument(
        "--pp-wire-quant", default=None, choices=[None, "int8"],
        help="quantized inter-stage transfers: int8 + per-token-row fp32 "
             "scales on every pp/sp activation hand-off (microstep ring, "
             "1F1B, sp chunk rotation, final-stage broadcast) — ~4x "
             "fewer ICI bytes at fp32 (~2x at bf16), the binding "
             "constraint for deeper pipelines; default off = "
             "bit-identical wire (greedy output toleranced when on)",
    )
    ap.add_argument("--max-tokens-cap", type=int, default=30)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--deadline", type=float, default=None, metavar="SECONDS",
        help="per-request wall-clock deadline; overruns return a 503 "
             "timeout envelope (reference: 30s per worker hop)",
    )
    ap.add_argument(
        "--drain-deadline", type=float, default=30.0, metavar="SECONDS",
        help="graceful-drain budget on SIGTERM: readiness flips "
             "immediately (503 + Retry-After on new requests, /ready "
             "503), in-flight requests get this long to finish, then the "
             "process exits cleanly",
    )
    ap.add_argument(
        "--restart-budget", type=int, default=3, metavar="N",
        help="continuous-scheduler supervisor: how many CONSECUTIVE "
             "crashes to absorb (restart + re-admit in-flight requests "
             "as continuation prefills) before declaring the fleet dead; "
             "a healthy decode chunk resets the window",
    )
    ap.add_argument(
        "--poison-strikes", type=int, default=2, metavar="K",
        help="quarantine a request implicated in K consecutive "
             "scheduler crash-restarts (error_type 'poison'), instead of "
             "letting it take the fleet down with it",
    )
    ap.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="arm the deterministic fault-injection harness "
             "(utils/faults.py), e.g. 'decode_launch:transient:on=3'; "
             "the DLI_FAULTS env var is the config-file-free spelling. "
             "Chaos drills only — never in front of real traffic",
    )
    ap.add_argument(
        "--trace-sample-rate", type=float, default=0.0, metavar="F",
        help="fraction of traced requests that also get launch-level "
             "device-time attribution on the continuous fleet: sampled "
             "requests' mixed/chunk launches record dispatch->fetch "
             "spans (host timestamps keyed by launch seq — never an "
             "extra device sync) into GET /debug/traces/{trace_id}. "
             "0 (default) keeps the hot path allocation-free",
    )
    ap.add_argument(
        "--wedge-unready", type=float, default=10.0, metavar="SECONDS",
        help="flip GET /ready to 503 (reason 'wedged') while an abandoned "
             "deadline-overrun device call has been stuck this long — the "
             "router tier's health probes then eject the replica until "
             "the call drains (0 disables; needs --deadline to ever "
             "trigger; liveness /health stays 200 throughout)",
    )
    ap.add_argument(
        "--restore-dir", default=None, metavar="DIR",
        help="warm-state persistence for --continuous with "
             "--kv-pool-blocks (engine/shadow.py): graceful drain "
             "(SIGTERM / rolling restart) serializes the shadowed KV "
             "blocks + block-prefix chains here, and startup restores "
             "them into the fresh pool — the replica rejoins with a "
             "WARM prefix cache (needs --prefix-cache > 0)",
    )
    ap.add_argument(
        "--replica-class", default="mixed",
        choices=["mixed", "prefill", "decode"],
        help="disaggregation class for the router tier (serving/"
             "router.py): 'prefill' replicas take fresh long-prompt work "
             "and hand the finished prefix to a 'decode' replica by "
             "chunk digest over the KV fabric; 'mixed' (default) serves "
             "everything. Engine behavior is identical — this labels "
             "/health and the dli_kv_fabric_* metrics' role",
    )
    ap.add_argument(
        "--no-kv-fabric", action="store_true",
        help="disable the cross-replica KV fabric (the GET /kv/{digest} "
             "surface and X-KV-Transfer-* fetch hints); the shadow "
             "store stays purely local (crash recovery / --restore-dir)",
    )
    ap.add_argument(
        "--kv-fabric-timeout", type=float, default=5.0, metavar="SECONDS",
        help="hard deadline on one fabric fetch; a dead or wedged peer "
             "costs at most this long before admission prefills locally",
    )
    ap.add_argument(
        "--no-kv-shadow", action="store_true",
        help="disable the warm-recovery shadow store (supervisor "
             "restarts and --restore-dir starts then recover cold, "
             "re-prefilling every salvaged request from its full prompt)",
    )
    ap.add_argument(
        "--kv-disk-dir", default=None, metavar="DIR",
        help="disk tier (tier 2) of the KV cache hierarchy: LRU-evicted "
             "host-shadow entries demote into parent-chained chunk files "
             "here instead of dropping, and every shadow read surface "
             "(prefix planning, warm recovery, preemption swap, the "
             "fabric) promotes hits back out — the replica's logical "
             "prefix cache becomes disk-bounded. Default: no disk tier",
    )
    ap.add_argument(
        "--kv-disk-blocks", type=int, default=0, metavar="N",
        help="disk-tier bound in blocks (chunk files, LRU). 0 = auto: "
             "8x the host shadow tier",
    )
    ap.add_argument(
        "--no-kv-stream", action="store_true",
        help="pull fabric chains as one whole-manifest blob instead of "
             "chunk-at-a-time streamed frames (the streamed pull "
             "overlaps the wire with the importing replica's pool "
             "scatters; this pins the pre-stream behavior)",
    )
    ap.add_argument(
        "--kv-health-digests", type=int, default=64, metavar="N",
        help="cap on the resident-chain digests /health advertises for "
             "router residency bootstrap (MRU-first, host tier before "
             "disk) — keeps bootstrap payloads O(1) however deep the "
             "disk tier grows",
    )
    ap.add_argument(
        "--spec-decode", action="store_true",
        help="fleet-wide speculative decoding on the continuous ragged "
             "paged fleet: EVERY eligible greedy slot submits draft-then-"
             "verify rows inside the mixed launch (without this flag only "
             "requests passing \"speculative\": true speculate); the SLO "
             "scheduler throttles drafting to 0 under decode TPOT "
             "pressure, and greedy output stays bit-identical",
    )
    ap.add_argument(
        "--spec-draft-len", type=int, default=4, metavar="K",
        help="drafted tokens per mixed-launch verify row (0 disables the "
             "fleet speculation machinery entirely)",
    )
    ap.add_argument(
        "--spec-draft-model", default=None, metavar="NAME",
        help="draft the fleet's verify rows with a small same-tokenizer "
             "model's device-side greedy chain (shares the block tables "
             "over its own pool) instead of n-gram lookup; an attached "
             "--draft-model takes precedence over loading NAME",
    )
    ap.add_argument(
        "--die-on-wedge", type=float, default=None, metavar="SECONDS",
        help="exit the process (code 17) once an abandoned deadline-overrun "
             "device call has been stuck this long — a supervisor restart "
             "is the only real recovery from a wedged accelerator runtime; "
             "/health reports \"degraded\" with the stuck age either way "
             "(needs --deadline)",
    )
    ap.add_argument(
        "--queue", type=int, default=0, metavar="N",
        help="bounded request queue of depth N in front of the engine: "
             "concurrent singles coalesce into ragged batched fleets, "
             "full queue returns 429 (0 = disabled)",
    )
    ap.add_argument(
        "--queue-max-batch", type=int, default=8,
        help="largest coalesced fleet the queue dispatcher forms",
    )
    ap.add_argument(
        "--queue-wait-ms", type=float, default=5.0,
        help="coalescing window before a fleet is cut",
    )
    ap.add_argument(
        "--continuous", type=int, default=0, metavar="SLOTS",
        help="continuous (in-flight) batching: a fleet of SLOTS KV-cache "
             "rows decodes in lock-step and new requests join free slots "
             "mid-flight (llama + gpt2 families; single chip or a pp mesh "
             "with dp=1; 0 = disabled; mutually exclusive with --queue)",
    )
    ap.add_argument(
        "--continuous-chunk", type=int, default=16,
        help="decode steps per device round-trip in continuous mode",
    )
    ap.add_argument(
        "--continuous-max-seq", type=int, default=None, metavar="N",
        help="per-slot KV budget for --continuous (prompt + generated "
             "tokens per request; default: the model's max_seq_len). The "
             "fleet pins SLOTS x N of KV in HBM — cap it to what you "
             "actually serve: 8 slots x 4096 on a 7B-class model is "
             "~8.5 GB bf16 before weights",
    )
    ap.add_argument(
        "--kv-pool-blocks", type=int, default=None, metavar="N",
        help="block-paged KV for --continuous (llama family, single chip "
             "or a dp=1 pp/tp mesh — the pool shards layers over pp): "
             "a shared pool of N blocks replaces the dense SLOTS x max-seq "
             "fleet — HBM is a function of aggregate in-flight tokens and "
             "admission backpressures on pool exhaustion (engine/paged.py)",
    )
    ap.add_argument(
        "--kv-block-size", type=int, default=16,
        help="tokens per KV pool block (with --kv-pool-blocks)",
    )
    ap.add_argument(
        "--continuous-lag", type=int, default=2,
        help="decode chunks in flight before blocking on the oldest "
             "fetch (>1 hides a device-fetch RTT larger than a chunk's "
             "compute; EOS/stop noticed up to LAG chunks late)",
    )
    ap.add_argument(
        "--prefix-cache", type=int, default=0, metavar="N",
        help="keep N chunk-aligned prompt-prefix KV snapshots on device; "
             "requests sharing a stored prefix prefill only their tail "
             "(TTFT scales with new tokens, not the prompt)",
    )
    ap.add_argument(
        "--coordinator", default=None, metavar="HOST:PORT",
        help="multi-host DCN bring-up: jax.distributed coordinator address "
             "(use with --num-processes/--process-id on every host)",
    )
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument(
        "--warmup", action="store_true",
        help="pre-compile every (prefill, decode) bucket before serving "
             "(first requests then never pay jit latency)",
    )
    ap.add_argument(
        "--compile-cache", default=None, metavar="DIR",
        help="persistent XLA compilation cache directory: server restarts "
             "(and --warmup) reuse compiled programs instead of recompiling "
             "from scratch",
    )
    args = ap.parse_args(argv)

    if args.die_on_wedge and not args.deadline:
        # checked BEFORE the (potentially minutes-long) model load
        raise SystemExit(
            "--die-on-wedge needs --deadline: wedges are detected by "
            "deadline-overrun calls that never drain"
        )
    if args.adapter and not args.adapter_slots:
        raise SystemExit(
            "--adapter needs --adapter-slots N: the runtime pool's "
            "device pages are reserved at engine build"
        )
    if args.adapter_slots and (
        args.continuous <= 0 or args.kv_pool_blocks is None
    ):
        # also pre-model-load: a pool no request could ever select
        # (the adapter path rides the ragged paged fleet's mixed
        # launch) is a misconfiguration, not a degraded mode
        raise SystemExit(
            "--adapter-slots needs --continuous SLOTS with "
            "--kv-pool-blocks N: runtime adapters ride the ragged "
            "paged fleet's mixed launch"
        )
    adapter_specs = []
    for spec in args.adapter or ():
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            raise SystemExit(f"--adapter {spec!r}: expected NAME=DIR")
        adapter_specs.append((name, path))
    tenant_weights = []
    for spec in args.tenant_weight or ():
        name, sep, w = spec.partition("=")
        if not sep or not name:
            raise SystemExit(
                f"--tenant-weight {spec!r}: expected NAME=WEIGHT"
            )
        try:
            tenant_weights.append((name, float(w)))
        except ValueError:
            raise SystemExit(
                f"--tenant-weight {spec!r}: WEIGHT must be a number"
            ) from None
    from ..utils import faults as _faults

    if args.faults:
        try:
            _faults.arm(args.faults)
        except ValueError as e:
            raise SystemExit(f"--faults: {e}") from e
        print(f"💥 fault injection armed: {args.faults}")
    elif _faults.arm_from_env() is not None:
        print(f"💥 fault injection armed from DLI_FAULTS")
    if args.compile_cache:
        import jax

        jax.config.update("jax_compilation_cache_dir", args.compile_cache)
        # cache even fast-to-compile programs: restart latency is the point
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    if args.coordinator or args.num_processes is not None or args.process_id is not None:
        from ..parallel.mesh import multihost_initialize

        multihost_initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    import jax as _jax

    if _jax.process_count() > 1 and (args.continuous > 0 or args.queue > 0):
        # checked BEFORE the checkpoint load + warmup (the expensive
        # steps): batching by request ARRIVAL TIMING cannot mirror
        # deterministically across processes
        raise SystemExit(
            "--continuous/--queue batch by request ARRIVAL TIMING, "
            "which cannot mirror deterministically across processes; "
            "mirrored multi-process serving drives the bare engine. "
            "For admission layers on a multi-process fleet, use the "
            "MPMD stage runtime (serving/stage_runtime.py --frontend): "
            "its controller owns arrival timing and drives stages over "
            "the stage transport"
        )
    mesh_cfg = MeshConfig(
        dp=args.dp, pp=args.pp, sp=args.sp, tp=args.tp, ep=args.ep
    )
    model, params, dtype = args.model, None, args.dtype
    if args.checkpoint:
        model, params = _load_checkpoint(args, mesh_cfg)
        dtype = None  # the checkpoint's recorded dtype governs
    tokenizer = None
    tok_src = args.tokenizer or (
        args.checkpoint if args.checkpoint and _has_tokenizer_files(args.checkpoint)
        else None
    )
    if tok_src:
        from ..utils.tokenizer import load_tokenizer

        # strict: serving real weights through the byte fallback produces
        # garbled text with status "success" (round-2 review weak #6)
        tokenizer = load_tokenizer(tok_src, strict=True)
    elif args.checkpoint:
        print(
            "⚠️  --checkpoint without a tokenizer: responses will be "
            "byte-decoded. Pass --tokenizer PATH for real text."
        )
    engine = create_engine(
        model,
        mesh_cfg=mesh_cfg,
        engine_cfg=EngineConfig(
            request_deadline_s=args.deadline,
            prefix_cache_entries=args.prefix_cache,
            kv_shadow=not args.no_kv_shadow,
            kv_fabric=not args.no_kv_fabric,
            kv_fabric_timeout_s=args.kv_fabric_timeout,
            kv_disk_dir=args.kv_disk_dir,
            kv_disk_blocks=args.kv_disk_blocks,
            kv_fabric_stream=not args.no_kv_stream,
            kv_health_digests=args.kv_health_digests,
            replica_class=args.replica_class,
            spec_decode=args.spec_decode,
            spec_draft_len=args.spec_draft_len,
            spec_draft_model=args.spec_draft_model,
            pp_wire_quant=args.pp_wire_quant,
            adapter_slots=args.adapter_slots,
            adapter_rank=args.adapter_rank,
            tenant_weights=tuple(tenant_weights),
            tenant_max_queue_share=args.tenant_queue_share,
            trace_sample_rate=args.trace_sample_rate,
        ),
        microbatches=args.microbatches,
        params=params,
        dtype=dtype,
        quant=args.quant,
        kv_quant=args.kv_quant,
        attn_impl=args.attn_impl,
        tokenizer=tokenizer,
        seed=args.seed,
        sp_strategy=args.sp_strategy,
        draft_model=args.draft_model,
        lora=args.lora,
    )
    for name, path in adapter_specs:
        try:
            # fails startup loudly on a bad directory, rank overflow,
            # shape mismatch, or the --lora merge-at-load collision
            engine.adapters.register(name, path)
        except (ValueError, OSError) as e:
            raise SystemExit(f"--adapter {name}={path}: {e}") from e
    if adapter_specs:
        print(
            f"🎛  {len(adapter_specs)} adapter(s) registered: "
            f"{', '.join(n for n, _ in adapter_specs)}"
        )
    if args.die_on_wedge:

        def _wedge_reaper():
            import os as _os

            while True:
                time.sleep(max(1.0, min(args.die_on_wedge / 4, 10.0)))
                age = engine.max_wedged_age()
                if age is not None and age > args.die_on_wedge:
                    print(
                        f"💀 wedged device call stuck {age:.0f}s > "
                        f"--die-on-wedge {args.die_on_wedge:g}s; exiting "
                        f"for a supervisor restart"
                    )
                    _os._exit(17)

        threading.Thread(target=_wedge_reaper, daemon=True).start()
    if args.warmup:
        print("⏳ warming up (compiling all bucket shapes)...")
        try:
            stats = engine.warmup()
        except ValueError as e:
            # backend bucket-validation errors (e.g. a prefill bucket not
            # divisible by sp on a context-parallel mesh) should name the
            # fix, not crash startup with a bare traceback
            raise SystemExit(
                f"--warmup failed: {e}\nfix the engine prefill_buckets / "
                f"mesh shape so every bucket is servable, or start without "
                f"--warmup"
            ) from e
        print(f"✅ warm: {stats['programs']} programs in {stats['seconds']}s")
    if _jax.process_count() > 1:
        # multi-process SPMD serving (the reference's N-machine shape,
        # Worker1.py:248-266): every process built the same engine above
        # (warmup included — identical program sequence; --continuous/
        # --queue were rejected before the model load); process 0 now
        # serves HTTP and broadcasts each request so followers mirror the
        # device program launches (serving/multihost.py).
        from .multihost import MirroredEngine, follower_loop

        if _jax.process_index() != 0:
            print(
                f"🛰  follower {_jax.process_index()}/{_jax.process_count()}"
                f" mirroring leader requests"
            )
            follower_loop(engine, _jax.process_index())
            return
        engine = MirroredEngine(engine)
    queue = None
    continuous = None
    if args.continuous > 0 and args.queue > 0:
        raise SystemExit(
            "--continuous and --queue are mutually exclusive: in-flight "
            "batching already provides bounded admission + batching"
        )
    if args.kv_pool_blocks is not None and args.continuous <= 0:
        raise SystemExit("--kv-pool-blocks requires --continuous")
    if args.continuous > 0:
        from ..engine.continuous import ContinuousEngine

        continuous = ContinuousEngine(
            engine, n_slots=args.continuous, chunk_steps=args.continuous_chunk,
            chunk_lag=args.continuous_lag, slot_max_seq=args.continuous_max_seq,
            kv_pool_blocks=args.kv_pool_blocks,
            kv_block_size=args.kv_block_size,
            restart_budget=args.restart_budget,
            poison_strikes=args.poison_strikes,
            restore_dir=args.restore_dir,
        )
        if args.warmup:
            w = continuous.warmup()
            if not w["ok"]:
                raise SystemExit(
                    f"--warmup failed on the continuous engine: {w}\n"
                    f"fix the configuration or start without --warmup"
                )
            print(f"✅ continuous warm in {w['seconds']}s")
    elif args.queue > 0:
        from .queue import BatchingQueue

        queue = BatchingQueue(
            engine, max_queue=args.queue, max_batch=args.queue_max_batch,
            max_wait_ms=args.queue_wait_ms,
        )
    try:
        InferenceServer(
            engine, args.host, args.port, args.max_tokens_cap, queue=queue,
            continuous=continuous, drain_deadline_s=args.drain_deadline,
            wedge_unready_s=args.wedge_unready,
        ).serve_forever()
    finally:
        if hasattr(engine, "shutdown_followers"):
            # release the follower loops (blocked in the broadcast
            # collective) so a leader shutdown doesn't strand N-1 hung
            # processes until the distributed heartbeat reaps them
            engine.shutdown_followers()


if __name__ == "__main__":
    main()
