"""Cross-replica KV fabric: shadowed KV blocks as a WIRE format.

The shadow store (engine/shadow.py) made filled paged-KV blocks a
content-keyed, host-portable artifact for crash recovery — and, since
the tiered hierarchy, a cache whose logical depth is bounded by disk.
This module promotes that artifact to a wire format so N replicas'
caches behave as one logical cache — the disaggregated-serving shape
the router tier builds on (serving/router.py: prefill-class replicas
compute long prefixes, decode-class replicas pull them by digest and
run the token loop, TTFT and TPOT stop competing for one step budget).

Pieces, all strictly host-side (pinned decode-UNREACHABLE in the
tests/test_analysis.py callgraph fixture, like the router tier):

  * WIRE FORMAT: encode_chain/decode_chain serialize one shadow chain —
    parents-first blocks of one token prefix — as an npz blob: a JSON
    manifest (version, block_size, per-block token chunks) plus the
    stacked per-leaf KV arrays, the exact layout ShadowStore entries
    hold. The manifest carries the TOKENS, not the digest: the fetcher
    recomputes the parent-chained digests (engine/block_prefix.
    chunk_digests) from the payload's own tokens and rejects any blob
    whose recomputed digest differs from the one it asked for. That
    content-key recheck is the whole consistency protocol — KV is a pure
    function of the token prefix under teacher forcing, so a verified
    chain is bit-identical to one computed locally, and a corrupt,
    truncated, or wrong-prefix payload can only produce a REJECTION
    (cold local prefill), never wrong output.
  * STREAM FORMAT: encode_frame/decode_frame carry ONE block per frame —
    [8-byte big-endian length][npz: manifest {version, block_size,
    c: chunk tokens, d: claimed running digest} + per-leaf single-block
    arrays], terminated by a zero-length frame. The fetcher verifies the
    RUNNING parent-chained digest after every frame (early abort on the
    first bad one) and the final digest against the one it asked for, so
    a streamed chain meets exactly the whole-blob bar — but the importer
    can scatter block i into the pool while block i+1 is still on the
    wire, overlapping the pull with device work instead of buffering the
    whole manifest (GET /kv/{digest} with X-KV-Stream: 1; old peers
    ignore the header and answer whole-blob, which the client detects by
    Content-Type and falls back to transparently).
  * SERVER: serve_chain(shadow, digest) -> npz bytes | None and
    serve_chain_stream(shadow, digest) -> (n_chunks, tier, frame iter) |
    None back the replica's GET /kv/{digest} route (serving/server.py);
    the stream side encodes chunk-at-a-time, so time-to-first-byte is
    O(1) in chain length. A miss — never resident, or churned out of
    every tier — is a 404 the fetcher treats as "prefill locally".
    decode_push validates a proactively POSTed chain against its OWN
    content key (the digest is recomputed from the payload's tokens, so
    a push needs no out-of-band name to be verifiable).
  * CLIENT: KVFabricClient.fetch / fetch_stream with a hard deadline —
    EVERY failure (connect refused on a kill -9'd peer, a wedged socket
    timing out, 404, a payload failing the recheck mid-stream) ends at
    None / FabricPayloadError and the fallback ladder ends at local
    re-prefill, never at an error. push_chain POSTs a finished chain to
    the decode peer at the prefill->decode handoff so the decode side
    never round-trips a pull. Counts
    dli_kv_fabric_{fetches,hits,misses}_total{role},
    dli_kv_fabric_bytes_total{role,tier} (tier = the SERVING tier at
    the peer — host|disk — or "push"), and
    dli_kv_fabric_fetch_seconds (families pre-registered in
    engine/engine.py; role = this replica's --replica-class). All
    verified wire bytes route through _account_link("kv-fabric-dcn"),
    the comms-contract seam analysis/comms.py audits WIRE_LINKS against.
"""

from __future__ import annotations

import io
import json
import time
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

from ..engine.block_prefix import chunk_digests
from ..utils.logging import get_logger, request_id_context

log = get_logger("kv_fabric")

WIRE_VERSION = 1

# stream framing: 8-byte big-endian length prefix per frame, zero-length
# frame terminates; Content-Type distinguishes streamed from whole-blob
STREAM_CONTENT_TYPE = "application/x-dli-kv-stream"
_FRAME_LEN = 8
_MAX_FRAME = 1 << 31  # sanity bound before allocating for a frame

# hex digests only (block_prefix.chunk_digests emits truncated sha1 hex);
# the /kv route validates against this so a probing client cannot make
# the digest index do arbitrary-string lookups
_DIGEST_CHARS = frozenset("0123456789abcdef")
MAX_DIGEST_LEN = 64


def valid_digest(digest: str) -> bool:
    return (
        0 < len(digest) <= MAX_DIGEST_LEN
        and all(c in _DIGEST_CHARS for c in digest)
    )


class FabricPayloadError(ValueError):
    """A /kv payload failed structural validation or the content-key
    recheck. Callers degrade to local prefill — never an error."""


# jaxlint: decode-unreachable -- public digest helper for peers/tests; no in-package caller
def chain_digest(ids, block_size: int) -> Optional[str]:
    """The deepest parent-chained digest of `ids`' full blocks — the name
    a peer would serve this prefix under — or None when `ids` has no full
    block."""
    n = len(ids) // block_size
    if n <= 0:
        return None
    return chunk_digests(ids, block_size, max_chunks=n)[-1]


def encode_chain(block_size: int, keys: list, entries: list) -> bytes:
    """Serialize one parents-first chain. keys[i] is the token prefix
    block i completes (len == (i+1) * block_size, each extending the
    previous by one chunk); entries[i] carries .leaves — the per-leaf
    arrays in jax.tree flatten order of the pool, exactly as the shadow
    store holds them."""
    if not keys:
        raise ValueError("encode_chain needs a non-empty chain")
    chunks = []
    for i, key in enumerate(keys):
        if len(key) != (i + 1) * block_size:
            raise ValueError(
                f"chain key {i} has {len(key)} tokens, expected "
                f"{(i + 1) * block_size}"
            )
        chunks.append([int(t) for t in key[-block_size:]])
    manifest = {
        "version": WIRE_VERSION,
        "block_size": int(block_size),
        "chunks": chunks,
    }
    arrays = {"manifest": np.array(json.dumps(manifest))}
    for j in range(len(entries[0].leaves)):
        arrays[f"leaf_{j}"] = np.stack([e.leaves[j] for e in entries])
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _parse_chain(data: bytes, block_size: int) -> tuple:
    """Structural half of chain validation (no digest comparison):
    parse + validate one wire blob, returning (keys, per_block_leaves,
    ids). Raises FabricPayloadError on any malformation."""
    try:
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            manifest = json.loads(str(z["manifest"]))
            leaves = []
            j = 0
            while f"leaf_{j}" in z.files:
                leaves.append(z[f"leaf_{j}"])
                j += 1
    except Exception as e:
        raise FabricPayloadError(f"unparseable /kv payload: {e}") from e
    if manifest.get("version") != WIRE_VERSION:
        raise FabricPayloadError(
            f"wire version {manifest.get('version')!r} != {WIRE_VERSION}"
        )
    if manifest.get("block_size") != block_size:
        raise FabricPayloadError(
            f"peer block_size {manifest.get('block_size')!r} != local "
            f"{block_size} — replicas must share --kv-block-size"
        )
    chunks = manifest.get("chunks") or []
    if not chunks or not leaves or any(
        leaf.shape[0] != len(chunks) for leaf in leaves
    ):
        raise FabricPayloadError("empty or ragged /kv payload")
    ids: list = []
    keys = []
    for chunk in chunks:
        if len(chunk) != block_size:
            raise FabricPayloadError("chunk length != block_size")
        ids.extend(int(t) for t in chunk)
        keys.append(tuple(ids))
    per_block = [
        [leaf[i] for leaf in leaves] for i in range(len(chunks))
    ]
    return keys, per_block, ids


def decode_chain(data: bytes, block_size: int,
                 expected_digest: str) -> tuple:
    """Parse + VERIFY one wire chain. Returns (keys, per_block_leaves):
    keys parents-first, per_block_leaves[i] the list of per-leaf arrays
    for block i (the put_host / restore-scatter layout).

    The content-key recheck: the parent-chained digest is recomputed
    from the payload's OWN token chunks and must equal the digest the
    caller fetched by. A tampered token, a truncated chain, a
    block-size mismatch, or a peer answering with the wrong prefix all
    land here as FabricPayloadError — the caller prefills locally."""
    keys, per_block, ids = _parse_chain(data, block_size)
    got = chunk_digests(ids, block_size, max_chunks=len(keys))[-1]
    if got != expected_digest:
        raise FabricPayloadError(
            f"content-key recheck failed: payload tokens digest to "
            f"{got}, fetched {expected_digest}"
        )
    return keys, per_block


def decode_push(data: bytes, block_size: int) -> tuple:
    """Validate a proactively PUSHED chain (POST /kv) against its OWN
    content key: the digest is recomputed from the payload's tokens —
    there is nothing external to compare against, and nothing needed;
    content keying means the payload names itself, and a tampered one
    simply names a prefix nobody will ever look up (plus the structural
    checks reject ragged/malformed blobs outright). Returns
    (digest, keys, per_block_leaves)."""
    keys, per_block, ids = _parse_chain(data, block_size)
    digest = chunk_digests(ids, block_size, max_chunks=len(keys))[-1]
    return digest, keys, per_block


def encode_frame(block_size: int, chunk, digest: str, leaves) -> bytes:
    """Serialize ONE stream frame (no length prefix): the block's own
    token chunk, the claimed RUNNING parent-chained digest through this
    block, and the per-leaf single-block arrays."""
    manifest = {
        "version": WIRE_VERSION,
        "block_size": int(block_size),
        "c": [int(t) for t in chunk],
        "d": str(digest),
    }
    arrays = {"manifest": np.array(json.dumps(manifest))}
    for j, leaf in enumerate(leaves):
        arrays[f"leaf_{j}"] = np.asarray(leaf)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def decode_frame(data: bytes, block_size: int) -> tuple:
    """Parse one stream frame -> (chunk_tokens, claimed_digest, leaves).
    Structural checks only — the RUNNING digest comparison is the
    stream consumer's (it owns the accumulated token prefix)."""
    try:
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            manifest = json.loads(str(z["manifest"]))
            leaves = []
            j = 0
            while f"leaf_{j}" in z.files:
                leaves.append(np.array(z[f"leaf_{j}"]))
                j += 1
    except Exception as e:
        raise FabricPayloadError(f"unparseable /kv frame: {e}") from e
    if manifest.get("version") != WIRE_VERSION:
        raise FabricPayloadError(
            f"frame version {manifest.get('version')!r} != {WIRE_VERSION}"
        )
    if manifest.get("block_size") != block_size:
        raise FabricPayloadError(
            f"frame block_size {manifest.get('block_size')!r} != local "
            f"{block_size}"
        )
    chunk = manifest.get("c") or []
    digest = manifest.get("d") or ""
    if len(chunk) != block_size or not valid_digest(digest) or not leaves:
        raise FabricPayloadError("malformed /kv frame")
    return [int(t) for t in chunk], digest, leaves


def serve_chain(shadow, digest: str) -> Optional[bytes]:
    """The /kv route's whole-blob body: the resident chain ending at
    `digest`, wire-encoded, or None (-> 404) when not resident / not a
    valid digest."""
    if not valid_digest(digest):
        return None
    chain = shadow.chain_for_digest(digest)
    if chain is None:
        return None
    keys, entries = chain
    return encode_chain(shadow.block_size, keys, entries)


def serve_chain_stream(shadow, digest: str) -> Optional[tuple]:
    """The /kv route's STREAMED body: (n_chunks, tier, frame iterator)
    or None (-> 404). `tier` is where the chain tip was resident BEFORE
    this lookup promoted it ("host" | "disk" — the response's X-KV-Tier
    and the peer's bytes{tier} label). Frames are length-prefixed and
    encoded lazily, one block at a time, ending with the zero-length
    terminator — time-to-first-byte is O(1) in chain length."""
    if not valid_digest(digest):
        return None
    tier = shadow.digest_tier(digest) or "host"
    chain = shadow.chain_for_digest(digest)
    if chain is None:
        return None
    keys, entries = chain
    bs = shadow.block_size
    digests = chunk_digests(keys[-1], bs, max_chunks=len(keys))

    def frames():
        for i, (key, e) in enumerate(zip(keys, entries)):
            payload = encode_frame(bs, key[-bs:], digests[i], e.leaves)
            yield len(payload).to_bytes(_FRAME_LEN, "big") + payload
        yield (0).to_bytes(_FRAME_LEN, "big")

    return len(keys), tier, frames()


def _read_exact(r, n: int) -> bytes:
    """Read exactly n bytes from the response (r.read(n) may return
    short on a chunked socket) — short final read = truncated stream."""
    out = b""
    while len(out) < n:
        piece = r.read(n - len(out))
        if not piece:
            raise FabricPayloadError("truncated /kv stream")
        out += piece
    return out


class KVFabricClient:
    """One replica's fetching/pushing half of the fabric. Deadline'd,
    metric'd, and failure-silent: fetch()/fetch_stream()/push_chain()
    return the verified result or None."""

    def __init__(self, registry=None, role: str = "mixed",
                 timeout_s: float = 5.0):
        self.role = str(role)
        self.timeout_s = float(timeout_s)
        self.fetches = 0
        self.hits = 0
        self.misses = 0
        self.bytes = 0
        self.pushes = 0
        self.pushed_blocks = 0
        # serving tier of the last successful fetch (observability for
        # the single-threaded prefetch caller's flight event)
        self.last_tier = "host"
        self._m_fetches = self._m_hits = None
        self._m_misses = self._m_seconds = None
        self._m_bytes: dict = {}
        if registry is not None:
            self._m_fetches = registry.counter(
                "dli_kv_fabric_fetches_total",
                "cross-replica /kv chain fetches attempted", ("role",),
            ).labels(role=self.role)
            self._m_hits = registry.counter(
                "dli_kv_fabric_hits_total",
                "fabric fetches that returned a verified chain", ("role",),
            ).labels(role=self.role)
            self._m_misses = registry.counter(
                "dli_kv_fabric_misses_total",
                "fabric fetches that fell back to local prefill (404, "
                "dead/wedged peer, failed content-key recheck)", ("role",),
            ).labels(role=self.role)
            fam = registry.counter(
                "dli_kv_fabric_bytes_total",
                "wire bytes of verified fabric chains moved, by serving "
                "tier (host/disk = pull source at the peer, push = "
                "proactive POST /kv at the prefill->decode handoff)",
                ("role", "tier"),
            )
            for tier in ("host", "disk", "push"):
                self._m_bytes[tier] = fam.labels(role=self.role, tier=tier)
            self._m_seconds = registry.histogram(
                "dli_kv_fabric_fetch_seconds",
                "fabric fetch wall time, failures included",
            ).labels()

    def _account_link(self, name: str, nbytes: int, tier: str):
        """Account verified /kv wire bytes against the comms contract:
        `name` is the WIRE_LINKS row (analysis/comms.py audits that
        every symbolic row has a literal call site here — the same seam
        the ICI collectives route through), `tier` the serving tier at
        the peer (host | disk | push)."""
        del name  # the literal at the call site is the contract
        self.bytes += int(nbytes)
        m = self._m_bytes.get(tier if tier in self._m_bytes else "host")
        if m is not None:
            m.inc(int(nbytes))

    def _headers(self, ctx, request_id, stream: bool = False) -> dict:
        headers = {}
        if ctx is not None:
            headers["traceparent"] = ctx.header()
        if request_id:
            headers["X-Request-Id"] = request_id
        if stream:
            headers["X-KV-Stream"] = "1"
        return headers

    def fetch(self, peer_url: str, digest: str, block_size: int,
              ctx=None, request_id=None, store=None) -> Optional[tuple]:
        """GET {peer}/kv/{digest}, verify, return (keys, per_block_leaves)
        or None. Bounded by timeout_s end to end (a wedged peer costs one
        deadline, then the caller prefills locally).

        Fleet tracing (ISSUE 17): `ctx` (a tracing.SpanContext) rides
        the request as a `traceparent` header so the serving peer's /kv
        span joins the same trace, `request_id` rides as X-Request-Id
        (echoed back by the peer), and `store` (a TraceStore) records
        this side's `fabric.pull` span around the whole fetch —
        context managed, so every early return above closes it."""
        self.fetches += 1
        if self._m_fetches is not None:
            self._m_fetches.inc()
        t0 = time.perf_counter()
        wall0 = time.time()
        ok = False
        tier = "host"
        with request_id_context(request_id, getattr(ctx, "trace_id", None)):
            try:
                if not valid_digest(digest):
                    raise FabricPayloadError(
                        f"invalid digest {digest[:80]!r}"
                    )
                url = peer_url.rstrip("/") + "/kv/" + digest
                req = urllib.request.Request(
                    url, headers=self._headers(ctx, request_id)
                )
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as r:
                    tier = r.headers.get("X-KV-Tier") or "host"
                    data = r.read()
                out = decode_chain(data, block_size, digest)
                ok = True
            except FabricPayloadError as e:
                log.warning("kv_fabric_payload_rejected", peer=peer_url,
                            digest=digest, error=str(e))
                out = None
            except (urllib.error.URLError, urllib.error.HTTPError, OSError,
                    TimeoutError, ValueError) as e:
                # 404 (evicted / never resident), connect refused (peer
                # kill -9'd mid-handoff), socket timeout (wedged peer) —
                # all one outcome: prefill locally
                log.info("kv_fabric_miss", peer=peer_url, digest=digest,
                         error=str(e))
                out = None
            finally:
                if self._m_seconds is not None:
                    self._m_seconds.observe(time.perf_counter() - t0)
                if store is not None and ctx is not None:
                    store.add_span(
                        ctx.trace_id, "fabric.pull", wall0, time.time(),
                        parent_id=ctx.span_id,
                        attrs={
                            "peer": peer_url, "digest": str(digest)[:16],
                            "hit": ok, "streamed": False, "tier": tier,
                        },
                    )
        if not ok or out is None:
            self.misses += 1
            if self._m_misses is not None:
                self._m_misses.inc()
            return None
        self.hits += 1
        self.last_tier = tier
        self._account_link("kv-fabric-dcn", len(data), tier)
        if self._m_hits is not None:
            self._m_hits.inc()
        return out

    def fetch_stream(self, peer_url: str, digest: str, block_size: int,
                     ctx=None, request_id=None,
                     store=None) -> Optional[tuple]:
        """GET {peer}/kv/{digest} with X-KV-Stream: 1 — returns
        (n_chunks, tier, blocks_iter) or None (connect/404/invalid).
        blocks_iter yields (key, leaves) per block, parents-first, each
        verified against the RUNNING recomputed digest as it arrives
        (the final one against the digest asked for), and raises
        FabricPayloadError / OSError mid-iteration on tamper,
        truncation, or a died socket — the consumer discards everything
        it scattered (nothing was registered yet) and prefills locally.
        Fully consuming OR closing the iterator settles the hit/miss
        metrics and the `fabric.pull` span.

        A pre-stream peer ignores the header and answers whole-blob
        (Content-Type octet-stream): detected and decoded in one piece,
        then yielded block-at-a-time — same contract, no overlap."""
        self.fetches += 1
        if self._m_fetches is not None:
            self._m_fetches.inc()
        t0 = time.perf_counter()
        wall0 = time.time()
        if not valid_digest(digest):
            self.misses += 1
            if self._m_misses is not None:
                self._m_misses.inc()
            return None
        url = peer_url.rstrip("/") + "/kv/" + digest
        req = urllib.request.Request(
            url, headers=self._headers(ctx, request_id, stream=True)
        )
        try:
            r = urllib.request.urlopen(req, timeout=self.timeout_s)
        except (urllib.error.URLError, urllib.error.HTTPError, OSError,
                TimeoutError, ValueError) as e:
            log.info("kv_fabric_miss", peer=peer_url, digest=digest,
                     error=str(e))
            if self._m_seconds is not None:
                self._m_seconds.observe(time.perf_counter() - t0)
            self.misses += 1
            if self._m_misses is not None:
                self._m_misses.inc()
            return None
        streamed = (
            (r.headers.get("Content-Type") or "") == STREAM_CONTENT_TYPE
        )
        tier = r.headers.get("X-KV-Tier") or "host"
        try:
            n_chunks = max(0, int(r.headers.get("X-KV-Chain-Len") or 0))
        except ValueError:
            n_chunks = 0

        def blocks():
            ok = False
            nbytes = 0
            try:
                if not streamed:
                    # pre-stream peer: whole blob, verified in one piece
                    data = r.read()
                    nbytes = len(data)
                    keys, per_block = decode_chain(data, block_size, digest)
                    for key, leaves in zip(keys, per_block):
                        yield key, leaves
                    ok = True
                    return
                ids: list = []
                deadline = time.monotonic() + self.timeout_s
                while True:
                    if time.monotonic() > deadline:
                        raise FabricPayloadError("/kv stream overran the "
                                                 "fetch deadline")
                    hdr = _read_exact(r, _FRAME_LEN)
                    length = int.from_bytes(hdr, "big")
                    if length == 0:
                        break  # clean terminator
                    if length > _MAX_FRAME:
                        raise FabricPayloadError("oversized /kv frame")
                    payload = _read_exact(r, length)
                    nbytes += _FRAME_LEN + length
                    chunk, claimed, leaves = decode_frame(
                        payload, block_size
                    )
                    ids.extend(chunk)
                    got = chunk_digests(
                        ids, block_size, max_chunks=len(ids) // block_size
                    )[-1]
                    if got != claimed:
                        raise FabricPayloadError(
                            f"running content-key recheck failed at chunk "
                            f"{len(ids) // block_size}: tokens digest to "
                            f"{got}, frame claims {claimed}"
                        )
                    yield tuple(ids), leaves
                if not ids:
                    raise FabricPayloadError("empty /kv stream")
                final = chunk_digests(
                    ids, block_size, max_chunks=len(ids) // block_size
                )[-1]
                if final != digest:
                    raise FabricPayloadError(
                        f"content-key recheck failed: stream tokens digest "
                        f"to {final}, fetched {digest}"
                    )
                ok = True
            except FabricPayloadError as e:
                log.warning("kv_fabric_payload_rejected", peer=peer_url,
                            digest=digest, error=str(e))
                raise
            finally:
                try:
                    r.close()
                except OSError:
                    pass
                if self._m_seconds is not None:
                    self._m_seconds.observe(time.perf_counter() - t0)
                if ok:
                    self.hits += 1
                    self._account_link("kv-fabric-dcn", nbytes, tier)
                    if self._m_hits is not None:
                        self._m_hits.inc()
                else:
                    self.misses += 1
                    if self._m_misses is not None:
                        self._m_misses.inc()
                if store is not None and ctx is not None:
                    store.add_span(
                        ctx.trace_id, "fabric.pull", wall0, time.time(),
                        parent_id=ctx.span_id,
                        attrs={
                            "peer": peer_url, "digest": str(digest)[:16],
                            "hit": ok, "streamed": streamed, "tier": tier,
                        },
                    )

        return n_chunks, tier, blocks()

    def push_chain(self, peer_url: str, data: bytes, ctx=None,
                   request_id=None, store=None) -> Optional[int]:
        """POST {peer}/kv — proactively hand a finished wire-encoded
        chain to the decode peer at the prefill->decode handoff, so its
        admission finds the prefix already host-resident instead of
        round-tripping a pull. Returns the peer's accepted block count,
        or None on ANY failure (the pull path remains the fallback —
        a failed push costs nothing but this deadline)."""
        self.pushes += 1
        t0 = time.perf_counter()
        wall0 = time.time()
        accepted = None
        with request_id_context(request_id, getattr(ctx, "trace_id", None)):
            try:
                url = peer_url.rstrip("/") + "/kv"
                headers = self._headers(ctx, request_id)
                headers["Content-Type"] = "application/octet-stream"
                req = urllib.request.Request(
                    url, data=data, headers=headers, method="POST"
                )
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as r:
                    body = json.loads(r.read().decode("utf-8"))
                accepted = int(body.get("accepted", 0))
                self.pushed_blocks += accepted
                self._account_link("kv-fabric-dcn", len(data), "push")
            except (urllib.error.URLError, urllib.error.HTTPError, OSError,
                    TimeoutError, ValueError) as e:
                log.info("kv_fabric_push_failed", peer=peer_url,
                         error=str(e))
            finally:
                if store is not None and ctx is not None:
                    store.add_span(
                        ctx.trace_id, "fabric.push", wall0, time.time(),
                        parent_id=ctx.span_id,
                        attrs={
                            "peer": peer_url, "bytes": len(data),
                            "accepted": -1 if accepted is None else accepted,
                        },
                    )
                del t0
        return accepted

    def stats(self) -> dict:
        return {
            "role": self.role,
            "fetches": self.fetches,
            "hits": self.hits,
            "misses": self.misses,
            "bytes": self.bytes,
            "pushes": self.pushes,
            "pushed_blocks": self.pushed_blocks,
            "timeout_s": self.timeout_s,
        }
