"""Cross-replica KV fabric: shadowed KV blocks as a WIRE format.

The shadow store (engine/shadow.py) made filled paged-KV blocks a
content-keyed, host-portable artifact for crash recovery. This module
promotes that artifact to a wire format so N replicas' caches behave as
one logical cache — the disaggregated-serving shape the router tier
builds on (serving/router.py: prefill-class replicas compute long
prefixes, decode-class replicas pull them by digest and run the token
loop, TTFT and TPOT stop competing for one step budget).

Three pieces, all strictly host-side (pinned decode-UNREACHABLE in the
tests/test_analysis.py callgraph fixture, like the router tier):

  * WIRE FORMAT: encode_chain/decode_chain serialize one shadow chain —
    parents-first blocks of one token prefix — as an npz blob: a JSON
    manifest (version, block_size, per-block token chunks) plus the
    stacked per-leaf KV arrays, the exact layout ShadowStore entries
    hold. The manifest carries the TOKENS, not the digest: the fetcher
    recomputes the parent-chained digests (engine/block_prefix.
    chunk_digests) from the payload's own tokens and rejects any blob
    whose recomputed digest differs from the one it asked for. That
    content-key recheck is the whole consistency protocol — KV is a pure
    function of the token prefix under teacher forcing, so a verified
    chain is bit-identical to one computed locally, and a corrupt,
    truncated, or wrong-prefix payload can only produce a REJECTION
    (cold local prefill), never wrong output.
  * SERVER: serve_chain(shadow, digest) -> npz bytes | None backs the
    replica's GET /kv/{digest} route (serving/server.py). A miss — the
    digest was never resident, or LRU churn evicted it — is a 404 the
    fetcher treats as "prefill locally".
  * CLIENT: KVFabricClient.fetch(peer, digest) with a hard deadline.
    EVERY failure (connect refused on a kill -9'd peer, a wedged socket
    timing out, 404, a payload failing the recheck) returns None — the
    fallback ladder ends at local re-prefill, never at an error. Counts
    dli_kv_fabric_{fetches,hits,misses,bytes}_total{role} and
    dli_kv_fabric_fetch_seconds (families pre-registered in
    engine/engine.py; role = this replica's --replica-class).
"""

from __future__ import annotations

import io
import json
import time
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

from ..engine.block_prefix import chunk_digests
from ..utils.logging import get_logger, request_id_context

log = get_logger("kv_fabric")

WIRE_VERSION = 1

# hex digests only (block_prefix.chunk_digests emits truncated sha1 hex);
# the /kv route validates against this so a probing client cannot make
# the digest index do arbitrary-string lookups
_DIGEST_CHARS = frozenset("0123456789abcdef")
MAX_DIGEST_LEN = 64


def valid_digest(digest: str) -> bool:
    return (
        0 < len(digest) <= MAX_DIGEST_LEN
        and all(c in _DIGEST_CHARS for c in digest)
    )


class FabricPayloadError(ValueError):
    """A /kv payload failed structural validation or the content-key
    recheck. Callers degrade to local prefill — never an error."""


# jaxlint: decode-unreachable -- public digest helper for peers/tests; no in-package caller
def chain_digest(ids, block_size: int) -> Optional[str]:
    """The deepest parent-chained digest of `ids`' full blocks — the name
    a peer would serve this prefix under — or None when `ids` has no full
    block."""
    n = len(ids) // block_size
    if n <= 0:
        return None
    return chunk_digests(ids, block_size, max_chunks=n)[-1]


def encode_chain(block_size: int, keys: list, entries: list) -> bytes:
    """Serialize one parents-first chain. keys[i] is the token prefix
    block i completes (len == (i+1) * block_size, each extending the
    previous by one chunk); entries[i] carries .leaves — the per-leaf
    arrays in jax.tree flatten order of the pool, exactly as the shadow
    store holds them."""
    if not keys:
        raise ValueError("encode_chain needs a non-empty chain")
    chunks = []
    for i, key in enumerate(keys):
        if len(key) != (i + 1) * block_size:
            raise ValueError(
                f"chain key {i} has {len(key)} tokens, expected "
                f"{(i + 1) * block_size}"
            )
        chunks.append([int(t) for t in key[-block_size:]])
    manifest = {
        "version": WIRE_VERSION,
        "block_size": int(block_size),
        "chunks": chunks,
    }
    arrays = {"manifest": np.array(json.dumps(manifest))}
    for j in range(len(entries[0].leaves)):
        arrays[f"leaf_{j}"] = np.stack([e.leaves[j] for e in entries])
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def decode_chain(data: bytes, block_size: int,
                 expected_digest: str) -> tuple:
    """Parse + VERIFY one wire chain. Returns (keys, per_block_leaves):
    keys parents-first, per_block_leaves[i] the list of per-leaf arrays
    for block i (the put_host / restore-scatter layout).

    The content-key recheck: the parent-chained digest is recomputed
    from the payload's OWN token chunks and must equal the digest the
    caller fetched by. A tampered token, a truncated chain, a
    block-size mismatch, or a peer answering with the wrong prefix all
    land here as FabricPayloadError — the caller prefills locally."""
    try:
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            manifest = json.loads(str(z["manifest"]))
            leaves = []
            j = 0
            while f"leaf_{j}" in z.files:
                leaves.append(z[f"leaf_{j}"])
                j += 1
    except Exception as e:
        raise FabricPayloadError(f"unparseable /kv payload: {e}") from e
    if manifest.get("version") != WIRE_VERSION:
        raise FabricPayloadError(
            f"wire version {manifest.get('version')!r} != {WIRE_VERSION}"
        )
    if manifest.get("block_size") != block_size:
        raise FabricPayloadError(
            f"peer block_size {manifest.get('block_size')!r} != local "
            f"{block_size} — replicas must share --kv-block-size"
        )
    chunks = manifest.get("chunks") or []
    if not chunks or not leaves or any(
        leaf.shape[0] != len(chunks) for leaf in leaves
    ):
        raise FabricPayloadError("empty or ragged /kv payload")
    ids: list = []
    keys = []
    for chunk in chunks:
        if len(chunk) != block_size:
            raise FabricPayloadError("chunk length != block_size")
        ids.extend(int(t) for t in chunk)
        keys.append(tuple(ids))
    got = chunk_digests(ids, block_size, max_chunks=len(chunks))[-1]
    if got != expected_digest:
        raise FabricPayloadError(
            f"content-key recheck failed: payload tokens digest to "
            f"{got}, fetched {expected_digest}"
        )
    per_block = [
        [leaf[i] for leaf in leaves] for i in range(len(chunks))
    ]
    return keys, per_block


def serve_chain(shadow, digest: str) -> Optional[bytes]:
    """The /kv route's body: the resident chain ending at `digest`, wire-
    encoded, or None (-> 404) when not resident / not a valid digest."""
    if not valid_digest(digest):
        return None
    chain = shadow.chain_for_digest(digest)
    if chain is None:
        return None
    keys, entries = chain
    return encode_chain(shadow.block_size, keys, entries)


class KVFabricClient:
    """One replica's fetching half of the fabric. Deadline'd, metric'd,
    and failure-silent: fetch() returns the verified chain or None."""

    def __init__(self, registry=None, role: str = "mixed",
                 timeout_s: float = 5.0):
        self.role = str(role)
        self.timeout_s = float(timeout_s)
        self.fetches = 0
        self.hits = 0
        self.misses = 0
        self.bytes = 0
        self._m_fetches = self._m_hits = None
        self._m_misses = self._m_bytes = self._m_seconds = None
        if registry is not None:
            self._m_fetches = registry.counter(
                "dli_kv_fabric_fetches_total",
                "cross-replica /kv chain fetches attempted", ("role",),
            ).labels(role=self.role)
            self._m_hits = registry.counter(
                "dli_kv_fabric_hits_total",
                "fabric fetches that returned a verified chain", ("role",),
            ).labels(role=self.role)
            self._m_misses = registry.counter(
                "dli_kv_fabric_misses_total",
                "fabric fetches that fell back to local prefill (404, "
                "dead/wedged peer, failed content-key recheck)", ("role",),
            ).labels(role=self.role)
            self._m_bytes = registry.counter(
                "dli_kv_fabric_bytes_total",
                "wire bytes of verified fabric chains received", ("role",),
            ).labels(role=self.role)
            self._m_seconds = registry.histogram(
                "dli_kv_fabric_fetch_seconds",
                "fabric fetch wall time, failures included",
            ).labels()

    def fetch(self, peer_url: str, digest: str, block_size: int,
              ctx=None, request_id=None, store=None) -> Optional[tuple]:
        """GET {peer}/kv/{digest}, verify, return (keys, per_block_leaves)
        or None. Bounded by timeout_s end to end (a wedged peer costs one
        deadline, then the caller prefills locally).

        Fleet tracing (ISSUE 17): `ctx` (a tracing.SpanContext) rides
        the request as a `traceparent` header so the serving peer's /kv
        span joins the same trace, `request_id` rides as X-Request-Id
        (echoed back by the peer), and `store` (a TraceStore) records
        this side's `fabric.pull` span around the whole fetch —
        context managed, so every early return above closes it."""
        self.fetches += 1
        if self._m_fetches is not None:
            self._m_fetches.inc()
        t0 = time.perf_counter()
        wall0 = time.time()
        ok = False
        with request_id_context(request_id, getattr(ctx, "trace_id", None)):
            try:
                if not valid_digest(digest):
                    raise FabricPayloadError(
                        f"invalid digest {digest[:80]!r}"
                    )
                url = peer_url.rstrip("/") + "/kv/" + digest
                headers = {}
                if ctx is not None:
                    headers["traceparent"] = ctx.header()
                if request_id:
                    headers["X-Request-Id"] = request_id
                req = urllib.request.Request(url, headers=headers)
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as r:
                    data = r.read()
                out = decode_chain(data, block_size, digest)
                ok = True
            except FabricPayloadError as e:
                log.warning("kv_fabric_payload_rejected", peer=peer_url,
                            digest=digest, error=str(e))
                out = None
            except (urllib.error.URLError, urllib.error.HTTPError, OSError,
                    TimeoutError, ValueError) as e:
                # 404 (evicted / never resident), connect refused (peer
                # kill -9'd mid-handoff), socket timeout (wedged peer) —
                # all one outcome: prefill locally
                log.info("kv_fabric_miss", peer=peer_url, digest=digest,
                         error=str(e))
                out = None
            finally:
                if self._m_seconds is not None:
                    self._m_seconds.observe(time.perf_counter() - t0)
                if store is not None and ctx is not None:
                    store.add_span(
                        ctx.trace_id, "fabric.pull", wall0, time.time(),
                        parent_id=ctx.span_id,
                        attrs={
                            "peer": peer_url, "digest": str(digest)[:16],
                            "hit": ok,
                        },
                    )
        if not ok or out is None:
            self.misses += 1
            if self._m_misses is not None:
                self._m_misses.inc()
            return None
        self.hits += 1
        self.bytes += len(data)
        if self._m_hits is not None:
            self._m_hits.inc()
            self._m_bytes.inc(len(data))
        return out

    def stats(self) -> dict:
        return {
            "role": self.role,
            "fetches": self.fetches,
            "hits": self.hits,
            "misses": self.misses,
            "bytes": self.bytes,
            "timeout_s": self.timeout_s,
        }
