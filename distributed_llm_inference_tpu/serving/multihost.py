"""Multi-process serving: one HTTP front door, mirrored SPMD followers.

The reference's deployment shape is N separate serving machines — an
orchestrator Flask plus a hand-started Flask per worker, wired by pasted
ngrok URLs (/root/reference/Worker1.py:248-266, orchestration.py:22-24).
Under multi-controller JAX the equivalent is: every process runs the SAME
engine build (each restoring only its own stage's weights off mmap), and
every compiled program must be launched by every process in the same
order. So serving becomes a mirroring problem, not an RPC problem:

  * process 0 serves HTTP. Before running any engine method that launches
    device programs, it broadcasts the (method, args, kwargs) triple to
    all processes — one fixed-size uint8 collective.
  * processes > 0 run `follower_loop`: receive a triple, invoke the same
    engine method with the same arguments, discard the result, repeat.
    Determinism of the engine surface (tokenizer, bucket planning, key
    derivation from the request seed / per-process counter) guarantees
    both sides issue byte-identical program sequences.
  * a single issue-lock around (broadcast, engine call) on the leader
    pins the collective launch order: no second request can interleave
    its broadcast between another request's broadcast and compute.

Scope: the bare engine surface (generate / generate_batch / score).
`--continuous` and `--queue` are admission layers whose batching depends
on request ARRIVAL TIMING — inherently different per process — and are
rejected at startup for MIRRORED multi-process serving, where every
process must replay the identical launch sequence. That restriction is
specific to this module's mirroring model: the MPMD stage runtime
(serving/stage_runtime.py) is the multi-process deployment that lifts
it, by making arrival timing a controller-only concern — stages receive
an explicit, replayable (request_id, pos, window) stream over the stage
transport, so admission layers batch freely in the one process that
owns timing. Use stage_runtime for pipeline-sharded fleets; this module
remains the SPMD-mirroring path for meshes that fit one program.
"""

from __future__ import annotations

import json
import threading

import jax
import numpy as np

from ..utils.logging import get_logger

log = get_logger("multihost")

# Fixed wire size: the payload collective must have the same shape on
# every process, request content is length-prefixed inside it. 64 KiB
# covers any request the HTTP edge accepts (prompts are bounded by the
# prefill buckets long before this).
_WIRE_BYTES = 64 * 1024

# Engine methods that launch device programs and therefore must be
# mirrored on every process. Everything else (health, stats, tokenizer
# helpers) is host/local-device work the leader answers alone.
MIRRORED_METHODS = ("generate", "generate_batch", "score")

_SHUTDOWN = {"m": "__shutdown__"}


def _broadcast_obj(obj, is_source: bool):
    """Broadcast a JSON-serializable obj from process 0 to all processes.

    One collective of fixed [4 + _WIRE_BYTES] uint8 (4-byte big-endian
    length prefix). Every process must call this the same number of times
    in the same order — the leader's issue-lock guarantees it.
    """
    from jax.experimental import multihost_utils

    buf = np.zeros(4 + _WIRE_BYTES, np.uint8)
    if is_source:
        payload = json.dumps(obj).encode()
        if len(payload) > _WIRE_BYTES:
            raise ValueError(
                f"mirrored request of {len(payload)} bytes exceeds the "
                f"{_WIRE_BYTES}-byte wire buffer"
            )
        buf[:4] = np.frombuffer(
            len(payload).to_bytes(4, "big"), np.uint8
        )
        buf[4 : 4 + len(payload)] = np.frombuffer(payload, np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    n = int.from_bytes(out[:4].tobytes(), "big")
    return json.loads(out[4 : 4 + n].tobytes().decode())


class MirroredEngine:
    """Leader-side proxy: broadcast-then-run for the mirrored methods,
    transparent passthrough for everything else (health, stats, cfg,
    tokenizer, backend — all host-local)."""

    def __init__(self, engine):
        self._engine = engine
        # ONE lock across (broadcast, engine call): the follower issues
        # [bcast_i, programs_i, bcast_i+1, ...] strictly in order, so the
        # leader must too — a second thread slipping its broadcast between
        # another request's broadcast and compute would desynchronize the
        # collective stream and wedge every process.
        self._issue_lock = threading.Lock()

    def _mirrored(self, method, args, kwargs):
        with self._issue_lock:
            _broadcast_obj(
                {"m": method, "a": list(args), "kw": kwargs}, is_source=True
            )
            return getattr(self._engine, method)(*args, **kwargs)

    def generate(self, *args, **kwargs):
        return self._mirrored("generate", args, kwargs)

    def generate_batch(self, *args, **kwargs):
        return self._mirrored("generate_batch", args, kwargs)

    def score(self, *args, **kwargs):
        return self._mirrored("score", args, kwargs)

    def shutdown_followers(self, timeout_s: float = 5.0) -> bool:
        """Release the follower loops (idempotent best-effort: call once,
        right before the leader exits).

        Bounded: a follower that already DIED can never answer the
        collective, and an unguarded broadcast would wedge leader exit
        on it forever. The broadcast (lock acquisition included — a
        stuck mirrored call may hold the issue lock for the same reason)
        runs on a daemon thread the leader abandons past `timeout_s` —
        the same abandonment discipline as engine._with_deadline. Returns
        True when the broadcast completed inside the timeout."""
        done = threading.Event()

        def _bcast():
            try:
                with self._issue_lock:
                    _broadcast_obj(_SHUTDOWN, is_source=True)
            finally:
                done.set()

        t = threading.Thread(
            target=_bcast, daemon=True, name="multihost-shutdown"
        )
        t.start()
        if done.wait(timeout_s):
            return True
        log.warning(
            "shutdown_followers_timeout", timeout_s=timeout_s,
        )
        return False

    def __getattr__(self, name):
        return getattr(self._engine, name)


def follower_loop(engine, process_id: int):
    """Processes > 0: mirror every leader request until shutdown.

    Results are discarded — the POINT is the device program launches,
    which the SPMD mesh needs from every process. Errors that the engine
    surfaces as error envelopes (validation, deadline) return normally on
    both sides; anything raised here is fatal by design (a diverged
    follower cannot safely keep answering collectives).
    """
    while True:
        msg = _broadcast_obj(None, is_source=False)
        if msg["m"] == _SHUTDOWN["m"]:
            return
        if msg["m"] not in MIRRORED_METHODS:
            raise RuntimeError(
                f"follower {process_id} received unknown mirrored method "
                f"{msg['m']!r}"
            )
        getattr(engine, msg["m"])(*msg["a"], **msg["kw"])
