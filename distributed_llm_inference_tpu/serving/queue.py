"""Bounded request queue with ragged-batch coalescing.

Round-1 review: the serving edge had no backpressure — ThreadingHTTPServer
spawns a thread per request and every one of them serializes on the engine
lock, so a burst piles up unboundedly behind a multi-second decode. (The
reference is strictly worse: concurrent /generate requests interleave
worker HTTP calls with NO locking at all, SURVEY.md §5 race note.)

Here concurrent single-prompt requests:

  * enter a BOUNDED queue — when it is full the caller immediately gets an
    `overloaded` envelope (HTTP 429), the standard shed-load answer the
    reference lacks;
  * are COALESCED: the dispatcher grabs every queued request with the same
    sampling parameters (up to max_batch) and runs them as ONE ragged
    left-padded fleet through engine.generate_batch — one prefill + one
    decode loop for the lot instead of N serialized generations. This is
    the first genuinely-beyond-reference serving feature: aggregate
    throughput scales with concurrency because batch rows share each HBM
    weight stream.

Coalescing requires the llama family + a ragged-capable backend and only
groups seedless requests (a per-request seed pins that request to a solo
generation so its determinism contract survives). Anything that cannot
coalesce still flows through the same queue one request at a time, so
backpressure semantics are uniform.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Optional

from ..utils.logging import get_logger
from ..utils.metrics import DEFAULT_SIZE_BUCKETS
from ..utils.retry import overload_retry_after
from ..utils.tracing import Trace

log = get_logger("queue")


class _Pending:
    __slots__ = ("prompt", "kwargs", "done", "result", "enqueued", "is_batch",
                 "trace", "slo", "deadline_at", "trace_ctx")

    def __init__(self, prompt, kwargs: dict, is_batch: bool = False):
        self.prompt = prompt  # str, or list[str] for a client batch
        self.kwargs = kwargs
        self.done = threading.Event()
        self.result: Optional[dict] = None
        self.enqueued = time.time()
        self.is_batch = is_batch
        # end-to-end deadline_ms: absolute expiry. Checked at submit
        # (fail-fast, zero queue time spent) and again at dispatch
        # (_expire); the engine enforces the REMAINING budget in-flight
        # (the kwarg is rewritten at dispatch so queue wait counts).
        dl = kwargs.get("deadline_ms")
        self.deadline_at = (
            self.enqueued + float(dl) / 1e3 if dl is not None else None
        )
        # SLO class (engine/scheduler.py): resolved against the engine's
        # configured classes at submit; drives the per-class depth gauge
        # and the class-local Retry-After on shed — the kwarg itself
        # stays, the engine accepts + echoes it
        self.slo = kwargs.get("slo_class")
        # per-request trace: the dispatcher wait lands in the queue_wait
        # span; solo dispatch hands the SAME trace to the engine so the
        # response's timings cover enqueue -> detokenize contiguously
        self.trace = Trace(kwargs.pop("request_id", None))
        # fleet trace context (serving/server.py sets it): consumed here —
        # engine.generate has no seam for it, and the server's own
        # replica.request span already brackets the queue wait (which
        # lands in this trace's queue_wait timing, hence in the exported
        # stage spans)
        self.trace_ctx = kwargs.pop("trace_ctx", None)

    def coalesce_key(self):
        k = self.kwargs
        # client batches dispatch as their own fleet; seeded requests run
        # solo (their determinism contract is the solo RNG stream); debug
        # requests run solo (top_predictions needs the single-stream
        # prefill logits)
        # logprobs requests run solo too: a coalesced fleet has no
        # per-token logprob buffer, so batching would silently drop the
        # requested data
        if (
            self.is_batch or k.get("seed") is not None or k.get("debug")
            or k.get("logprobs")
            # generate_batch has no logit_bias seam; biased requests solo
            or k.get("logit_bias")
            # a deadline_ms request runs solo: a fleet-wide deadline
            # would fail innocent rows the moment one member's budget
            # expires, and per-row deadlines have no fleet seam
            or k.get("deadline_ms") is not None
            # beam search is its own batched program; runs solo
            or int(k.get("num_beams", 1) or 1) > 1
        ):
            return None
        return (
            k.get("max_tokens"), k.get("temperature"), k.get("top_k"),
            k.get("top_p"), k.get("greedy"), k.get("chat"),
            k.get("min_p", 0.0), k.get("repetition_penalty", 1.0),
            # the OpenAI penalties are fleet-shared scalars like the other
            # sampling knobs: only identical values may share a fleet
            k.get("frequency_penalty", 0.0), k.get("presence_penalty", 0.0),
            # class-pure fleets: the envelope echoes one slo_class per
            # fleet call, so mixed-class coalescing would mislabel rows
            k.get("slo_class"),
            tuple(k.get("stop") or ()),
            # a grammar constraint is fleet-shared (one [S, V] table pair
            # broadcast over the rows), so only IDENTICAL constraints may
            # coalesce — canonical-JSON'd because dicts don't hash
            json.dumps(k["constraint"], sort_keys=True)
            if k.get("constraint") is not None else None,
        )


class BatchingQueue:
    """Bounded queue + coalescing dispatcher in front of an InferenceEngine."""

    def __init__(
        self,
        engine: Any,
        max_queue: int = 32,
        max_batch: int = 8,
        max_wait_ms: float = 5.0,
    ):
        from ..engine.engine import BATCH_BUCKETS

        self.engine = engine
        self.max_queue = int(max_queue)
        # clamp to the largest batch the engine compiles: a bigger fleet
        # would be rejected by generate_batch and silently serialize solo
        self.max_batch = min(int(max_batch), BATCH_BUCKETS[-1])
        if self.max_batch < int(max_batch):
            log.warning(
                "max_batch_clamped", requested=int(max_batch),
                clamped_to=self.max_batch,
            )
        self.max_wait_s = float(max_wait_ms) / 1e3
        self._cv = threading.Condition()
        self._queue: list[_Pending] = []  # guarded-by: _cv
        self._closed = False  # guarded-by: _cv
        self._draining = False  # guarded-by: _cv
        # guarded-by: _cv
        self._busy = False  # dispatcher mid-group (drain must wait for it)
        self.coalesced_batches = 0  # observability: fleets actually formed
        # registry families (engine.metrics — one /metrics scrape covers
        # the queue alongside the engine): depth, shed 429s, dispatcher
        # waits, fleets formed + their row counts
        m = engine.metrics
        self._m_depth = m.gauge(
            "dli_queue_depth", "requests waiting for dispatch", ("queue",)
        ).labels(queue="batching")
        self._m_shed = m.counter(
            "dli_queue_shed_total", "requests shed with 429", ("queue",)
        ).labels(queue="batching")
        self._m_wait = m.histogram(
            "dli_admission_wait_seconds", "enqueue-to-dispatch wait",
            ("queue",),
        ).labels(queue="batching")
        self._m_coalesced = m.counter(
            "dli_coalesced_fleets_total",
            "coalesced fleets that served successfully",
        ).labels()
        self._m_fleet_rows = m.histogram(
            "dli_batch_rows", "rows per batched fleet", ("engine",),
            buckets=DEFAULT_SIZE_BUCKETS,
        ).labels(engine="queue")
        # SLO classes (engine/scheduler.py): the batching queue has no
        # prefill budget to apportion, but classed requests still get the
        # per-class depth gauge and a CLASS-local Retry-After on shed —
        # a deep batch backlog must not tell an interactive client to
        # stay away, and vice versa
        from ..engine.scheduler import parse_slo_classes

        self._slo = parse_slo_classes(engine.engine_cfg)
        self._slo_default = engine.engine_cfg.slo_default_class
        self._m_slo_depth = m.gauge(
            "dli_slo_queue_depth",
            "queued requests per SLO class and tenant",
            ("slo_class", "tenant"),
        )
        self._m_slo_shed = m.counter(
            "dli_slo_shed_total",
            "requests shed with 429 by SLO admission control (class drain "
            "estimate over the TTFT target, or queue full)", ("slo_class",),
        )
        self._m_deadline_exceeded = m.counter(
            "dli_deadline_exceeded_total",
            "requests failed by their end-to-end deadline_ms",
        ).labels()
        self._can_coalesce = (
            getattr(engine.cfg, "arch", None) == "llama"
            and getattr(engine.backend, "supports_ragged", False)
            and self.max_batch > 1
        )
        self._thread = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="batching-queue"
        )
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def submit(self, prompt: str, **kwargs) -> dict:
        """Enqueue one request and block until its envelope is ready.

        Returns an `overloaded` envelope immediately when the queue is
        full — the serving edge maps it to HTTP 429.
        """
        return self._submit(_Pending(prompt, kwargs))

    def submit_batch(self, prompts: list, **kwargs) -> dict:
        """Enqueue a client 'prompts'-list request as one unit, so batched
        traffic shares the same bounded-queue backpressure as singles (it
        dispatches as its own fleet, never coalesced with others)."""
        return self._submit(_Pending(prompts, kwargs, is_batch=True))

    def _note_queue_locked(self):  # guarded-by: _cv
        """Refresh the global + per-SLO-class depth gauges (caller holds
        the lock)."""
        self._m_depth.set(len(self._queue))
        counts: dict = {}
        for p in self._queue:
            counts[p.slo] = counts.get(p.slo, 0) + 1
        for name in self._slo:
            # the batching queue carries no tenant identity; its series
            # report under the anonymous tenant like untagged continuous
            # traffic
            self._m_slo_depth.labels(slo_class=name, tenant="").set(
                counts.get(name, 0)
            )

    def _deadline_env(self, where: str = "") -> dict:
        self._m_deadline_exceeded.inc()
        suffix = f" {where}" if where else ""
        return {
            "error": f"Error: request exceeded its deadline_ms "
            f"budget{suffix}",
            "status": "failed",
            "error_type": "deadline_exceeded",
        }

    def _submit(self, pend: _Pending) -> dict:
        if pend.slo not in self._slo:
            pend.slo = self._slo_default
        if pend.deadline_at is not None and time.time() >= pend.deadline_at:
            # fail-fast: an already-expired request never enters the
            # queue, never reaches the engine (zero prefill spent)
            return self._deadline_env(where="before admission")
        with self._cv:
            if self._closed:
                return {
                    "error": "Error: server shutting down", "status": "failed",
                    "error_type": "overloaded",
                }
            if self._draining:
                # graceful drain: the serving edge maps this to HTTP 503
                # with a Retry-After header (in-flight work still finishes)
                return {
                    "error": "Error: server draining", "status": "failed",
                    "error_type": "draining",
                }
            if len(self._queue) >= self.max_queue:
                log.warning("queue_full", depth=len(self._queue),
                            slo_class=pend.slo)
                self._m_shed.inc()
                self._m_slo_shed.labels(slo_class=pend.slo).inc()
                # the 429 carries a drain-estimate Retry-After hint (the
                # drain path always sent one; overload must too, so
                # client and router backoff stays server-directed) —
                # derived from the shed request's OWN class depth: one
                # second per max_batch-sized dispatch cycle THAT class's
                # backlog needs to clear, never the global queue depth
                class_depth = sum(
                    1 for p in self._queue if p.slo == pend.slo
                )
                return {
                    "error": f"Error: request queue full ({self.max_queue})",
                    "status": "failed",
                    "error_type": "overloaded",
                    "slo_class": pend.slo,
                    "retry_after_s": overload_retry_after(
                        class_depth, self.max_batch
                    ),
                }
            self._queue.append(pend)
            self._note_queue_locked()
            self._cv.notify_all()
        pend.done.wait()
        return pend.result

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Graceful drain: reject NEW submissions (draining envelope →
        HTTP 503 + Retry-After), then wait until the queue is empty and
        the dispatcher is idle, up to deadline_s. Returns True when fully
        drained; the caller's close() fails any stragglers. Idempotent."""
        t0 = time.time()
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        drained = True
        with self._cv:
            while self._queue or self._busy:
                if self._closed:
                    drained = not self._queue and not self._busy
                    break
                left = (
                    None if deadline_s is None
                    else deadline_s - (time.time() - t0)
                )
                if left is not None and left <= 0:
                    drained = False
                    break
                self._cv.wait(
                    timeout=0.1 if left is None else min(left, 0.1)
                )
        self.engine.metrics.histogram(
            "dli_drain_duration_seconds",
            "graceful-drain wall time (SIGTERM / drain())", ("component",),
        ).labels(component="queue").observe(time.time() - t0)
        log.info(
            "queue_drained", ok=drained, seconds=round(time.time() - t0, 3)
        )
        return drained

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
        # fail anything still queued
        with self._cv:
            for p in self._queue:
                p.result = {
                    "error": "Error: server shutting down", "status": "failed",
                    "error_type": "overloaded",
                }
                p.done.set()
            self._queue.clear()
            self._note_queue_locked()

    def depth(self) -> int:
        with self._cv:
            return len(self._queue)

    # -- dispatcher ----------------------------------------------------------
    def _take_group(self) -> list[_Pending]:  # guarded-by: _cv
        """Pop the head request plus every compatible queued request (in
        arrival order) up to max_batch. Caller holds the lock."""
        head = self._queue.pop(0)
        self._note_queue_locked()
        key = head.coalesce_key() if self._can_coalesce else None
        group = [head]
        if key is None:
            return group
        rest = []
        for p in self._queue:
            if len(group) < self.max_batch and p.coalesce_key() == key:
                group.append(p)
            else:
                rest.append(p)
        self._queue[:] = rest
        self._note_queue_locked()
        return group

    def _dispatch_loop(self):
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if self._closed:
                    return
                depth = len(self._queue)
                head_age = time.time() - self._queue[0].enqueued
                head_solo = self._queue[0].coalesce_key() is None
            # brief coalescing window: give a burst's stragglers a chance
            # to arrive before the fleet is cut. The head only ever waits
            # out the REMAINDER of its window — a request that already
            # aged past it behind a running fleet dispatches immediately —
            # and a head that can never coalesce (seeded/debug/client
            # batch) skips the window entirely.
            wait = self.max_wait_s - head_age
            if (
                self._can_coalesce and not head_solo
                and depth < self.max_batch and wait > 0
            ):
                time.sleep(wait)
            with self._cv:
                if not self._queue:
                    continue
                group = self._take_group()
                self._busy = True  # drain() waits for the group to finish
            try:
                group = self._expire(group)
                if group:
                    self._run_group(group)
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _expire(self, group: list[_Pending]) -> list[_Pending]:
        """Fail requests whose QUEUE WAIT already exceeded the engine's
        per-request deadline — --deadline promises a per-request wall
        clock, and under backlog (the only time deadlines matter) the
        wait would otherwise not count against it."""
        deadline = getattr(self.engine.engine_cfg, "request_deadline_s", None)
        now = time.time()
        live = []
        for p in group:
            if p.deadline_at is not None and now >= p.deadline_at:
                # the request's OWN deadline_ms expired while queued:
                # distinct envelope (504 at the edge, never retried)
                p.result = dict(
                    self._deadline_env(where="while queued"),
                    request_id=p.trace.request_id,
                    timings=p.trace.timings(),
                )
                p.done.set()
            elif deadline and now - p.enqueued > deadline:
                p.result = {
                    "error": f"Error: request exceeded the {deadline:g}s "
                    "deadline while queued",
                    "status": "failed",
                    "error_type": "timeout",
                    "request_id": p.trace.request_id,
                    "timings": p.trace.timings(),
                }
                p.done.set()
            else:
                if p.deadline_at is not None:
                    # the engine enforces the REMAINING budget: rewrite
                    # the kwarg so queue wait counts against end-to-end
                    p.kwargs["deadline_ms"] = max(
                        1.0, (p.deadline_at - now) * 1e3
                    )
                live.append(p)
        return live

    def _run_group(self, group: list[_Pending]):
        now = time.time()
        for p in group:
            self._m_wait.observe(now - p.enqueued)
        try:
            if len(group) == 1:
                p = group[0]
                # the engine continues THIS trace: its first checkpoint
                # (lock acquisition) folds the dispatcher wait into the
                # queue_wait span, and the envelope echoes p's request_id
                if p.is_batch:
                    p.result = self.engine.generate_batch(
                        p.prompt, _trace=p.trace, **p.kwargs
                    )
                else:
                    p.result = self.engine.generate(
                        p.prompt, _trace=p.trace, **p.kwargs
                    )
                return
            kwargs = dict(group[0].kwargs)
            kwargs.pop("seed", None)
            kwargs.pop("debug", None)
            # a coalesced greedy fleet already produces the exact tokens a
            # speculative solo run would; the flag just doesn't apply.
            # logprobs=False (the server sets it unconditionally) is
            # likewise not a generate_batch parameter — logprobs=True
            # requests never coalesce (coalesce_key).
            kwargs.pop("speculative", None)
            kwargs.pop("logprobs", None)
            for p in group:
                # dispatcher wait closed out per member; the fleet's own
                # stage spans are copied onto each member below
                p.trace.checkpoint("queue_wait")
            t0 = time.time()
            batch = self.engine.generate_batch(
                [p.prompt for p in group], **kwargs
            )
            elapsed = time.time() - t0
            if batch.get("status") == "success":
                # counted only for fleets that actually served (a failed
                # fleet falls back to solo — counting it would mask a
                # coalescing regression behind a healthy-looking metric)
                self.coalesced_batches += 1
                self._m_coalesced.inc()
                self._m_fleet_rows.observe(len(group))
            if batch.get("status") != "success":
                if batch.get("error_type") in ("timeout", "overloaded"):
                    # capacity failures propagate as-is: retrying N members
                    # solo against a wedged engine would stall the single
                    # dispatcher thread N x deadline and outage the queue
                    for p in group:
                        p.result = dict(
                            batch, request_id=p.trace.request_id,
                            timings=p.trace.timings(),
                        )
                    return
                # request-shaped fleet failure (e.g. one over-long prompt):
                # retry each member SOLO so one bad request cannot fail the
                # innocent ones it happened to coalesce with — solo also
                # reaches paths batching lacks (chunked prefill)
                for p in group:
                    p.result = self.engine.generate(
                        p.prompt, _trace=p.trace, **p.kwargs
                    )
                return
            fleet_spans = {
                k: v for k, v in batch.get("timings", {}).items()
                if k not in ("queue_wait_s", "total_s")
            }
            for p, row in zip(group, batch["results"]):
                n = row["tokens_generated"]
                for k, v in fleet_spans.items():
                    p.trace.add(k[:-2], v)  # strip the "_s" suffix
                p.result = {
                    "prompt": row["prompt"],
                    "response": row["response"],
                    "status": row["status"],
                    **({"stopped": True} if row.get("stopped") else {}),
                    "time_taken": batch["time_taken"],
                    "tokens_generated": n,
                    "prompt_tokens": row.get("prompt_tokens", 0),
                    **({"finish_reason": row["finish_reason"]}
                       if "finish_reason" in row else {}),
                    "tokens_per_sec": f"{(n / elapsed if elapsed > 0 else 0.0):.2f}",
                    "ttft_s": batch["ttft_s"],
                    "backend": batch["backend"],
                    "batched_with": len(group),
                    "request_id": p.trace.request_id,
                    "timings": p.trace.timings(),
                }
        except Exception as e:  # noqa: BLE001 - callers must always unblock
            log.error("dispatch_failed", exc_info=True, error=str(e))
            for p in group:
                if p.result is None:
                    p.result = {"error": f"Error: {e}", "status": "failed"}
        finally:
            for p in group:
                if p.result is None:
                    p.result = {
                        "error": "Error: dispatcher produced no result",
                        "status": "failed",
                    }
                p.done.set()
