"""Bounded per-process span store + cross-process trace assembly.

Each process in the fleet (router, replica server, engine) keeps ONE
`TraceStore`: a thread-safe, LRU-bounded map of trace_id → recorded
spans. Spans are plain dicts — `{"name", "trace_id", "span_id",
"parent_id", "t0", "t1", "attrs", "service"}` with wall-clock second
timestamps — so the store is JSON-dumpable as-is and the router can
assemble a full cross-process trace by concatenating span lists fetched
from every replica's `GET /debug/traces/{trace_id}` (serving/router.py)
without any schema translation.

Span lifecycle discipline: `start_span` / `end_span` form an
acquire/release pair machine-checked by the resource-lifecycle analysis
rule (analysis/rules/lifecycle.py) — every started span must be ended on
all exit paths (try/finally or ownership transfer). Prefer the `span()`
contextmanager, which is safe by construction; use the explicit pair
only where a span must outlive one frame (e.g. the replica request span
closed after streaming completes). Fully-formed spans measured elsewhere
(a finished Trace's stage segments, launch-attribution records) enter
via `add_span`.

Export: `assemble_tree` nests spans by parent_id for the JSON debug
view; `to_chrome_trace` emits Chrome trace-event format (Perfetto-
loadable) with one pid lane per service (router / replica-N / engine
role) declared via `process_name` metadata events and every span a
complete `ph:"X"` event in microseconds.

Strictly host-side and dependency-free, like utils/metrics.py.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.tracing import SpanContext, new_span_id

# Bounds: per-process, tuned so a busy replica holds the last few
# hundred requests' spans in a few MB. Evicting is strictly LRU on
# trace_id — a trace being appended to (or read) is "recently used".
DEFAULT_MAX_TRACES = 256
DEFAULT_MAX_SPANS_PER_TRACE = 512


class TraceStore:
    """Thread-safe bounded span store for one process."""

    def __init__(
        self,
        service: str = "engine",
        max_traces: int = DEFAULT_MAX_TRACES,
        max_spans_per_trace: int = DEFAULT_MAX_SPANS_PER_TRACE,
    ):
        self.service = str(service)
        self.max_traces = int(max_traces)
        self.max_spans_per_trace = int(max_spans_per_trace)
        # trace_id -> deque of finished span dicts (LRU order on the dict)
        self._traces: "collections.OrderedDict[str, collections.deque]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()
        self._dropped = 0  # spans lost to per-trace bound (not eviction)

    # -- recording -----------------------------------------------------------
    def start_span(
        self,
        name: str,
        ctx: SpanContext,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> dict:
        """Open a span under `ctx` (ctx.span_id is the parent). Returns
        the span dict — pass it to `end_span` on EVERY exit path (the
        resource-lifecycle rule enforces this pairing). The open span is
        not visible in the store until ended."""
        return {
            "name": str(name),
            "trace_id": ctx.trace_id,
            "span_id": new_span_id(),
            "parent_id": ctx.span_id,
            "t0": time.time(),
            "t1": None,
            "attrs": dict(attrs) if attrs else {},
            "service": self.service,
        }

    def end_span(self, span: dict, attrs: Optional[Dict[str, Any]] = None):
        """Close `span` and commit it to the store. Idempotent: the first
        call sets t1 and commits; later calls only merge attrs (the store
        holds the same dict object, so they still land) — crash/cleanup
        paths may end defensively without duplicating the span."""
        if attrs:
            span["attrs"].update(attrs)
        if span.get("t1") is None:
            span["t1"] = time.time()
            self._commit(span)

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        ctx: SpanContext,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        """Record a span around a block — ends on all exit paths by
        construction. Yields the open span dict so the block can attach
        attrs (`sp["attrs"]["rows"] = n`)."""
        sp = self.start_span(name, ctx, attrs)
        try:
            yield sp
        except BaseException:
            sp["attrs"]["error"] = True
            raise
        finally:
            self.end_span(sp)

    def add_span(
        self,
        trace_id: str,
        name: str,
        t0: float,
        t1: float,
        parent_id: Optional[str] = None,
        span_id: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
        service: Optional[str] = None,
    ) -> dict:
        """Commit a fully-formed span measured elsewhere (stage segments
        from a finished Trace, launch-attribution records). Returns the
        committed dict (its span_id can parent further spans)."""
        sp = {
            "name": str(name),
            "trace_id": trace_id,
            "span_id": span_id or new_span_id(),
            "parent_id": parent_id,
            "t0": float(t0),
            "t1": float(t1),
            "attrs": dict(attrs) if attrs else {},
            "service": service or self.service,
        }
        self._commit(sp)
        return sp

    def _commit(self, span: dict):
        tid = span["trace_id"]
        with self._lock:
            dq = self._traces.get(tid)
            if dq is None:
                dq = collections.deque(maxlen=self.max_spans_per_trace)
                self._traces[tid] = dq
            if len(dq) == dq.maxlen:
                self._dropped += 1
            dq.append(span)
            self._traces.move_to_end(tid)
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)

    # -- reading -------------------------------------------------------------
    def get(self, trace_id: str) -> List[dict]:
        """All recorded spans for `trace_id` (chronological by record
        order), [] when unknown. Reading refreshes LRU recency — an
        operator inspecting a trace keeps it alive."""
        with self._lock:
            dq = self._traces.get(trace_id)
            if dq is None:
                return []
            self._traces.move_to_end(trace_id)
            return [dict(sp, attrs=dict(sp["attrs"])) for sp in dq]

    def trace_ids(self) -> List[str]:
        """Known trace ids, least- to most-recently used."""
        with self._lock:
            return list(self._traces.keys())

    def stats(self) -> dict:
        with self._lock:
            return {
                "service": self.service,
                "traces": len(self._traces),
                "spans": sum(len(dq) for dq in self._traces.values()),
                "max_traces": self.max_traces,
                "max_spans_per_trace": self.max_spans_per_trace,
                "spans_dropped": self._dropped,
            }


# -- assembly + export --------------------------------------------------------
def assemble_tree(spans: List[dict]) -> List[dict]:
    """Nest a flat span list (possibly concatenated from several
    processes' stores) into root trees: each node is the span dict plus a
    `children` list sorted by start time. Spans whose parent_id is
    unknown locally (the parent lives in a process that was not queried,
    or was evicted) surface as roots — partial traces degrade to a
    forest instead of vanishing."""
    by_id = {sp["span_id"]: dict(sp, children=[]) for sp in spans}
    roots: List[dict] = []
    for node in by_id.values():
        parent = by_id.get(node.get("parent_id"))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    def _sort(nodes):
        nodes.sort(key=lambda n: (n["t0"], n["name"]))
        for n in nodes:
            _sort(n["children"])
    _sort(roots)
    return roots


def span_tree_total(roots: List[dict]) -> float:
    """Wall-clock seconds covered by the trees' root spans (max end −
    min start over roots with both bounds) — the "span sum ≈ end-to-end
    wall time" acceptance check reads this."""
    t0s = [r["t0"] for r in roots if r.get("t0") is not None]
    t1s = [r["t1"] for r in roots if r.get("t1") is not None]
    if not t0s or not t1s:
        return 0.0
    return max(t1s) - min(t0s)


def to_chrome_trace(spans: List[dict]) -> dict:
    """Chrome trace-event JSON (Perfetto-loadable): one pid lane per
    service, named via `process_name` metadata events; every span a
    complete (`ph:"X"`) event with ts/dur in MICROseconds. Unfinished
    spans (t1 None — a crash mid-request) export with dur 0 and an
    `unfinished` arg rather than being dropped."""
    services = sorted({sp.get("service") or "unknown" for sp in spans})
    pid_of = {svc: i + 1 for i, svc in enumerate(services)}
    events: List[dict] = []
    for svc in services:
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": pid_of[svc],
            "tid": 0,
            "args": {"name": svc},
        })
        events.append({
            "name": "process_sort_index",
            "ph": "M",
            "pid": pid_of[svc],
            "tid": 0,
            "args": {"sort_index": pid_of[svc]},
        })
    for sp in sorted(spans, key=lambda s: s["t0"]):
        t1 = sp.get("t1")
        args = dict(sp.get("attrs") or {})
        args["span_id"] = sp["span_id"]
        if sp.get("parent_id"):
            args["parent_id"] = sp["parent_id"]
        if t1 is None:
            args["unfinished"] = True
        events.append({
            "name": sp["name"],
            "cat": sp.get("service") or "unknown",
            "ph": "X",
            "ts": round(sp["t0"] * 1e6, 3),
            "dur": round(max(0.0, (t1 or sp["t0"]) - sp["t0"]) * 1e6, 3),
            "pid": pid_of[sp.get("service") or "unknown"],
            "tid": 1,
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
