"""Model registry: named presets for the BASELINE.json configs.

Replaces the reference's single hardcoded MODEL_NAME
(/root/reference/orchestration.py:20). Architecture hyperparameters are
pinned here so the framework runs fully offline (random-init or converted
weights); when a HF checkpoint is available, models/convert.py produces the
params and the converted config overrides these.
"""

from __future__ import annotations

from ..config import ModelConfig

_REGISTRY: dict[str, ModelConfig] = {}


def register(cfg: ModelConfig) -> ModelConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


def get_model_config(name: str, **overrides) -> ModelConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model '{name}'; known: {sorted(_REGISTRY)}")
    cfg = _REGISTRY[name]
    return cfg.replace(**overrides) if overrides else cfg


def list_models() -> list[str]:
    return sorted(_REGISTRY)


# --- Llama family ----------------------------------------------------------
register(ModelConfig(
    name="tinyllama-1.1b", arch="llama", vocab_size=32000, dim=2048,
    n_layers=22, n_heads=32, n_kv_heads=4, ffn_dim=5632, max_seq_len=2048,
    rope_theta=10000.0, eos_token_id=2, bos_token_id=1,
))
register(ModelConfig(
    name="llama2-7b", arch="llama", vocab_size=32000, dim=4096,
    n_layers=32, n_heads=32, n_kv_heads=32, ffn_dim=11008, max_seq_len=4096,
    rope_theta=10000.0, eos_token_id=2, bos_token_id=1,
))
register(ModelConfig(
    name="llama2-13b", arch="llama", vocab_size=32000, dim=5120,
    n_layers=40, n_heads=40, n_kv_heads=40, ffn_dim=13824, max_seq_len=4096,
    rope_theta=10000.0, eos_token_id=2, bos_token_id=1,
))
register(ModelConfig(
    name="llama3-8b", arch="llama", vocab_size=128256, dim=4096,
    n_layers=32, n_heads=32, n_kv_heads=8, ffn_dim=14336, max_seq_len=8192,
    rope_theta=500000.0, eos_token_id=128001, bos_token_id=128000,
))
# Llama-3.1/3.2: "llama3" rope_scaling stretches the 8192-token training
# context to the checkpoints' 131072 max positions; the engine's
# EngineConfig.max_seq_len still bounds the actual KV-cache allocation.
register(ModelConfig(
    name="llama3.1-8b", arch="llama", vocab_size=128256, dim=4096,
    n_layers=32, n_heads=32, n_kv_heads=8, ffn_dim=14336, max_seq_len=131072,
    rope_theta=500000.0, rope_scaling="llama3", rope_scaling_factor=8.0,
    eos_token_id=128001, bos_token_id=128000,
))
# Llama-3.1-70B: the BASELINE-class large config for pp=8/tp meshes.
# Llama-3.3-70B is the identical architecture with newer instruct data —
# derived by replace(name=...) so the equivalence holds by construction.
_l31_70b = register(ModelConfig(
    name="llama3.1-70b", arch="llama", vocab_size=128256, dim=8192,
    n_layers=80, n_heads=64, n_kv_heads=8, ffn_dim=28672, max_seq_len=131072,
    rope_theta=500000.0, rope_scaling="llama3", rope_scaling_factor=8.0,
    eos_token_id=128001, bos_token_id=128000,
))
register(_l31_70b.replace(name="llama3.3-70b"))
register(ModelConfig(
    name="llama3.2-1b", arch="llama", vocab_size=128256, dim=2048,
    n_layers=16, n_heads=32, n_kv_heads=8, ffn_dim=8192, max_seq_len=131072,
    rope_theta=500000.0, rope_scaling="llama3", rope_scaling_factor=32.0,
    tie_embeddings=True, eos_token_id=128001, bos_token_id=128000,
))
register(ModelConfig(
    name="llama3.2-3b", arch="llama", vocab_size=128256, dim=3072,
    n_layers=28, n_heads=24, n_kv_heads=8, ffn_dim=8192, max_seq_len=131072,
    rope_theta=500000.0, rope_scaling="llama3", rope_scaling_factor=32.0,
    tie_embeddings=True, eos_token_id=128001, bos_token_id=128000,
))

# --- Mistral family (llama arch + sliding-window attention) ---------------
register(ModelConfig(
    name="mistral-7b", arch="llama", vocab_size=32000, dim=4096,
    n_layers=32, n_heads=32, n_kv_heads=8, ffn_dim=14336, max_seq_len=8192,
    rope_theta=10000.0, attn_window=4096, eos_token_id=2, bos_token_id=1,
))
register(ModelConfig(
    name="mistral-7b-v0.2", arch="llama", vocab_size=32000, dim=4096,
    n_layers=32, n_heads=32, n_kv_heads=8, ffn_dim=14336, max_seq_len=32768,
    rope_theta=1000000.0, eos_token_id=2, bos_token_id=1,
))

# --- Mixtral family (llama arch + sparse MoE FFN) -------------------------
register(ModelConfig(
    name="mixtral-8x7b", arch="llama", vocab_size=32000, dim=4096,
    n_layers=32, n_heads=32, n_kv_heads=8, ffn_dim=14336, max_seq_len=32768,
    rope_theta=1000000.0, n_experts=8, n_experts_per_tok=2,
    eos_token_id=2, bos_token_id=1,
))

# --- Qwen2 family (llama arch + q/k/v projection biases) ------------------
_qwen2_7b = register(ModelConfig(
    name="qwen2-7b", arch="llama", vocab_size=152064, dim=3584,
    n_layers=28, n_heads=28, n_kv_heads=4, ffn_dim=18944, max_seq_len=32768,
    norm_eps=1e-6, rope_theta=1000000.0, attn_qkv_bias=True,
    eos_token_id=151645, bos_token_id=151643, pad_token_id=151643,
))
# Qwen2.5-7B: the Qwen2-7B architecture unchanged (same dims, GQA,
# qkv-bias, 1e6 theta) with refreshed training — derived, not retyped.
register(_qwen2_7b.replace(name="qwen2.5-7b"))
register(ModelConfig(
    name="qwen2-0.5b", arch="llama", vocab_size=151936, dim=896,
    n_layers=24, n_heads=14, n_kv_heads=2, ffn_dim=4864, max_seq_len=32768,
    norm_eps=1e-6, rope_theta=1000000.0, attn_qkv_bias=True,
    tie_embeddings=True,
    eos_token_id=151645, bos_token_id=151643, pad_token_id=151643,
))

# --- Qwen3 (llama arch + per-head q/k RMSNorm, explicit head_dim, no
# qkv biases) — HF transformers models/qwen3 ---
register(ModelConfig(
    name="qwen3-0.6b", arch="llama", vocab_size=151936, dim=1024,
    n_layers=28, n_heads=16, n_kv_heads=8, ffn_dim=3072, max_seq_len=40960,
    norm_eps=1e-6, rope_theta=1000000.0, head_dim_override=128,
    use_qk_norm=True, tie_embeddings=True,
    eos_token_id=151645, bos_token_id=151643, pad_token_id=151643,
))
register(ModelConfig(
    name="qwen3-30b-a3b", arch="llama", vocab_size=151936, dim=2048,
    n_layers=48, n_heads=32, n_kv_heads=4, ffn_dim=768, max_seq_len=40960,
    norm_eps=1e-6, rope_theta=1000000.0, head_dim_override=128,
    use_qk_norm=True, n_experts=128, n_experts_per_tok=8,
    moe_renormalize=True,
    eos_token_id=151645, bos_token_id=151643, pad_token_id=151643,
))
register(ModelConfig(
    name="qwen3-8b", arch="llama", vocab_size=151936, dim=4096,
    n_layers=36, n_heads=32, n_kv_heads=8, ffn_dim=12288, max_seq_len=40960,
    norm_eps=1e-6, rope_theta=1000000.0, head_dim_override=128,
    use_qk_norm=True,
    eos_token_id=151645, bos_token_id=151643, pad_token_id=151643,
))

# --- OLMo-2 (post-norm residuals, whole-projection qk-norm) ---
register(ModelConfig(
    name="olmo2-7b", arch="llama", vocab_size=100352, dim=4096,
    n_layers=32, n_heads=32, n_kv_heads=32, ffn_dim=11008,
    max_seq_len=4096, norm_eps=1e-6, rope_theta=500000.0,
    pre_norms=False, post_norms=True, use_qk_norm=True, qk_norm_dim="proj",
    eos_token_id=100257, bos_token_id=100257, pad_token_id=100277,
))

# --- Gemma-3 (gemma-2 bones minus softcaps, plus unit-offset qk-norm,
# 5-sliding:1-full layer pattern, dual local/global RoPE) ---
register(ModelConfig(
    name="gemma3-1b", arch="llama", vocab_size=262144, dim=1152,
    n_layers=26, n_heads=4, n_kv_heads=1, ffn_dim=6912, max_seq_len=32768,
    norm_eps=1e-6, rope_theta=1000000.0, rope_local_theta=10000.0,
    head_dim_override=256, norm_unit_offset=True, act="gelu_tanh",
    embed_scale=True, post_norms=True, use_qk_norm=True,
    query_scale_override=256.0, attn_window=512,
    attn_window_layer_types=tuple(
        1 if (i % 6) != 5 else 0 for i in range(26)
    ),
    tie_embeddings=True, chat_template="gemma",
    eos_token_id=1, stop_token_ids=(106,),  # <end_of_turn>
    bos_token_id=2, pad_token_id=0,
))

# --- Gemma family (llama arch + unit-offset norms / GeGLU / embed scale) --
register(ModelConfig(
    name="gemma-2b", arch="llama", vocab_size=256000, dim=2048,
    n_layers=18, n_heads=8, n_kv_heads=1, ffn_dim=16384, max_seq_len=8192,
    norm_eps=1e-6, rope_theta=10000.0, head_dim_override=256,
    norm_unit_offset=True, act="gelu_tanh", embed_scale=True,
    tie_embeddings=True, chat_template="gemma",
    eos_token_id=1, stop_token_ids=(107,),  # <end_of_turn> (gemma-it)
    bos_token_id=2, pad_token_id=0,
))
register(ModelConfig(
    name="gemma-7b", arch="llama", vocab_size=256000, dim=3072,
    n_layers=28, n_heads=16, n_kv_heads=16, ffn_dim=24576, max_seq_len=8192,
    norm_eps=1e-6, rope_theta=10000.0, head_dim_override=256,
    norm_unit_offset=True, act="gelu_tanh", embed_scale=True,
    tie_embeddings=True, chat_template="gemma",
    eos_token_id=1, stop_token_ids=(107,),  # <end_of_turn> (gemma-it)
    bos_token_id=2, pad_token_id=0,
))
# Gemma-2: sandwich norms, logit softcaps, alternating sliding window
register(ModelConfig(
    name="gemma2-2b", arch="llama", vocab_size=256000, dim=2304,
    n_layers=26, n_heads=8, n_kv_heads=4, ffn_dim=9216, max_seq_len=8192,
    norm_eps=1e-6, rope_theta=10000.0, head_dim_override=256,
    norm_unit_offset=True, act="gelu_tanh", embed_scale=True,
    post_norms=True, attn_softcap=50.0, final_softcap=30.0,
    query_scale_override=256.0, attn_window=4096, attn_window_pattern="even",
    tie_embeddings=True, chat_template="gemma",
    eos_token_id=1, stop_token_ids=(107,),  # <end_of_turn> (gemma-it)
    bos_token_id=2, pad_token_id=0,
))
register(ModelConfig(
    name="gemma2-9b", arch="llama", vocab_size=256000, dim=3584,
    n_layers=42, n_heads=16, n_kv_heads=8, ffn_dim=14336, max_seq_len=8192,
    norm_eps=1e-6, rope_theta=10000.0, head_dim_override=256,
    norm_unit_offset=True, act="gelu_tanh", embed_scale=True,
    post_norms=True, attn_softcap=50.0, final_softcap=30.0,
    query_scale_override=256.0, attn_window=4096, attn_window_pattern="even",
    tie_embeddings=True, chat_template="gemma",
    eos_token_id=1, stop_token_ids=(107,),  # <end_of_turn> (gemma-it)
    bos_token_id=2, pad_token_id=0,
))

# --- Phi-3 family (llama arch; HF fuses qkv / gate_up, split at convert) --
register(ModelConfig(
    name="phi3-mini-4k", arch="llama", vocab_size=32064, dim=3072,
    n_layers=32, n_heads=32, n_kv_heads=32, ffn_dim=8192, max_seq_len=4096,
    norm_eps=1e-5, rope_theta=10000.0, attn_window=2047,
    chat_template="phi3",
    eos_token_id=32000, stop_token_ids=(32007,),  # <|endoftext|>, <|end|>
    bos_token_id=1, pad_token_id=32000,
))

# --- GPT-2 family ----------------------------------------------------------
register(ModelConfig(
    name="gpt2-small", arch="gpt2", vocab_size=50257, dim=768,
    n_layers=12, n_heads=12, n_kv_heads=12, ffn_dim=3072, max_seq_len=1024,
    norm_eps=1e-5, tie_embeddings=True, use_learned_pos=True,
    eos_token_id=50256, bos_token_id=50256, pad_token_id=50256,
))
register(ModelConfig(
    name="gpt2-medium", arch="gpt2", vocab_size=50257, dim=1024,
    n_layers=24, n_heads=16, n_kv_heads=16, ffn_dim=4096, max_seq_len=1024,
    norm_eps=1e-5, tie_embeddings=True, use_learned_pos=True,
    eos_token_id=50256, bos_token_id=50256, pad_token_id=50256,
))

# --- tiny test configs (CI-sized) -----------------------------------------
register(ModelConfig(
    name="test-llama-tiny", arch="llama", vocab_size=256, dim=64,
    n_layers=4, n_heads=4, n_kv_heads=2, ffn_dim=128, max_seq_len=128,
    eos_token_id=2, bos_token_id=1,
))
register(ModelConfig(
    name="test-qwen3-tiny", arch="llama", vocab_size=256, dim=64,
    n_layers=4, n_heads=4, n_kv_heads=2, ffn_dim=128, max_seq_len=128,
    norm_eps=1e-6, head_dim_override=24, use_qk_norm=True,
    tie_embeddings=True, eos_token_id=2, bos_token_id=1,
))
register(ModelConfig(
    name="test-olmo2-tiny", arch="llama", vocab_size=256, dim=64,
    n_layers=4, n_heads=4, n_kv_heads=4, ffn_dim=128, max_seq_len=128,
    norm_eps=1e-6, rope_theta=500000.0,
    pre_norms=False, post_norms=True, use_qk_norm=True, qk_norm_dim="proj",
    eos_token_id=2, bos_token_id=1,
))
register(ModelConfig(
    name="test-gemma3-tiny", arch="llama", vocab_size=256, dim=64,
    n_layers=6, n_heads=4, n_kv_heads=2, ffn_dim=128, max_seq_len=128,
    norm_eps=1e-6, rope_theta=1000000.0, rope_local_theta=10000.0,
    head_dim_override=24, norm_unit_offset=True, act="gelu_tanh",
    embed_scale=True, post_norms=True, use_qk_norm=True,
    query_scale_override=24.0, attn_window=32,
    attn_window_layer_types=(1, 1, 1, 1, 1, 0),
    tie_embeddings=True, chat_template="gemma",
    eos_token_id=1, bos_token_id=2, pad_token_id=0,
))
register(ModelConfig(
    name="test-moe-tiny", arch="llama", vocab_size=256, dim=64,
    n_layers=4, n_heads=4, n_kv_heads=2, ffn_dim=96, max_seq_len=128,
    n_experts=4, n_experts_per_tok=2,
    eos_token_id=2, bos_token_id=1,
))
register(ModelConfig(
    name="test-gemma2-tiny", arch="llama", vocab_size=256, dim=64,
    n_layers=4, n_heads=4, n_kv_heads=2, ffn_dim=128, max_seq_len=128,
    norm_eps=1e-6, head_dim_override=24, norm_unit_offset=True,
    act="gelu_tanh", embed_scale=True, post_norms=True,
    attn_softcap=50.0, final_softcap=30.0, query_scale_override=24.0,
    attn_window=32, attn_window_pattern="even", tie_embeddings=True,
    chat_template="gemma", eos_token_id=1, bos_token_id=2, pad_token_id=0,
))
register(ModelConfig(
    name="test-gpt2-tiny", arch="gpt2", vocab_size=256, dim=64,
    n_layers=4, n_heads=4, n_kv_heads=4, ffn_dim=256, max_seq_len=128,
    tie_embeddings=True, use_learned_pos=True,
    eos_token_id=250, bos_token_id=250, pad_token_id=250,
))
