"""HuggingFace checkpoint -> JAX params converter.

Replaces the reference's L0 loading/partitioning, which downloads the *full*
torch model on every process and slices `nn.ModuleList`s
(/root/reference/orchestration.py:38-53, Worker1.py:60-77 — keeping the whole
model around just for rotary access). Here a HF state dict (torch tensors or
safetensors files on disk) is converted once into the stacked-layer pytree of
models/llama.py / models/gpt2.py; pipeline stages then slice the stacked
layer axis, so a stage only ever materializes its own shard.

Two entry paths:
  * `params_from_hf_model(model)` — an in-memory transformers model
    (tests build tiny-random HF models from configs, no hub access);
  * `load_hf_checkpoint(dir)` — a saved HF checkpoint directory
    (`config.json` + `model.safetensors` or a sharded
    `model.safetensors.index.json`), read with a hand-rolled zero-copy
    mmap safetensors parser — no torch model is ever instantiated, unlike
    the reference which materializes the full torch module on every
    process just to slice it (/root/reference/Worker1.py:60-75).

CLI (conversion to the local checkpoint store, models/checkpoint.py):
  python -m distributed_llm_inference_tpu.models.convert \
      --in <hf_checkpoint_dir> --out <ckpt_dir> [--dtype bfloat16]
"""

from __future__ import annotations

import glob
import json
import mmap
import os
from typing import Any, Mapping

import numpy as np
import jax.numpy as jnp

from ..config import ModelConfig


def _np(t) -> np.ndarray:
    """torch tensor / np array -> float32 numpy (converted to model dtype at
    the end, matching HF's fp32 master weights for small models)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, dtype=np.float32)


def config_from_hf(hf_cfg: Any, name: str = "converted", dtype: str = "float32") -> ModelConfig:
    """Map a transformers LlamaConfig/GPT2Config/Qwen2Config to our ModelConfig."""
    mt = getattr(hf_cfg, "model_type", "llama")
    if mt == "gpt2":
        return ModelConfig(
            name=name,
            arch="gpt2",
            vocab_size=hf_cfg.vocab_size,
            dim=hf_cfg.n_embd,
            n_layers=hf_cfg.n_layer,
            n_heads=hf_cfg.n_head,
            n_kv_heads=hf_cfg.n_head,
            ffn_dim=hf_cfg.n_inner if hf_cfg.n_inner is not None else 4 * hf_cfg.n_embd,
            max_seq_len=hf_cfg.n_positions,
            norm_eps=hf_cfg.layer_norm_epsilon,
            tie_embeddings=True,
            use_learned_pos=True,
            dtype=dtype,
            eos_token_id=hf_cfg.eos_token_id if hf_cfg.eos_token_id is not None else 50256,
            bos_token_id=hf_cfg.bos_token_id if hf_cfg.bos_token_id is not None else 50256,
            pad_token_id=hf_cfg.eos_token_id if hf_cfg.eos_token_id is not None else 50256,
        )
    # Qwen2 carries a sliding_window value but gates it off by default
    window = getattr(hf_cfg, "sliding_window", None)
    if mt == "qwen2" and not getattr(hf_cfg, "use_sliding_window", False):
        window = None
    # Gemma / Gemma-2 (llama-family variants): unit-offset RMSNorm, GeGLU,
    # sqrt(dim)-scaled embeddings, explicit head_dim, tied embeddings;
    # Gemma-2 adds sandwich norms, logit softcaps, query_pre_attn_scalar,
    # and sliding window on even-indexed layers only.
    gemma_kw = {}
    if mt in ("gemma", "gemma2"):
        gemma_kw = dict(
            norm_unit_offset=True,
            act="gelu_tanh",
            embed_scale=True,
            head_dim_override=getattr(hf_cfg, "head_dim", None),
            chat_template="gemma",
        )
        if mt == "gemma2":
            gemma_kw.update(
                post_norms=True,
                attn_softcap=getattr(hf_cfg, "attn_logit_softcapping", None),
                final_softcap=getattr(hf_cfg, "final_logit_softcapping", None),
                query_scale_override=getattr(
                    hf_cfg, "query_pre_attn_scalar", None
                ),
                attn_window_pattern="even",
            )
        else:
            window = None  # gemma-1 is full-causal everywhere
    elif mt == "phi3":
        # llama semantics with fused projections (split at load time) and
        # the <|user|>/<|assistant|>/<|end|> chat format
        gemma_kw = dict(chat_template="phi3")
    elif mt == "qwen3":
        # Qwen3: per-head q/k RMSNorm before RoPE, explicit head_dim
        # (often != dim/n_heads), NO qkv biases (dropped from Qwen2)
        gemma_kw = dict(
            use_qk_norm=True,
            head_dim_override=getattr(hf_cfg, "head_dim", None),
        )
    elif mt in ("gemma3_text", "gemma3"):
        if mt == "gemma3" or not hasattr(hf_cfg, "num_hidden_layers"):
            raise ValueError(
                "multimodal gemma3 checkpoints are not supported; convert "
                "the text model (model_type gemma3_text)"
            )
        # Gemma-3 text: gemma-2 bones (unit norms, GeGLU, embed scale,
        # sandwich norms, query scale) MINUS softcaps, PLUS unit-offset
        # qk-norm, an explicit 5-sliding:1-full layer pattern, and dual
        # RoPE (local theta on sliding layers; optional linear scaling on
        # the global table)
        raw_types = tuple(getattr(hf_cfg, "layer_types", ()) or ())
        unknown_types = set(raw_types) - {
            "sliding_attention", "full_attention"
        }
        if unknown_types:
            raise ValueError(
                f"gemma3 layer_types has unsupported entries "
                f"{sorted(unknown_types)} — converting would silently "
                f"treat them as full attention"
            )
        layer_types = tuple(
            1 if t == "sliding_attention" else 0 for t in raw_types
        ) or None
        if layer_types is None:
            # released gemma-3 config.json files carry the pattern as
            # sliding_window_pattern=p (every p-th layer full) instead of
            # an explicit layer_types list; Gemma3TextConfig derives one
            # in __init__ but the raw-JSON checkpoint path does not
            p_every = getattr(hf_cfg, "sliding_window_pattern", None)
            if p_every:
                layer_types = tuple(
                    1 if (i + 1) % int(p_every) else 0
                    for i in range(hf_cfg.num_hidden_layers)
                )
        rs = getattr(hf_cfg, "rope_scaling", None)
        g3_rope = {}
        if isinstance(rs, dict) and rs:
            if rs.get("rope_type", rs.get("type")) != "linear":
                raise ValueError(
                    f"gemma3 rope_scaling {rs!r} unsupported (linear only)"
                )
            g3_rope = dict(
                rope_scaling="linear",
                rope_scaling_factor=float(rs.get("factor", 8.0)),
            )
        gemma_kw = dict(
            norm_unit_offset=True,
            act="gelu_tanh",
            embed_scale=True,
            post_norms=True,
            use_qk_norm=True,
            head_dim_override=getattr(hf_cfg, "head_dim", None),
            query_scale_override=getattr(
                hf_cfg, "query_pre_attn_scalar", None
            ),
            attn_window_layer_types=layer_types,
            rope_local_theta=getattr(hf_cfg, "rope_local_base_freq", None),
            chat_template="gemma",
            **g3_rope,
        )
    elif mt == "granite":
        # IBM Granite: llama structure + four scalar multipliers
        gemma_kw = dict(
            embed_multiplier=float(getattr(hf_cfg, "embedding_multiplier", 1.0)),
            residual_multiplier=float(getattr(hf_cfg, "residual_multiplier", 1.0)),
            attn_scale_override=float(getattr(hf_cfg, "attention_multiplier", 1.0)),
            logits_divider=float(getattr(hf_cfg, "logits_scaling", 1.0)),
        )
    elif mt == "olmo2":
        # OLMo-2: NO pre-sublayer norms (the residual adds
        # norm(sublayer(x))), RMSNorm over the WHOLE q/k projection
        gemma_kw = dict(
            pre_norms=False,
            post_norms=True,
            use_qk_norm=True,
            qk_norm_dim="proj",
        )
    elif mt == "qwen3_moe":
        # Qwen3-MoE: qwen3 attention + a Mixtral-shaped expert bank with
        # its own intermediate size and an optional top-k renormalization
        if getattr(hf_cfg, "mlp_only_layers", None) or getattr(
            hf_cfg, "decoder_sparse_step", 1
        ) != 1:
            raise ValueError(
                "qwen3_moe checkpoints with dense layers (mlp_only_layers "
                "/ decoder_sparse_step != 1) are not supported: the "
                "stacked-layer scan assumes a uniform layer shape"
            )
        gemma_kw = dict(
            use_qk_norm=True,
            head_dim_override=getattr(hf_cfg, "head_dim", None),
            moe_renormalize=bool(getattr(hf_cfg, "norm_topk_prob", False)),
        )
    # Phi-3 instruct ends its turn with <|end|> (32007), but config.json
    # only carries the scalar eos 32000 (the extra stops live in
    # generation_config.json, which a weights-only conversion never sees) —
    # without it generation sails past end-of-turn into hallucinated
    # follow-on turns. Guarded by vocab size so tiny test configs are
    # unaffected.
    extra_stops = tuple(_eos_list(hf_cfg)[1:])
    if mt == "phi3" and hf_cfg.vocab_size > 32007 and 32007 not in extra_stops:
        extra_stops += (32007,)
    # Llama-3.1/3.2 "llama3" rope_scaling: affects frequencies at every
    # position, so silently ignoring it would convert a checkpoint into one
    # that produces wrong logits everywhere. Unsupported types fail loudly.
    rs = getattr(hf_cfg, "rope_scaling", None) or {}
    rs_type = rs.get("rope_type", rs.get("type")) if isinstance(rs, dict) else None
    rope_kw = {}
    if mt in ("gemma3_text", "gemma3"):
        rs_type = None  # gemma3 parsed its (linear) scaling above
    if rs_type in (None, "default"):
        pass
    elif rs_type == "llama3":
        rope_kw = dict(
            rope_scaling="llama3",
            rope_scaling_factor=float(rs.get("factor", 8.0)),
            rope_low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
            rope_high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
            rope_original_max_len=int(
                rs.get("original_max_position_embeddings", 8192)
            ),
        )
    else:
        raise ValueError(
            f"unsupported rope_scaling type {rs_type!r} (supported: llama3)"
        )
    # expert count: Mixtral names it num_local_experts, Qwen3-MoE
    # num_experts; experts may use their own intermediate size
    n_experts = (
        getattr(hf_cfg, "num_local_experts", None)
        or (getattr(hf_cfg, "num_experts", None) if mt == "qwen3_moe" else None)
        or 0
    )
    ffn_dim = hf_cfg.intermediate_size
    if mt == "qwen3_moe":
        ffn_dim = hf_cfg.moe_intermediate_size
    return ModelConfig(
        name=name,
        arch="llama",
        n_experts=n_experts,
        n_experts_per_tok=getattr(hf_cfg, "num_experts_per_tok", None) or 2,
        vocab_size=hf_cfg.vocab_size,
        dim=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=getattr(hf_cfg, "num_key_value_heads", hf_cfg.num_attention_heads),
        ffn_dim=ffn_dim,
        max_seq_len=hf_cfg.max_position_embeddings,
        norm_eps=hf_cfg.rms_norm_eps,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        **rope_kw,
        # Mistral-style sliding window (HF: None/absent = full causal)
        attn_window=window,
        **gemma_kw,
        # Qwen2-style q/k/v biases: Qwen2 has them unconditionally; Llama
        # exposes the optional `attention_bias` flag
        attn_qkv_bias=bool(getattr(hf_cfg, "attention_bias", False)) or mt == "qwen2",
        tie_embeddings=getattr(hf_cfg, "tie_word_embeddings", False),
        dtype=dtype,
        # HF eos_token_id may be a LIST (Llama-3.1's [128001,128008,128009],
        # gemma-it's [1,107]): the first is the primary eos, the rest become
        # extra stop tokens so chat turns actually terminate
        eos_token_id=_eos_list(hf_cfg)[0],
        stop_token_ids=extra_stops,
        bos_token_id=hf_cfg.bos_token_id if hf_cfg.bos_token_id is not None else 1,
        pad_token_id=hf_cfg.pad_token_id if hf_cfg.pad_token_id is not None else 0,
    )


def _eos_list(hf_cfg) -> list:
    e = hf_cfg.eos_token_id
    if e is None:
        return [2]
    if isinstance(e, (list, tuple)):
        return list(e) if e else [2]
    return [e]


def llama_params_from_state_dict(sd: Mapping[str, Any], cfg: ModelConfig) -> dict:
    """Convert a HF Llama-family `state_dict()` into the stacked pytree.

    torch Linear stores weight as [out, in]; our matmuls are x @ W with
    W [in, out], so every projection is transposed once here.
    """
    dt = cfg.jnp_dtype
    L = cfg.n_layers
    p = lambda k: _np(sd[k])

    def stack(fmt: str, transpose: bool) -> jnp.ndarray:
        mats = [p(fmt.format(i)) for i in range(L)]
        arr = np.stack([m.T if transpose else m for m in mats], axis=0)
        return jnp.asarray(arr, dtype=dt)

    # Phi-3 fuses q/k/v into qkv_proj [(H+2KV)*Dh, D] and gate/up into
    # gate_up_proj [2F, D]; split them into the canonical stacked leaves so
    # every downstream consumer (tp sharding, quant, pipeline slicing) sees
    # one layout.
    fused_qkv = "model.layers.0.self_attn.qkv_proj.weight" in sd
    fused_gate_up = "model.layers.0.mlp.gate_up_proj.weight" in sd
    H, KV, Dh, F = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.ffn_dim

    def stack_rows(fmt: str, lo: int, hi: int) -> jnp.ndarray:
        """Stack rows [lo:hi) of a fused [out, in] projection, transposed."""
        mats = [p(fmt.format(i))[lo:hi].T for i in range(L)]
        return jnp.asarray(np.stack(mats, axis=0), dtype=dt)

    params = {
        "embed": jnp.asarray(p("model.embed_tokens.weight"), dtype=dt),
        "layers": {
            "wo": stack("model.layers.{}.self_attn.o_proj.weight", True),
        },
        "final_norm": jnp.asarray(p("model.norm.weight"), dtype=dt),
    }
    if cfg.pre_norms:
        params["layers"]["attn_norm"] = stack(
            "model.layers.{}.input_layernorm.weight", False
        )
        # Gemma-2 renames the MLP pre-norm: post_attention_layernorm
        # becomes the ATTENTION post-norm and pre_feedforward_layernorm
        # is the MLP pre-norm (HF Gemma2DecoderLayer)
        params["layers"]["mlp_norm"] = stack(
            "model.layers.{}.pre_feedforward_layernorm.weight"
            if cfg.post_norms
            else "model.layers.{}.post_attention_layernorm.weight",
            False,
        )
    if fused_qkv:
        qkv = "model.layers.{}.self_attn.qkv_proj.weight"
        params["layers"]["wq"] = stack_rows(qkv, 0, H * Dh)
        params["layers"]["wk"] = stack_rows(qkv, H * Dh, (H + KV) * Dh)
        params["layers"]["wv"] = stack_rows(qkv, (H + KV) * Dh, (H + 2 * KV) * Dh)
    else:
        params["layers"]["wq"] = stack("model.layers.{}.self_attn.q_proj.weight", True)
        params["layers"]["wk"] = stack("model.layers.{}.self_attn.k_proj.weight", True)
        params["layers"]["wv"] = stack("model.layers.{}.self_attn.v_proj.weight", True)
    if cfg.post_norms:
        params["layers"]["attn_post_norm"] = stack(
            "model.layers.{}.post_attention_layernorm.weight", False
        )
        params["layers"]["mlp_post_norm"] = stack(
            "model.layers.{}.post_feedforward_layernorm.weight", False
        )
    from .llama import make_window_flags

    wf = make_window_flags(cfg)
    if wf is not None:
        params["layers"]["window_flag"] = wf
    if cfg.n_experts:
        # Sparse-MoE expert bank + router. Two namings for the same
        # structure: Mixtral (block_sparse_moe, w1=gate/w3=up/w2=down) and
        # Qwen3-MoE (mlp.experts.E.gate_proj/up_proj/down_proj, mlp.gate)
        if "model.layers.0.block_sparse_moe.gate.weight" in sd:
            moe_pref = "model.layers.{}.block_sparse_moe"
            names = {"gate": "w1", "up": "w3", "down": "w2"}
        else:
            moe_pref = "model.layers.{}.mlp"
            names = {"gate": "gate_proj", "up": "up_proj", "down": "down_proj"}

        def stack_experts(role: str) -> jnp.ndarray:
            w_name = names[role]
            mats = [
                np.stack(
                    [
                        p(
                            f"{moe_pref.format(i)}.experts.{e}."
                            f"{w_name}.weight"
                        ).T
                        for e in range(cfg.n_experts)
                    ],
                    axis=0,
                )
                for i in range(L)
            ]
            return jnp.asarray(np.stack(mats, axis=0), dtype=dt)

        params["layers"].update(
            w_router=stack(moe_pref + ".gate.weight", True),
            w_gate=stack_experts("gate"),
            w_up=stack_experts("up"),
            w_down=stack_experts("down"),
        )
    elif fused_gate_up:
        gu = "model.layers.{}.mlp.gate_up_proj.weight"
        params["layers"].update(
            w_gate=stack_rows(gu, 0, F),
            w_up=stack_rows(gu, F, 2 * F),
            w_down=stack("model.layers.{}.mlp.down_proj.weight", True),
        )
    else:
        params["layers"].update(
            w_gate=stack("model.layers.{}.mlp.gate_proj.weight", True),
            w_up=stack("model.layers.{}.mlp.up_proj.weight", True),
            w_down=stack("model.layers.{}.mlp.down_proj.weight", True),
        )
    if cfg.attn_qkv_bias:
        # Qwen2-style per-output-column biases, stacked like their weights
        params["layers"]["bq"] = stack("model.layers.{}.self_attn.q_proj.bias", False)
        params["layers"]["bk"] = stack("model.layers.{}.self_attn.k_proj.bias", False)
        params["layers"]["bv"] = stack("model.layers.{}.self_attn.v_proj.bias", False)
    elif "model.layers.0.self_attn.q_proj.bias" in sd:
        raise ValueError(
            "checkpoint has q/k/v projection biases but cfg.attn_qkv_bias is "
            "False — converting would silently drop them"
        )
    if cfg.use_qk_norm:
        # Qwen3 per-head q/k norms, [Dh] each, stacked over layers
        params["layers"]["q_norm"] = stack(
            "model.layers.{}.self_attn.q_norm.weight", False
        )
        params["layers"]["k_norm"] = stack(
            "model.layers.{}.self_attn.k_norm.weight", False
        )
    elif "model.layers.0.self_attn.q_norm.weight" in sd:
        raise ValueError(
            "checkpoint has q/k norms but cfg.use_qk_norm is False — "
            "converting would silently drop them"
        )
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(p("lm_head.weight").T, dtype=dt)
    return params


def gpt2_params_from_state_dict(sd: Mapping[str, Any], cfg: ModelConfig) -> dict:
    """Convert a HF GPT-2 `state_dict()` into the stacked pytree.

    GPT-2 uses Conv1D modules whose weights are already [in, out] — no
    transpose — and a fused qkv projection `c_attn` [D, 3D] that we split.
    """
    dt = cfg.jnp_dtype
    L, D = cfg.n_layers, cfg.dim
    p = lambda k: _np(sd[k])

    def stack(fmt: str) -> np.ndarray:
        return np.stack([p(fmt.format(i)) for i in range(L)], axis=0)

    c_attn_w = stack("transformer.h.{}.attn.c_attn.weight")  # [L, D, 3D]
    c_attn_b = stack("transformer.h.{}.attn.c_attn.bias")  # [L, 3D]
    params = {
        "embed": jnp.asarray(p("transformer.wte.weight"), dtype=dt),
        "pos_embed": jnp.asarray(p("transformer.wpe.weight"), dtype=dt),
        "layers": {
            "ln1_w": jnp.asarray(stack("transformer.h.{}.ln_1.weight"), dtype=dt),
            "ln1_b": jnp.asarray(stack("transformer.h.{}.ln_1.bias"), dtype=dt),
            "ln2_w": jnp.asarray(stack("transformer.h.{}.ln_2.weight"), dtype=dt),
            "ln2_b": jnp.asarray(stack("transformer.h.{}.ln_2.bias"), dtype=dt),
            "wq": jnp.asarray(c_attn_w[:, :, :D], dtype=dt),
            "wk": jnp.asarray(c_attn_w[:, :, D : 2 * D], dtype=dt),
            "wv": jnp.asarray(c_attn_w[:, :, 2 * D :], dtype=dt),
            "bq": jnp.asarray(c_attn_b[:, :D], dtype=dt),
            "bk": jnp.asarray(c_attn_b[:, D : 2 * D], dtype=dt),
            "bv": jnp.asarray(c_attn_b[:, 2 * D :], dtype=dt),
            "wo": jnp.asarray(stack("transformer.h.{}.attn.c_proj.weight"), dtype=dt),
            "bo": jnp.asarray(stack("transformer.h.{}.attn.c_proj.bias"), dtype=dt),
            "w_fc": jnp.asarray(stack("transformer.h.{}.mlp.c_fc.weight"), dtype=dt),
            "b_fc": jnp.asarray(stack("transformer.h.{}.mlp.c_fc.bias"), dtype=dt),
            "w_proj": jnp.asarray(stack("transformer.h.{}.mlp.c_proj.weight"), dtype=dt),
            "b_proj": jnp.asarray(stack("transformer.h.{}.mlp.c_proj.bias"), dtype=dt),
        },
        "final_norm_w": jnp.asarray(p("transformer.ln_f.weight"), dtype=dt),
        "final_norm_b": jnp.asarray(p("transformer.ln_f.bias"), dtype=dt),
    }
    return params


def params_from_hf_model(hf_model: Any, dtype: str = "float32"):
    """(cfg, params) from an in-memory transformers model instance."""
    cfg = config_from_hf(hf_model.config, name=getattr(hf_model.config, "name_or_path", "") or "converted", dtype=dtype)
    sd = hf_model.state_dict()
    if cfg.arch == "gpt2":
        return cfg, gpt2_params_from_state_dict(sd, cfg)
    return cfg, llama_params_from_state_dict(sd, cfg)


# -- safetensors files -------------------------------------------------------
#
# Hand-rolled reader for the safetensors on-disk format: 8-byte LE header
# length, JSON header {name: {dtype, shape, data_offsets}}, then raw tensor
# bytes. mmap + np.frombuffer gives zero-copy views — only the pages the
# stacking step actually touches are read, and no torch module is ever
# built (the reference instantiates the FULL model on every process and
# throws half away, /root/reference/Worker1.py:60-75).

_ST_DTYPES = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
}


def _st_dtype(name: str):
    if name == "BF16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        return np.dtype(_ST_DTYPES[name])
    except KeyError:
        raise ValueError(f"unsupported safetensors dtype {name!r}") from None


def load_safetensors_file(path: str) -> dict:
    """Read one .safetensors file into {name: np.ndarray} (zero-copy mmap
    views; the file mapping stays alive as long as the arrays do)."""
    with open(path, "rb") as f:
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    header_len = int.from_bytes(mm[:8], "little")
    header = json.loads(mm[8 : 8 + header_len].decode("utf-8"))
    base = 8 + header_len
    out = {}
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        dt = _st_dtype(meta["dtype"])
        shape = meta["shape"]
        o0, o1 = meta["data_offsets"]
        n = int(np.prod(shape)) if shape else 1
        if o1 - o0 != n * dt.itemsize:
            raise ValueError(
                f"{path}: tensor {name!r} length {o1 - o0} != "
                f"prod(shape)*itemsize {n * dt.itemsize}"
            )
        out[name] = np.frombuffer(mm, dtype=dt, count=n, offset=base + o0).reshape(shape)
    return out


def load_safetensors_dir(path: str) -> dict:
    """State dict from a HF checkpoint dir: `model.safetensors`, a sharded
    `model.safetensors.index.json`, or any *.safetensors files present."""
    index = os.path.join(path, "model.safetensors.index.json")
    single = os.path.join(path, "model.safetensors")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        sd = {}
        for shard in sorted(set(weight_map.values())):
            sd.update(load_safetensors_file(os.path.join(path, shard)))
        missing = set(weight_map) - set(sd)
        if missing:
            raise ValueError(f"{index}: shards missing tensors {sorted(missing)[:5]}")
        return sd
    if os.path.exists(single):
        return load_safetensors_file(single)
    files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    sd = {}
    for fp in files:
        sd.update(load_safetensors_file(fp))
    return sd


class _JsonConfig:
    """Attribute view over config.json.

    Transformers config objects always carry the token-id attributes (as
    None when unset), so those read as None here too; every other absent
    key raises AttributeError so (a) the getattr(..., default) probes in
    config_from_hf fall back to their real defaults instead of silently
    producing None-valued model hyperparameters, and (b) a checkpoint
    missing a required key (hidden_size, n_embd, ...) fails loudly."""

    _NONE_DEFAULTED = frozenset(
        {"eos_token_id", "bos_token_id", "pad_token_id", "n_inner"}
    )

    def __init__(self, d: dict):
        self.__dict__.update(d)

    def __getattr__(self, name):  # only called when not in __dict__
        if name in self._NONE_DEFAULTED:
            return None
        raise AttributeError(
            f"config.json has no {name!r} (and it has no None default)"
        )


def load_hf_checkpoint(path: str, name: str = None, dtype: str = "float32"):
    """(cfg, params) from a HF checkpoint directory on disk.

    `path` must hold config.json + safetensors weights (what
    `save_pretrained(..., safe_serialization=True)` writes, and what the
    Hub serves for every supported model family).
    """
    cfg_path = os.path.join(path, "config.json")
    with open(cfg_path) as f:
        raw = json.load(f)
    hf_cfg = _JsonConfig(raw)
    cfg = config_from_hf(hf_cfg, name=name or os.path.basename(os.path.normpath(path)), dtype=dtype)
    sd = load_safetensors_dir(path)
    # HF omits lm_head.weight from checkpoints when tied even if the config
    # says untied-capable; trust the tensors over the flag
    if cfg.arch == "llama" and not cfg.tie_embeddings and "lm_head.weight" not in sd:
        cfg = cfg.replace(tie_embeddings=True)
    if cfg.arch == "gpt2":
        return cfg, gpt2_params_from_state_dict(sd, cfg)
    return cfg, llama_params_from_state_dict(sd, cfg)


def main(argv=None) -> int:
    """CLI: convert a HF checkpoint dir into the local checkpoint store."""
    import argparse

    import jax

    # Conversion is a host-side file transform: force the CPU backend so
    # the CLI neither waits on nor contends with an accelerator another
    # process (e.g. the serving engine) is using. Must run before the
    # first backend init; wins over the env-pinned platform.
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized (e.g. main() called from tests)

    from .checkpoint import save_params

    ap = argparse.ArgumentParser(
        prog="python -m distributed_llm_inference_tpu.models.convert",
        description="Convert a HuggingFace safetensors checkpoint into the "
        "stacked-layer local checkpoint store (models/checkpoint.py).",
    )
    ap.add_argument("--in", dest="src", required=True, help="HF checkpoint dir")
    ap.add_argument("--out", dest="dst", required=True, help="output ckpt dir")
    ap.add_argument("--dtype", default="bfloat16", choices=["float32", "bfloat16"])
    ap.add_argument("--name", default=None, help="model name recorded in the config")
    args = ap.parse_args(argv)

    cfg, params = load_hf_checkpoint(args.src, name=args.name, dtype=args.dtype)
    save_params(args.dst, cfg, params)
    # carry the tokenizer along: the serving CLI auto-loads tokenizer files
    # found in --checkpoint DIR (strict), so a converted store serves real
    # text with no extra flags (the reference couples tokenizer + weights
    # the same way, /root/reference/orchestration.py:34-39)
    import shutil

    copied = []
    for fname in (
        "tokenizer.json", "tokenizer_config.json", "special_tokens_map.json",
        "vocab.json", "merges.txt", "tokenizer.model",
    ):
        src_f = os.path.join(args.src, fname)
        if os.path.exists(src_f):
            shutil.copy2(src_f, os.path.join(args.dst, fname))
            copied.append(fname)
    import jax

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    print(
        json.dumps(
            {
                "model": cfg.name,
                "arch": cfg.arch,
                "n_layers": cfg.n_layers,
                "n_params": int(n_params),
                "dtype": cfg.dtype,
                "out": args.dst,
                "tokenizer_files": copied,
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
