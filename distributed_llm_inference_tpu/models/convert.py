"""HuggingFace checkpoint -> JAX params converter.

Replaces the reference's L0 loading/partitioning, which downloads the *full*
torch model on every process and slices `nn.ModuleList`s
(/root/reference/orchestration.py:38-53, Worker1.py:60-77 — keeping the whole
model around just for rotary access). Here a HF state dict (torch tensors or
a safetensors file) is converted once into the stacked-layer pytree of
models/llama.py / models/gpt2.py; pipeline stages then slice the stacked
layer axis, so a stage only ever materializes its own shard.

Works fully offline: accepts any in-memory `state_dict()` (tests build
tiny-random HF models from configs, no hub access needed).
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np
import jax.numpy as jnp

from ..config import ModelConfig


def _np(t) -> np.ndarray:
    """torch tensor / np array -> float32 numpy (converted to model dtype at
    the end, matching HF's fp32 master weights for small models)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, dtype=np.float32)


def config_from_hf(hf_cfg: Any, name: str = "converted", dtype: str = "float32") -> ModelConfig:
    """Map a transformers LlamaConfig/GPT2Config to our ModelConfig."""
    mt = getattr(hf_cfg, "model_type", "llama")
    if mt == "gpt2":
        return ModelConfig(
            name=name,
            arch="gpt2",
            vocab_size=hf_cfg.vocab_size,
            dim=hf_cfg.n_embd,
            n_layers=hf_cfg.n_layer,
            n_heads=hf_cfg.n_head,
            n_kv_heads=hf_cfg.n_head,
            ffn_dim=hf_cfg.n_inner if hf_cfg.n_inner is not None else 4 * hf_cfg.n_embd,
            max_seq_len=hf_cfg.n_positions,
            norm_eps=hf_cfg.layer_norm_epsilon,
            tie_embeddings=True,
            use_learned_pos=True,
            dtype=dtype,
            eos_token_id=hf_cfg.eos_token_id if hf_cfg.eos_token_id is not None else 50256,
            bos_token_id=hf_cfg.bos_token_id if hf_cfg.bos_token_id is not None else 50256,
            pad_token_id=hf_cfg.eos_token_id if hf_cfg.eos_token_id is not None else 50256,
        )
    return ModelConfig(
        name=name,
        arch="llama",
        vocab_size=hf_cfg.vocab_size,
        dim=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=getattr(hf_cfg, "num_key_value_heads", hf_cfg.num_attention_heads),
        ffn_dim=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        norm_eps=hf_cfg.rms_norm_eps,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        # Mistral-style sliding window (HF: None/absent = full causal)
        attn_window=getattr(hf_cfg, "sliding_window", None),
        tie_embeddings=getattr(hf_cfg, "tie_word_embeddings", False),
        dtype=dtype,
        eos_token_id=hf_cfg.eos_token_id if hf_cfg.eos_token_id is not None else 2,
        bos_token_id=hf_cfg.bos_token_id if hf_cfg.bos_token_id is not None else 1,
        pad_token_id=hf_cfg.pad_token_id if hf_cfg.pad_token_id is not None else 0,
    )


def llama_params_from_state_dict(sd: Mapping[str, Any], cfg: ModelConfig) -> dict:
    """Convert a HF Llama-family `state_dict()` into the stacked pytree.

    torch Linear stores weight as [out, in]; our matmuls are x @ W with
    W [in, out], so every projection is transposed once here.
    """
    dt = cfg.jnp_dtype
    L = cfg.n_layers
    p = lambda k: _np(sd[k])

    def stack(fmt: str, transpose: bool) -> jnp.ndarray:
        mats = [p(fmt.format(i)) for i in range(L)]
        arr = np.stack([m.T if transpose else m for m in mats], axis=0)
        return jnp.asarray(arr, dtype=dt)

    params = {
        "embed": jnp.asarray(p("model.embed_tokens.weight"), dtype=dt),
        "layers": {
            "attn_norm": stack("model.layers.{}.input_layernorm.weight", False),
            "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight", False),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight", True),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight", True),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight", True),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight", True),
            "w_gate": stack("model.layers.{}.mlp.gate_proj.weight", True),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight", True),
            "w_down": stack("model.layers.{}.mlp.down_proj.weight", True),
        },
        "final_norm": jnp.asarray(p("model.norm.weight"), dtype=dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(p("lm_head.weight").T, dtype=dt)
    return params


def gpt2_params_from_state_dict(sd: Mapping[str, Any], cfg: ModelConfig) -> dict:
    """Convert a HF GPT-2 `state_dict()` into the stacked pytree.

    GPT-2 uses Conv1D modules whose weights are already [in, out] — no
    transpose — and a fused qkv projection `c_attn` [D, 3D] that we split.
    """
    dt = cfg.jnp_dtype
    L, D = cfg.n_layers, cfg.dim
    p = lambda k: _np(sd[k])

    def stack(fmt: str) -> np.ndarray:
        return np.stack([p(fmt.format(i)) for i in range(L)], axis=0)

    c_attn_w = stack("transformer.h.{}.attn.c_attn.weight")  # [L, D, 3D]
    c_attn_b = stack("transformer.h.{}.attn.c_attn.bias")  # [L, 3D]
    params = {
        "embed": jnp.asarray(p("transformer.wte.weight"), dtype=dt),
        "pos_embed": jnp.asarray(p("transformer.wpe.weight"), dtype=dt),
        "layers": {
            "ln1_w": jnp.asarray(stack("transformer.h.{}.ln_1.weight"), dtype=dt),
            "ln1_b": jnp.asarray(stack("transformer.h.{}.ln_1.bias"), dtype=dt),
            "ln2_w": jnp.asarray(stack("transformer.h.{}.ln_2.weight"), dtype=dt),
            "ln2_b": jnp.asarray(stack("transformer.h.{}.ln_2.bias"), dtype=dt),
            "wq": jnp.asarray(c_attn_w[:, :, :D], dtype=dt),
            "wk": jnp.asarray(c_attn_w[:, :, D : 2 * D], dtype=dt),
            "wv": jnp.asarray(c_attn_w[:, :, 2 * D :], dtype=dt),
            "bq": jnp.asarray(c_attn_b[:, :D], dtype=dt),
            "bk": jnp.asarray(c_attn_b[:, D : 2 * D], dtype=dt),
            "bv": jnp.asarray(c_attn_b[:, 2 * D :], dtype=dt),
            "wo": jnp.asarray(stack("transformer.h.{}.attn.c_proj.weight"), dtype=dt),
            "bo": jnp.asarray(stack("transformer.h.{}.attn.c_proj.bias"), dtype=dt),
            "w_fc": jnp.asarray(stack("transformer.h.{}.mlp.c_fc.weight"), dtype=dt),
            "b_fc": jnp.asarray(stack("transformer.h.{}.mlp.c_fc.bias"), dtype=dt),
            "w_proj": jnp.asarray(stack("transformer.h.{}.mlp.c_proj.weight"), dtype=dt),
            "b_proj": jnp.asarray(stack("transformer.h.{}.mlp.c_proj.bias"), dtype=dt),
        },
        "final_norm_w": jnp.asarray(p("transformer.ln_f.weight"), dtype=dt),
        "final_norm_b": jnp.asarray(p("transformer.ln_f.bias"), dtype=dt),
    }
    return params


def params_from_hf_model(hf_model: Any, dtype: str = "float32"):
    """(cfg, params) from an in-memory transformers model instance."""
    cfg = config_from_hf(hf_model.config, name=getattr(hf_model.config, "name_or_path", "") or "converted", dtype=dtype)
    sd = hf_model.state_dict()
    if cfg.arch == "gpt2":
        return cfg, gpt2_params_from_state_dict(sd, cfg)
    return cfg, llama_params_from_state_dict(sd, cfg)
