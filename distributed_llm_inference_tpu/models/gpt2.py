"""GPT-2 family decoder in pure JAX (BASELINE configs 1-2).

Same stacked-layer pytree discipline as models/llama.py (scan over layers,
layer axis shardable over the pipeline mesh axis, KV cache threaded through)
with GPT-2 architecture: LayerNorm with bias, learned absolute position
embeddings, fused-qkv MHA with biases, gelu_new MLP, tied LM head.

Params pytree:
  embed      [V, D]      pos_embed [P, D]
  layers:
    ln1_w/ln1_b [L, D]   ln2_w/ln2_b [L, D]
    wq/wk/wv [L, D, D]   bq/bk/bv [L, D]
    wo [L, D, D]         bo [L, D]
    w_fc [L, D, F]  b_fc [L, F]  w_proj [L, F, D]  b_proj [L, D]
  final_norm_w / final_norm_b [D]
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..ops.attention import causal_mask, slot_causal_mask
from ..ops.norms import layer_norm
from ..ops.quant import matmul as mm

Params = dict
KVCache = dict


def gelu_new(x: jnp.ndarray) -> jnp.ndarray:
    """GPT-2's tanh-approximate GELU (HF activation 'gelu_new'), fp32."""
    xf = x.astype(jnp.float32)
    c = jnp.sqrt(2.0 / jnp.pi)
    out = 0.5 * xf * (1.0 + jnp.tanh(c * (xf + 0.044715 * xf ** 3)))
    return out.astype(x.dtype)


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    dt = cfg.jnp_dtype
    L, D, F, V, P = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.vocab_size, cfg.max_seq_len
    ks = jax.random.split(key, 8)

    def normal(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "embed": normal(ks[0], (V, D)),
        "pos_embed": normal(ks[1], (P, D), 0.01),
        "layers": {
            "ln1_w": jnp.ones((L, D), dt),
            "ln1_b": jnp.zeros((L, D), dt),
            "ln2_w": jnp.ones((L, D), dt),
            "ln2_b": jnp.zeros((L, D), dt),
            "wq": normal(ks[2], (L, D, D)),
            "wk": normal(ks[3], (L, D, D)),
            "wv": normal(ks[4], (L, D, D)),
            "bq": jnp.zeros((L, D), dt),
            "bk": jnp.zeros((L, D), dt),
            "bv": jnp.zeros((L, D), dt),
            "wo": normal(ks[5], (L, D, D)),
            "bo": jnp.zeros((L, D), dt),
            "w_fc": normal(ks[6], (L, D, F)),
            "b_fc": jnp.zeros((L, F), dt),
            "w_proj": normal(ks[7], (L, F, D)),
            "b_proj": jnp.zeros((L, D), dt),
        },
        "final_norm_w": jnp.ones((D,), dt),
        "final_norm_b": jnp.zeros((D,), dt),
    }


def init_kv_cache(
    cfg: ModelConfig, batch: int, max_seq: Optional[int] = None, n_layers: Optional[int] = None
) -> KVCache:
    # MHA is GQA with n_kv_heads == n_heads (enforced by the GPT-2 configs),
    # so the cache-layout contract lives in one place: llama.init_kv_cache.
    from .llama import init_kv_cache as _llama_init_kv_cache

    return _llama_init_kv_cache(cfg, batch, max_seq=max_seq, n_layers=n_layers)


def decoder_layer(cfg, lp, x, cache_k, cache_v, pos, mask, update_gate=None,
                  tp_axis=None, attn_hook=None):
    """One GPT-2 block on chunk x [B,T,D] at offset pos.

    Cache write + attention go through the SHARED hook seam
    (models/llama.default_attn_hook — GPT-2 is MHA, i.e. GQA with
    group=1, no window/softcap/scale override, so the default hook's
    behavior is exactly the old inline path), which is what lets the
    paged pool (engine/paged.make_paged_hook) and the int8 KV cache ride
    GPT-2 the same way they ride llama. Projections go through ops/quant
    `mm` so int8/int4 weight-only quantization applies transparently.

    Tensor parallelism mirrors models/llama.py: head-sliced qkv shards
    (with their per-output-column biases bq/bk/bv sharded alongside),
    row-sharded wo/w_proj partial outputs psummed over `tp_axis`; the
    row-projection biases bo/b_proj are replicated and added once, OUTSIDE
    the psum (inside it they'd be added tp times).
    """
    from .llama import default_attn_hook

    B, T, D = x.shape
    Dh = cfg.head_dim
    H = lp["wq"].shape[-1] // Dh

    h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], cfg.norm_eps)
    q = (mm(h, lp["wq"]) + lp["bq"]).reshape(B, T, H, Dh)
    k = (mm(h, lp["wk"]) + lp["bk"]).reshape(B, T, H, Dh)
    v = (mm(h, lp["wv"]) + lp["bv"]).reshape(B, T, H, Dh)

    hook = attn_hook or default_attn_hook
    attn, new_k, new_v = hook(
        cfg, q, k, v, cache_k, cache_v, pos, mask, update_gate, None, None
    )
    attn_out = mm(attn.reshape(B, T, H * Dh), lp["wo"])
    if tp_axis is not None:
        attn_out = jax.lax.psum(attn_out, tp_axis)
    x = x + attn_out + lp["bo"]

    h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], cfg.norm_eps)
    mlp_out = mm(gelu_new(mm(h, lp["w_fc"]) + lp["b_fc"]), lp["w_proj"])
    if tp_axis is not None:
        mlp_out = jax.lax.psum(mlp_out, tp_axis)
    x = x + mlp_out + lp["b_proj"]
    return x, new_k, new_v


def forward_layers(cfg, layers, x, cache, pos, update_gate=None, tp_axis=None,
                   attn_hook=None, valid_start=None, ep_axis=None,
                   attn_seq_len=None):
    """Scan the stacked GPT-2 blocks over a chunk (any contiguous slice).
    pos: scalar chunk offset, or a per-row [B] vector (continuous-batching
    slots — GPT-2 CAN slot-batch: unlike ragged left-padding, every slot
    starts at position 0, so learned absolute positions stay exact).
    attn_hook: the shared attention/cache seam (paged pool, int8 cache);
    attn_seq_len: paged logical mask length (see llama.forward_layers).
    valid_start/ep_axis reject loudly: learned absolute positions are not
    shift-invariant (no ragged left-padding), and GPT-2 has no MoE."""
    if valid_start is not None:
        raise NotImplementedError(
            "gpt2 does not support ragged (valid_start) batches: learned "
            "absolute position embeddings are not shift-invariant"
        )
    if ep_axis is not None:
        raise NotImplementedError("gpt2 has no MoE layers (ep_axis)")
    T = x.shape[1]
    S = attn_seq_len if attn_seq_len is not None else cache["k"].shape[3]
    pos = jnp.asarray(pos, jnp.int32)
    if pos.ndim == 1:
        mask = slot_causal_mask(pos, T, S)
    else:
        mask = causal_mask(pos, T, S)

    def body(carry, xs):
        xc = carry
        lp, ck, cv = xs
        xc, ck, cv = decoder_layer(cfg, lp, xc, ck, cv, pos, mask, update_gate,
                                   tp_axis, attn_hook)
        return xc, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(body, x, (layers, cache["k"], cache["v"]))
    return x, {"k": new_k, "v": new_v}


def embed(cfg: ModelConfig, params: Params, tokens: jnp.ndarray, pos=0) -> jnp.ndarray:
    """Token + learned position embeddings. pos: chunk offset (scalar), or
    a per-row [B] vector (slots mode: each row at its own position)."""
    T = tokens.shape[1]
    pos = jnp.asarray(pos, jnp.int32)
    if pos.ndim == 1:
        positions = pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B, T]
        return params["embed"][tokens] + params["pos_embed"][positions]
    positions = pos + jnp.arange(T, dtype=jnp.int32)
    return params["embed"][tokens] + params["pos_embed"][positions][None, :, :]


def unembed(cfg: ModelConfig, params: Params, x: jnp.ndarray) -> jnp.ndarray:
    x = layer_norm(x, params["final_norm_w"], params["final_norm_b"], cfg.norm_eps)
    return (x @ params["embed"].T).astype(jnp.float32)


def forward(cfg, params, tokens, cache, pos):
    x = embed(cfg, params, tokens, pos)
    x, cache = forward_layers(cfg, params["layers"], x, cache, pos)
    return unembed(cfg, params, x), cache
