"""Local checkpoint store with per-stage slice loading.

The reference has no checkpointing at all: every process re-downloads the
FULL model from the HF Hub at every boot and then throws most of it away
(/root/reference/Worker1.py:60-75, orchestration.py:39-53 — SURVEY.md §5
"checkpoint/resume"). Here converted params (models/convert.py) are saved
once to a local directory and reloaded in milliseconds, and — because the
per-layer tensors are STACKED on a leading layer axis — a pipeline stage
can load exactly its `layers[start:end]` slice via numpy memory-mapping:
only the pages of its own shard are ever read from disk.

Format: one `.npy` per pytree leaf (slash-joined key paths, `/` -> `__`)
plus `manifest.json` holding the ModelConfig and each leaf's logical
dtype. bfloat16 leaves are stored as their raw uint16 bit patterns (np.save
round-trips ml_dtypes unreliably) and re-viewed on load.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import numpy as np
import jax.numpy as jnp
import ml_dtypes

from ..config import ModelConfig, stage_layer_range

_MANIFEST = "manifest.json"


def _flatten(tree: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _unflatten(flat: dict) -> dict:
    out: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def _leaf_file(key: str) -> str:
    return key.replace("/", "__") + ".npy"


def save_params(path: str, cfg: ModelConfig, params: dict) -> None:
    """Write params + config to `path` (created if needed)."""
    os.makedirs(path, exist_ok=True)
    flat = _flatten(params)
    leaves = {}
    for key, leaf in flat.items():
        arr = np.asarray(leaf)
        logical = str(arr.dtype)
        if arr.dtype == ml_dtypes.bfloat16:
            arr = arr.view(np.uint16)
        np.save(os.path.join(path, _leaf_file(key)), arr)
        leaves[key] = {"dtype": logical}
    manifest = {"config": dataclasses.asdict(cfg), "leaves": leaves}
    with open(os.path.join(path, _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)


def _read_manifest(path: str) -> tuple[ModelConfig, dict]:
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    raw = manifest["config"]
    # JSON round-trips tuples as lists; coerce tuple-typed fields back so
    # the loaded config compares equal to the saved one
    for k in ("stop_token_ids",):
        if k in raw and isinstance(raw[k], list):
            raw[k] = tuple(raw[k])
    cfg = ModelConfig(**raw)
    return cfg, manifest["leaves"]


def _load_leaf(
    path: str, key: str, logical: str, layer_slice: Optional[tuple] = None
):
    """mmap-load one leaf; with layer_slice=(start, end) only that slice of
    the leading (layer) axis is copied out of the mapping."""
    arr = np.load(os.path.join(path, _leaf_file(key)), mmap_mode="r")
    if layer_slice is not None:
        arr = arr[layer_slice[0] : layer_slice[1]]
    arr = np.ascontiguousarray(arr)
    if logical == "bfloat16":
        arr = arr.view(ml_dtypes.bfloat16)
    return jnp.asarray(arr)


def load_params(path: str) -> tuple[ModelConfig, dict]:
    """Full restore: (cfg, params)."""
    cfg, leaves = _read_manifest(path)
    flat = {k: _load_leaf(path, k, meta["dtype"]) for k, meta in leaves.items()}
    return cfg, _unflatten(flat)


def load_params_sharded(path: str, mesh) -> tuple[ModelConfig, dict]:
    """Restore a checkpoint directly into mesh-sharded ``jax.Array``s.

    This is `load_stage_params` generalized to a whole (dp, pp, tp, ep)
    mesh: every leaf is built with `jax.make_array_from_callback`, whose
    callback mmap-reads ONLY the rows/columns of the requesting device's
    shard — so no host ever materializes a full-model copy. The reference
    downloads the FULL model on every worker and keeps it
    (/root/reference/Worker1.py:60-77, the 2x memory waste SURVEY.md §5
    calls out); here a pp=8 host touches 1/8 of the layer pages on disk.

    Padding performed on the fly, mirroring parallel/partition.py:
      * stacked layer leaves pad the leading layer axis to ceil(L/pp)*pp
        with all-zero no-op layers (pad_stacked_layers's mapping);
      * embed rows / lm_head columns pad their vocab dim to a multiple of
        pp (parallel/vocab.pad_vocab).

    Returns (cfg, params) where params' leaves are already placed; the
    backends' shard_params() detects placed leaves and skips its own
    device_put (parallel/partition.params_already_placed).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.partition import (
        layer_specs, padded_layers_per_stage, shared_specs, validate_mesh,
    )
    from ..parallel.mesh import AXIS_EP, AXIS_PP, AXIS_TP
    from ..parallel.vocab import VOCAB_SHARDED, padded_vocab

    cfg, leaves = _read_manifest(path)
    pp = int(mesh.shape[AXIS_PP])
    tp = int(mesh.shape.get(AXIS_TP, 1))
    ep = int(mesh.shape.get(AXIS_EP, 1))
    validate_mesh(cfg, pp, tp, ep)
    L = cfg.n_layers
    per = padded_layers_per_stage(L, pp)
    # padded layer row -> (source row, valid): pad rows sit at the tail of
    # each stage's slot block, exactly as pad_stacked_layers lays them out
    src = np.zeros(per * pp, np.int64)
    valid = np.zeros(per * pp, bool)
    for s in range(pp):
        lo, hi = stage_layer_range(L, pp, s)
        for j in range(hi - lo):
            src[s * per + j] = lo + j
            valid[s * per + j] = True
    V_pad = padded_vocab(cfg.vocab_size, pp)

    mmaps = {
        key: np.load(os.path.join(path, _leaf_file(key)), mmap_mode="r")
        for key in leaves
    }
    layer_names = sorted(
        k.split("/", 1)[1] for k in leaves if k.startswith("layers/")
    )
    lspecs = layer_specs(cfg, {n: mmaps[f"layers/{n}"] for n in layer_names})
    sspecs = shared_specs(
        {k: v for k, v in mmaps.items() if not k.startswith("layers/")}
    )

    def _norm_idx(index, shape):
        # make_array_from_callback hands a per-dimension tuple of slices
        # (entries may have None bounds); concretize against the global shape
        out = []
        for sl, dim in zip(index, shape):
            start, stop, step = sl.indices(dim)
            if step != 1:
                raise NotImplementedError(f"strided shard index {sl}")
            out.append(slice(start, stop))
        return tuple(out)

    def _read_layer_shard(mm, index, gshape):
        idx = _norm_idx(index, gshape)
        rows = idx[0]
        rest = idx[1:]
        out = np.zeros(
            tuple(sl.stop - sl.start for sl in idx), dtype=mm.dtype
        )
        r = rows.start
        while r < rows.stop:
            if not valid[r]:
                r += 1
                continue
            r2 = r  # extend over a contiguous source run -> one disk read
            while r2 + 1 < rows.stop and valid[r2 + 1] and src[r2 + 1] == src[r2] + 1:
                r2 += 1
            out[r - rows.start : r2 - rows.start + 1] = mm[
                (slice(int(src[r]), int(src[r2]) + 1),) + rest
            ]
            r = r2 + 1
        return out

    def _read_vocab_shard(mm, index, gshape, vaxis):
        idx = _norm_idx(index, gshape)
        orig = mm.shape[vaxis]
        want = idx[vaxis]
        real = slice(want.start, min(want.stop, orig))
        out = np.zeros(tuple(sl.stop - sl.start for sl in idx), dtype=mm.dtype)
        if real.stop > real.start:
            n = real.stop - real.start
            dst = [slice(None)] * len(idx)
            dst[vaxis] = slice(0, n)
            src_idx = list(idx)
            src_idx[vaxis] = real
            out[tuple(dst)] = mm[tuple(src_idx)]
        return out

    def _make(key, mm, spec, gshape, reader):
        sharding = NamedSharding(mesh, spec)
        logical = leaves[key]["dtype"]

        def cb(index):
            arr = np.ascontiguousarray(reader(mm, index, gshape))
            if logical == "bfloat16":
                arr = arr.view(ml_dtypes.bfloat16)
            return arr

        return jax.make_array_from_callback(gshape, sharding, cb)

    flat = {}
    for key, mm in mmaps.items():
        if key.startswith("layers/"):
            name = key.split("/", 1)[1]
            gshape = (per * pp,) + mm.shape[1:]
            flat[key] = _make(key, mm, lspecs[name], gshape, _read_layer_shard)
        elif key in VOCAB_SHARDED:
            vaxis = VOCAB_SHARDED[key]
            gshape = list(mm.shape)
            gshape[vaxis] = V_pad
            flat[key] = _make(
                key, mm, sspecs[key], tuple(gshape),
                lambda m, i, g, a=vaxis: _read_vocab_shard(m, i, g, a),
            )
        else:
            flat[key] = _make(
                key, mm, sspecs[key], mm.shape,
                lambda m, i, g: m[_norm_idx(i, g)],
            )
    return cfg, _unflatten(flat)


def load_stage_params(
    path: str,
    pp: int,
    stage: int,
    *,
    load_embed: Optional[bool] = None,
    load_head: Optional[bool] = None,
) -> tuple[ModelConfig, dict]:
    """Restore one pipeline stage's shard: `layers/*` sliced to
    stage_layer_range(n_layers, pp, stage); shared leaves filtered by role.

    Embeddings are needed by the FIRST stage (token/pos embed) and the
    final norm + LM head by the LAST (defaults follow §7's design: embed
    and head live on first/last stages, not a separate orchestrator). Pass
    load_embed/load_head to override. Note tied-embedding models
    (gpt2/TinyLlama variants) need `embed` on the last stage too — the
    default handles that.
    """
    cfg, leaves = _read_manifest(path)
    start, end = stage_layer_range(cfg.n_layers, pp, stage)
    first, last = stage == 0, stage == pp - 1
    explicit_embed = load_embed  # None = role-based defaults below
    if load_embed is None:
        load_embed = first or (last and cfg.tie_embeddings)
    if load_head is None:
        load_head = last

    flat = {}
    for key, meta in leaves.items():
        if key.startswith("layers/"):
            flat[key] = _load_leaf(path, key, meta["dtype"], (start, end))
            continue
        if key == "pos_embed":
            # read only by the first stage's embedding step — a tied-head
            # last stage needs `embed` but never `pos_embed`
            want = first if explicit_embed is None else explicit_embed
        elif key == "embed":
            want = load_embed
        elif key in ("lm_head", "final_norm", "final_norm_w", "final_norm_b"):
            want = load_head
        else:
            want = True  # unknown shared leaf: keep it everywhere (safe default)
        if want:
            flat[key] = _load_leaf(path, key, meta["dtype"])
    return cfg, _unflatten(flat)
