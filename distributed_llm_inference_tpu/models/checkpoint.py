"""Local checkpoint store with per-stage slice loading.

The reference has no checkpointing at all: every process re-downloads the
FULL model from the HF Hub at every boot and then throws most of it away
(/root/reference/Worker1.py:60-75, orchestration.py:39-53 — SURVEY.md §5
"checkpoint/resume"). Here converted params (models/convert.py) are saved
once to a local directory and reloaded in milliseconds, and — because the
per-layer tensors are STACKED on a leading layer axis — a pipeline stage
can load exactly its `layers[start:end]` slice via numpy memory-mapping:
only the pages of its own shard are ever read from disk.

Format: one `.npy` per pytree leaf (slash-joined key paths, `/` -> `__`)
plus `manifest.json` holding the ModelConfig and each leaf's logical
dtype. bfloat16 leaves are stored as their raw uint16 bit patterns (np.save
round-trips ml_dtypes unreliably) and re-viewed on load.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import numpy as np
import jax.numpy as jnp
import ml_dtypes

from ..config import ModelConfig, stage_layer_range

_MANIFEST = "manifest.json"


def _flatten(tree: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _unflatten(flat: dict) -> dict:
    out: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def _leaf_file(key: str) -> str:
    return key.replace("/", "__") + ".npy"


def save_params(path: str, cfg: ModelConfig, params: dict) -> None:
    """Write params + config to `path` (created if needed)."""
    os.makedirs(path, exist_ok=True)
    flat = _flatten(params)
    leaves = {}
    for key, leaf in flat.items():
        arr = np.asarray(leaf)
        logical = str(arr.dtype)
        if arr.dtype == ml_dtypes.bfloat16:
            arr = arr.view(np.uint16)
        np.save(os.path.join(path, _leaf_file(key)), arr)
        leaves[key] = {"dtype": logical}
    manifest = {"config": dataclasses.asdict(cfg), "leaves": leaves}
    with open(os.path.join(path, _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)


def _read_manifest(path: str) -> tuple[ModelConfig, dict]:
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    raw = manifest["config"]
    # JSON round-trips tuples as lists; coerce tuple-typed fields back so
    # the loaded config compares equal to the saved one
    for k in ("stop_token_ids",):
        if k in raw and isinstance(raw[k], list):
            raw[k] = tuple(raw[k])
    cfg = ModelConfig(**raw)
    return cfg, manifest["leaves"]


def _load_leaf(
    path: str, key: str, logical: str, layer_slice: Optional[tuple] = None
):
    """mmap-load one leaf; with layer_slice=(start, end) only that slice of
    the leading (layer) axis is copied out of the mapping."""
    arr = np.load(os.path.join(path, _leaf_file(key)), mmap_mode="r")
    if layer_slice is not None:
        arr = arr[layer_slice[0] : layer_slice[1]]
    arr = np.ascontiguousarray(arr)
    if logical == "bfloat16":
        arr = arr.view(ml_dtypes.bfloat16)
    return jnp.asarray(arr)


def load_params(path: str) -> tuple[ModelConfig, dict]:
    """Full restore: (cfg, params)."""
    cfg, leaves = _read_manifest(path)
    flat = {k: _load_leaf(path, k, meta["dtype"]) for k, meta in leaves.items()}
    return cfg, _unflatten(flat)


def load_stage_params(
    path: str,
    pp: int,
    stage: int,
    *,
    load_embed: Optional[bool] = None,
    load_head: Optional[bool] = None,
) -> tuple[ModelConfig, dict]:
    """Restore one pipeline stage's shard: `layers/*` sliced to
    stage_layer_range(n_layers, pp, stage); shared leaves filtered by role.

    Embeddings are needed by the FIRST stage (token/pos embed) and the
    final norm + LM head by the LAST (defaults follow §7's design: embed
    and head live on first/last stages, not a separate orchestrator). Pass
    load_embed/load_head to override. Note tied-embedding models
    (gpt2/TinyLlama variants) need `embed` on the last stage too — the
    default handles that.
    """
    cfg, leaves = _read_manifest(path)
    start, end = stage_layer_range(cfg.n_layers, pp, stage)
    first, last = stage == 0, stage == pp - 1
    explicit_embed = load_embed  # None = role-based defaults below
    if load_embed is None:
        load_embed = first or (last and cfg.tie_embeddings)
    if load_head is None:
        load_head = last

    flat = {}
    for key, meta in leaves.items():
        if key.startswith("layers/"):
            flat[key] = _load_leaf(path, key, meta["dtype"], (start, end))
            continue
        if key == "pos_embed":
            # read only by the first stage's embedding step — a tied-head
            # last stage needs `embed` but never `pos_embed`
            want = first if explicit_embed is None else explicit_embed
        elif key == "embed":
            want = load_embed
        elif key in ("lm_head", "final_norm", "final_norm_w", "final_norm_b"):
            want = load_head
        else:
            want = True  # unknown shared leaf: keep it everywhere (safe default)
        if want:
            flat[key] = _load_leaf(path, key, meta["dtype"])
    return cfg, _unflatten(flat)
