"""Llama-family decoder (TinyLlama / Llama-2 / Llama-3) in pure JAX.

TPU-first redesign of the compute the reference spreads across three
processes: the orchestrator's embed/norm/lm_head
(/root/reference/orchestration.py:45-47,111,140-141) and the workers'
decoder-layer slices (/root/reference/Worker1.py:68-70,82-177) become one
functional model over a parameter pytree whose per-layer tensors are
*stacked on a leading layer axis*. That layout gives us:

  * `lax.scan` over layers (one compiled layer body, no Python loop),
  * clean pipeline partitioning — a stage's params are a contiguous slice
    of the layer axis, shardable with `NamedSharding` over the `pp` mesh
    axis (replacing the reference's LAYER_START/LAYER_END module constants,
    Worker1.py:27-28),
  * a KV cache with the same stacked layout, threaded through the scan.

Params pytree (L = n_layers, D = dim, H/KV heads, Dh = head_dim, F = ffn_dim,
V = vocab):
  embed       [V, D]
  layers:
    attn_norm [L, D]      mlp_norm [L, D]
    wq [L, D, H*Dh]  wk [L, D, KV*Dh]  wv [L, D, KV*Dh]  wo [L, H*Dh, D]
    w_gate [L, D, F]  w_up [L, D, F]  w_down [L, F, D]
  final_norm  [D]
  lm_head     [D, V]   (absent when tie_embeddings)
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..ops.attention import (
    attend,
    causal_mask,
    ragged_causal_mask,
    slot_causal_mask,
    update_kv_cache,
    update_kv_cache_slots,
)
from ..ops.flash_attention import flash_attend
from ..ops.kv_quant import KVQuant
from ..ops.kv_quant import dequantize as kv_dequantize
from ..ops.kv_quant import init_quant_cache
from ..ops.kv_quant import update_cache as kv_update
from ..ops.kv_quant import update_cache_slots as kv_update_slots
from ..ops.norms import rms_norm
from ..ops.quant import expert_einsum as eem
from ..ops.quant import matmul as mm
from ..ops.rope import apply_rope, rope_cos_sin

Params = dict
KVCache = dict  # {"k": [L, B, KV, S, Dh], "v": [L, B, KV, S, Dh]}


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Random-init params (for tests/benchmarks; real weights come from
    models/convert.py). Scaled-normal init, dtype = cfg.dtype."""
    dt = cfg.jnp_dtype
    L, D, F, V = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.vocab_size
    H, KV, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    ks = jax.random.split(key, 10)

    def normal(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    s = D ** -0.5
    # unit-offset norms (Gemma) multiply by (1 + w): neutral init is 0
    norm_init = jnp.zeros if cfg.norm_unit_offset else jnp.ones
    params = {
        "embed": normal(ks[0], (V, D), 0.02),
        "layers": {
            "wq": normal(ks[1], (L, D, H * Dh), s),
            "wk": normal(ks[2], (L, D, KV * Dh), s),
            "wv": normal(ks[3], (L, D, KV * Dh), s),
            "wo": normal(ks[4], (L, H * Dh, D), s),
        },
        "final_norm": norm_init((D,), dt),
    }
    if cfg.pre_norms:
        params["layers"]["attn_norm"] = norm_init((L, D), dt)
        params["layers"]["mlp_norm"] = norm_init((L, D), dt)
    if cfg.post_norms:  # Gemma-2 sandwich norms (and OLMo-2's only norms)
        params["layers"]["attn_post_norm"] = norm_init((L, D), dt)
        params["layers"]["mlp_post_norm"] = norm_init((L, D), dt)
    wf = make_window_flags(cfg)
    if wf is not None:
        params["layers"]["window_flag"] = wf
    if cfg.n_experts:  # Mixtral-style MoE FFN: expert bank + router
        E = cfg.n_experts
        params["layers"].update(
            w_router=normal(ks[9], (L, D, E), s),
            w_gate=normal(ks[5], (L, E, D, F), s),
            w_up=normal(ks[6], (L, E, D, F), s),
            w_down=normal(ks[7], (L, E, F, D), F ** -0.5),
        )
    else:
        params["layers"].update(
            w_gate=normal(ks[5], (L, D, F), s),
            w_up=normal(ks[6], (L, D, F), s),
            w_down=normal(ks[7], (L, F, D), F ** -0.5),
        )
    if cfg.attn_qkv_bias:  # Qwen2-style
        params["layers"]["bq"] = jnp.zeros((L, H * Dh), dt)
        params["layers"]["bk"] = jnp.zeros((L, KV * Dh), dt)
        params["layers"]["bv"] = jnp.zeros((L, KV * Dh), dt)
    if cfg.use_qk_norm:
        # Qwen3/Gemma-3: per-head [Dh]; OLMo-2 ("proj"): whole projection
        if cfg.qk_norm_dim == "proj":
            params["layers"]["q_norm"] = norm_init((L, H * Dh), dt)
            params["layers"]["k_norm"] = norm_init((L, KV * Dh), dt)
        else:
            params["layers"]["q_norm"] = norm_init((L, Dh), dt)
            params["layers"]["k_norm"] = norm_init((L, Dh), dt)
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(ks[8], (D, V), s)
    return params


def make_window_flags(cfg: ModelConfig) -> Optional[jnp.ndarray]:
    """[L] per-layer sliding-window flag for mixed attention patterns
    (Gemma-2: even-indexed layers slide, HF `not bool(layer_idx % 2)`;
    Gemma-3: an explicit layer_types list — 5 sliding : 1 full), or None
    when the pattern is uniform. Single source of truth for init_params
    AND the converter — the stacked flag travels with a pipeline stage's
    layer slice."""
    if cfg.attn_window is None:
        return None
    if cfg.attn_window_layer_types is not None:
        return jnp.asarray(cfg.attn_window_layer_types, jnp.float32)
    if cfg.attn_window_pattern != "even":
        return None
    L = cfg.n_layers
    return (jnp.arange(L, dtype=jnp.int32) % 2 == 0).astype(jnp.float32)


def kernel_window(cfg: ModelConfig, window_flag):
    """Resolve this layer's window for the Pallas kernels: (static,
    traced) where exactly one is live. Uniform configs keep the STATIC
    cfg.attn_window; mixed patterns (window_flag is the layer's scalar
    from the stacked make_window_flags leaf, only present for them)
    yield a TRACED width — this layer's cfg.attn_window when flagged,
    -1 (= full causal, the kernels' <= 0 sentinel) otherwise. The single
    source of the flag -> width encoding for BOTH kernel hooks
    (default_attn_hook's chunk flash and engine/paged's fused decode)."""
    if window_flag is None:
        return cfg.attn_window, None
    return None, jnp.where(
        window_flag > 0, jnp.int32(cfg.attn_window), jnp.int32(-1)
    )


def init_kv_cache(
    cfg: ModelConfig, batch: int, max_seq: Optional[int] = None, n_layers: Optional[int] = None
) -> KVCache:
    """Zeroed static-shape KV cache, stacked on the layer axis (shardable
    over `pp` exactly like the layer params)."""
    S = max_seq or cfg.max_seq_len
    L = n_layers if n_layers is not None else cfg.n_layers
    if cfg.kv_quant == "int8":
        # int8 data + per-(token, head) fp32 scales (ops/kv_quant.py);
        # same {"k", "v"} dict shape, leaves are KVQuant pytrees
        return init_quant_cache(L, batch, cfg.n_kv_heads, S, cfg.head_dim)
    shape = (L, batch, cfg.n_kv_heads, S, cfg.head_dim)
    dt = cfg.jnp_dtype
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def default_attn_hook(cfg, q, k, v, cache_k, cache_v, pos, mask, update_gate,
                      valid_start=None, window_flag=None):
    """Cache write + attention for the dense (whole-cache-per-device) case.

    The hook seam lets SPMD backends swap the attention/cache strategy per
    topology without forking the block: parallel/context.py substitutes
    ring attention (prefill) and context-parallel merge (decode) here.
    Returns (attn [B,T,H,Dh], cache_k, cache_v).

    window_flag: this layer's scalar from the stacked per-layer window
    pattern (Gemma-2/3 alternating layers; None for uniform configs). The
    XLA paths ignore it — their mask was already selected per layer in
    decoder_layer — but the flash kernel derives its traced per-layer
    window width from it (flash_attend's window_dyn scalar-prefetch
    operand).

    pos may be a PER-ROW [B] vector (continuous batching: each slot at its
    own position) — the cache write becomes a vmapped per-row update and
    attention uses the XLA path.

    attn_impl="pallas" applies to T>1 chunks only (prefill / chunked
    ingest / speculative verify — the compute-bound phases where the
    flash kernel measured 1.5x XLA); every T=1 decode step keeps the XLA
    einsum, which measured decisively faster (15x on the solo loop, see
    the inline notes).

    An int8 cache (ops/kv_quant.KVQuant leaves, cfg.kv_quant="int8")
    dispatches on the leaf type: quantize-on-write, dequantize into the
    attention matmuls on read. The fleet/solo split is the same.
    """
    # mixed per-layer window patterns (window_flag only exists for them):
    # the kernel's width becomes a TRACED per-layer scalar via the shared
    # kernel_window encoding, so one compiled kernel serves the whole scan
    def _flash(q_, nk, nv):
        w, wd = kernel_window(cfg, window_flag)
        return flash_attend(
            q_, nk, nv, pos, valid_start, wd, window=w,
            scale=cfg.query_scale, softcap=cfg.attn_softcap,
        )

    if isinstance(cache_k, KVQuant):
        upd = kv_update_slots if pos.ndim == 1 else kv_update
        new_k = upd(cache_k, k, pos, gate=update_gate)
        new_v = upd(cache_v, v, pos, gate=update_gate)
        if cfg.attn_impl == "pallas" and pos.ndim == 0 and q.shape[1] > 1:
            # same T>1-chunks-only gate as the raw-dtype path below; the
            # kernel dequantizes in its tile prologue, so the int8 cache
            # streams HALF the bytes the XLA dequant-then-attend path
            # materializes
            attn = _flash(q, new_k, new_v)
        else:
            attn = attend(
                q, kv_dequantize(new_k), kv_dequantize(new_v), mask,
                scale=cfg.query_scale, softcap=cfg.attn_softcap,
            )
        return attn, new_k, new_v
    if pos.ndim == 1:
        new_k, new_v = update_kv_cache_slots(
            cache_k, cache_v, k, v, pos, gate=update_gate
        )
        # Always the XLA einsum here, even under attn_impl="pallas":
        # fleet decode is T=1 and measured FASTER on XLA than the per-row
        # kernel (ops/paged_attention.flash_attend_slots, v5e: 395 vs
        # 382 tok/s end to end, ~1.00 vs ~1.08 ms at the attention
        # level). The kernel stays exported/tested and bench.py's fleet
        # leg tracks the gap every round.
        attn = attend(
            q, new_k, new_v, mask,
            scale=cfg.query_scale, softcap=cfg.attn_softcap,
        )
        return attn, new_k, new_v
    new_k, new_v = update_kv_cache(cache_k, cache_v, k, v, pos, gate=update_gate)
    if cfg.attn_impl == "pallas" and q.shape[1] > 1:
        # Flash kernel for the COMPUTE-bound chunks only (prefill,
        # chunked ingest, speculative verify): measured 1.5x the XLA
        # prefill throughput on v5e at 1k prompts (bench flash leg). At
        # T=1 the same kernel INSIDE the decode loop measured 15x slower
        # than the einsum (per-step kernel overhead with no flops to
        # hide it under), so decode always takes the XLA path — this
        # gate is what makes "--attn-impl pallas/auto" strictly a win.
        attn = _flash(q, new_k, new_v)
    else:
        attn = attend(
            q, new_k, new_v, mask,
            scale=cfg.query_scale, softcap=cfg.attn_softcap,
        )
    return attn, new_k, new_v


def moe_ffn(
    cfg: ModelConfig,
    lp: Params,
    h: jnp.ndarray,
    ep_axis: Optional[str] = None,
) -> jnp.ndarray:
    """Mixtral-style sparse MoE FFN on a (normed) chunk h [B, T, D].

    HF MixtralSparseMoeBlock semantics (the behavioral spec): fp32 softmax
    over the router logits, top-k, renormalize the selected weights, sum
    the selected experts' SwiGLU outputs. Computed as all-local-experts +
    masked weighted sum: for small decode batches that is the standard
    inference pattern — under an `ep` mesh axis every device computes its
    1/ep slice of the expert bank for ALL tokens and one psum combines, so
    per-device FLOPs stay ~constant while parameters scale with E.

    lp holds this layer's (possibly ep-sharded) expert slice:
    w_router [D, E] (replicated), w_gate/w_up [E_loc, D, F],
    w_down [E_loc, F, D].
    """
    E, k = cfg.n_experts, cfg.n_experts_per_tok
    logits = (h @ lp["w_router"]).astype(jnp.float32)  # [B, T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    topw, topi = jax.lax.top_k(probs, k)
    if cfg.moe_renormalize:  # Qwen3-MoE: only with norm_topk_prob
        topw = topw / jnp.sum(topw, axis=-1, keepdims=True)
    weights = jnp.sum(
        jax.nn.one_hot(topi, E, dtype=jnp.float32) * topw[..., None], axis=-2
    )  # [B, T, E]: renormalized weight per expert, 0 for unselected
    weights = weights.astype(h.dtype)
    E_loc = lp["w_gate"].shape[0]
    if ep_axis is not None:
        lo = jax.lax.axis_index(ep_axis) * E_loc
        weights = jax.lax.dynamic_slice_in_dim(weights, lo, E_loc, axis=-1)
    # eem: dense array or int8 QTensor expert bank (ops/quant.expert_einsum)
    gate = jax.nn.silu(
        eem("btd,edf->btef", h, lp["w_gate"]).astype(jnp.float32)
    ).astype(h.dtype)
    up = eem("btd,edf->btef", h, lp["w_up"])
    down = eem("btef,efd->bted", gate * up, lp["w_down"])
    out = jnp.einsum("bted,bte->btd", down, weights)
    if ep_axis is not None:
        out = jax.lax.psum(out, ep_axis)
    return out


def decoder_layer(
    cfg: ModelConfig,
    lp: Params,
    x: jnp.ndarray,
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    pos: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    mask: jnp.ndarray,
    update_gate: Optional[jnp.ndarray] = None,
    tp_axis: Optional[str] = None,
    attn_hook=None,
    valid_start: Optional[jnp.ndarray] = None,
    ep_axis: Optional[str] = None,
    lora_pages: Optional[jnp.ndarray] = None,
):
    """One pre-norm decoder block on a chunk x [B,T,D] at offset `pos`.

    lp: this layer's params (no leading L axis). Returns (x, cache_k, cache_v).
    update_gate: optional traced bool — when False the cache write is
    discarded (needed by the pipeline runtime, where a stage executes
    speculatively on microsteps when it holds no valid microbatch).
    attn_hook: optional override of `default_attn_hook` (same signature) —
    the context-parallel backend injects ring / merged attention here.

    Tensor parallelism (Megatron-style): under `shard_map` with a `tp` mesh
    axis, lp holds the HEAD-SLICED shard (wq/wk/wv column-sharded over
    heads, wo row-sharded; w_gate/w_up column-, w_down row-sharded) and
    `tp_axis` names the axis — head counts are derived from the local param
    shapes, and the two row-sharded projections psum their partial outputs
    before the residual add, keeping activations replicated over tp.

    lora_pages: optional [B] int32 adapter-pool page ids (engine/
    adapters.AdapterPool), TRACED — one compiled program serves any
    adapter mix. When lp carries paged lora_{leaf}_{a,b} leaves, every
    projection adds its per-row low-rank delta (x @ a[page]) @ b[page]
    via a traced gather + batched matmul. Page 0 is the reserved base
    page: its rows SELECT the undisturbed base product (jnp.where, not
    +0.0 — IEEE -0.0 + 0.0 would break bit-identity with the no-adapter
    program). Deltas apply BEFORE the tp psums: a/b shard so the partial
    products sum correctly by linearity (parallel/partition.py).
    """
    B, T, D = x.shape
    Dh = cfg.head_dim  # invariant under tp (heads shard, head_dim doesn't)
    H = lp["wq"].shape[-1] // Dh
    KV = lp["wk"].shape[-1] // Dh
    uo = cfg.norm_unit_offset

    if isinstance(mask, tuple):
        # Gemma-2 alternating attention: (full, windowed) masks built once
        # per chunk; this layer's stacked window_flag picks its own
        mask_full, mask_win = mask
        mask = jnp.where(lp["window_flag"] > 0, mask_win, mask_full)

    # OLMo-2 (pre_norms=False): the sublayer reads x raw, its OUTPUT is
    # normed before the residual (post_norms carries those weights)
    def lmm(hh, leaf):
        # mm: plain array or int8 QTensor (ops/quant.py) transparently;
        # paged LoRA delta rides on top when the leaves are installed
        out = mm(hh, lp[leaf])
        a = lp.get(f"lora_{leaf}_a")
        if lora_pages is None or a is None:
            return out
        b = lp[f"lora_{leaf}_b"]
        u = jnp.einsum("bti,bir->btr", hh, a[lora_pages])
        d = jnp.einsum("btr,bro->bto", u, b[lora_pages])
        return jnp.where(
            (lora_pages > 0)[:, None, None], out + d.astype(out.dtype), out
        )

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps, unit_offset=uo) \
        if cfg.pre_norms else x
    q, k, v = lmm(h, "wq"), lmm(h, "wk"), lmm(h, "wv")
    if cfg.attn_qkv_bias:  # Qwen2-style (biases tp-shard with their columns)
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    if cfg.use_qk_norm and cfg.qk_norm_dim == "proj":
        # OLMo-2: RMSNorm over the WHOLE projection before the head split
        # (weights [H*Dh] / [KV*Dh]; tp-sharded with their columns)
        q = rms_norm(q, lp["q_norm"], cfg.norm_eps, unit_offset=uo)
        k = rms_norm(k, lp["k_norm"], cfg.norm_eps, unit_offset=uo)
    q = q.reshape(B, T, H, Dh)
    k = k.reshape(B, T, KV, Dh)
    v = v.reshape(B, T, KV, Dh)
    if cfg.use_qk_norm and cfg.qk_norm_dim == "head":
        # Qwen3/Gemma-3: per-head RMSNorm over head_dim on q and k,
        # BEFORE RoPE (HF Qwen3Attention / Gemma3Attention); weights [Dh]
        # broadcast over the head axis, invariant under tp. Gemma-3's
        # norm is the unit-offset (1 + w) flavor like its other norms.
        q = rms_norm(q, lp["q_norm"], cfg.norm_eps, unit_offset=uo)
        k = rms_norm(k, lp["k_norm"], cfg.norm_eps, unit_offset=uo)
    if isinstance(cos, tuple):
        # Gemma-3 dual RoPE: sliding layers use the local table
        cos_full, cos_local = cos
        sin_full, sin_local = sin
        cos = jnp.where(lp["window_flag"] > 0, cos_local, cos_full)
        sin = jnp.where(lp["window_flag"] > 0, sin_local, sin_full)
    q, k = apply_rope(q, k, cos, sin)

    hook = attn_hook or default_attn_hook
    attn, new_k, new_v = hook(
        cfg, q, k, v, cache_k, cache_v, pos, mask, update_gate, valid_start,
        lp.get("window_flag"),
    )
    attn_out = lmm(attn.reshape(B, T, H * Dh), "wo")
    if tp_axis is not None:
        attn_out = jax.lax.psum(attn_out, tp_axis)
    if cfg.post_norms:  # Gemma-2: norm the branch output before the residual
        attn_out = rms_norm(attn_out, lp["attn_post_norm"], cfg.norm_eps, unit_offset=uo)
    if cfg.residual_multiplier is not None:  # Granite
        attn_out = attn_out * jnp.asarray(cfg.residual_multiplier, attn_out.dtype)
    x = x + attn_out

    h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps, unit_offset=uo) \
        if cfg.pre_norms else x
    if cfg.n_experts:
        mlp_out = moe_ffn(cfg, lp, h, ep_axis)  # psums over ep internally
    else:
        act = jax.nn.silu if cfg.act == "silu" else _gelu_tanh
        gate = act(lmm(h, "w_gate").astype(jnp.float32)).astype(h.dtype)
        mlp_out = lmm(gate * lmm(h, "w_up"), "w_down")
        if tp_axis is not None:
            mlp_out = jax.lax.psum(mlp_out, tp_axis)
    if cfg.post_norms:
        mlp_out = rms_norm(mlp_out, lp["mlp_post_norm"], cfg.norm_eps, unit_offset=uo)
    if cfg.residual_multiplier is not None:  # Granite
        mlp_out = mlp_out * jnp.asarray(cfg.residual_multiplier, mlp_out.dtype)
    x = x + mlp_out
    return x, new_k, new_v


def _gelu_tanh(x):
    """gelu_pytorch_tanh (Gemma's hidden activation)."""
    return jax.nn.gelu(x, approximate=True)


def forward_layers(
    cfg: ModelConfig,
    layers: Params,
    x: jnp.ndarray,
    cache: KVCache,
    pos: jnp.ndarray,
    update_gate: Optional[jnp.ndarray] = None,
    tp_axis: Optional[str] = None,
    attn_hook=None,
    valid_start: Optional[jnp.ndarray] = None,
    ep_axis: Optional[str] = None,
    attn_seq_len: Optional[int] = None,
    lora_pages: Optional[jnp.ndarray] = None,
):
    """Scan the stacked layer params over a chunk. Works for any contiguous
    slice of layers (full model or one pipeline stage's slice).

    x: [B, T, D]; cache k/v: [L_slice, B, KV, S, Dh]; pos: scalar int32 OR
    a per-row [B] int32 vector (continuous batching — each slot row at its
    own sequence position; RoPE tables and the causal mask go per-row).
    Returns (x, new_cache). attn_hook: see decoder_layer.
    valid_start: optional [B] int32 — first REAL slot per row for ragged
    left-padded batches (slots before it are pad and never attended).
    attn_seq_len: mask sequence length override — the paged-KV hook
    (engine/paged.py) attends a GATHERED [B, KV, n_blocks*bs, Dh] view
    whose logical length is not the cache leaf's seq axis (that axis is
    the block size there), so masks must be built to the logical length.
    """
    T = x.shape[1]
    S = attn_seq_len if attn_seq_len is not None else cache["k"].shape[3]
    pos = jnp.asarray(pos, jnp.int32)
    if pos.ndim == 1:
        positions = pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B, T]
    else:
        positions = pos + jnp.arange(T, dtype=jnp.int32)
    cos, sin = rope_cos_sin(
        positions, cfg.head_dim, cfg.rope_theta,
        scaling=cfg.rope_scaling,
        scaling_factor=cfg.rope_scaling_factor,
        low_freq_factor=cfg.rope_low_freq_factor,
        high_freq_factor=cfg.rope_high_freq_factor,
        original_max_len=cfg.rope_original_max_len,
    )
    if cfg.rope_local_theta is not None:
        # Gemma-3: sliding layers rotate with their own UNSCALED local
        # theta; both tables built once, each layer selects by its
        # window_flag (decoder_layer)
        cos_l, sin_l = rope_cos_sin(
            positions, cfg.head_dim, cfg.rope_local_theta
        )
        cos, sin = (cos, cos_l), (sin, sin_l)

    def make_mask(window):
        if pos.ndim == 1:
            return slot_causal_mask(pos, T, S, window)
        if valid_start is None:
            return causal_mask(pos, T, S, window)
        return ragged_causal_mask(pos, T, S, valid_start, window)

    mixed_pattern = cfg.attn_window is not None and (
        cfg.attn_window_pattern == "even"
        or cfg.attn_window_layer_types is not None
    )
    if mixed_pattern:
        # Gemma-2/3 mixed attention: both masks built once; each layer
        # selects by its stacked window_flag (decoder_layer)
        mask = (make_mask(None), make_mask(cfg.attn_window))
    else:
        mask = make_mask(cfg.attn_window)

    def body(carry, xs):
        xc = carry
        lp, ck, cv = xs
        xc, ck, cv = decoder_layer(
            cfg, lp, xc, ck, cv, pos, cos, sin, mask, update_gate, tp_axis,
            attn_hook, valid_start, ep_axis, lora_pages,
        )
        return xc, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(body, x, (layers, cache["k"], cache["v"]))
    return x, {"k": new_k, "v": new_v}


def embed(cfg: ModelConfig, params: Params, tokens: jnp.ndarray, pos=0) -> jnp.ndarray:
    """Token embedding lookup: [B, T] -> [B, T, D]
    (reference orchestration.py:111). `pos` is accepted for interface parity
    with gpt2.embed (learned positions); RoPE models ignore it here.
    Gemma scales by sqrt(dim) in the activation dtype (HF normalizer)."""
    x = params["embed"][tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.dim ** 0.5, x.dtype)
    if cfg.embed_multiplier is not None:  # Granite
        x = x * jnp.asarray(cfg.embed_multiplier, x.dtype)
    return x


def unembed(cfg: ModelConfig, params: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Final RMSNorm + LM head: [B, T, D] -> [B, T, V] logits
    (reference orchestration.py:140-141). Gemma-2 softcaps the final
    logits: cap * tanh(logits / cap)."""
    x = rms_norm(x, params["final_norm"], cfg.norm_eps,
                 unit_offset=cfg.norm_unit_offset)
    if cfg.tie_embeddings:
        logits = (x @ params["embed"].T).astype(jnp.float32)
    else:
        logits = mm(x, params["lm_head"]).astype(jnp.float32)
    if cfg.final_softcap is not None:
        logits = cfg.final_softcap * jnp.tanh(logits / cfg.final_softcap)
    if cfg.logits_divider is not None:  # Granite logits_scaling
        logits = logits / cfg.logits_divider
    return logits


def forward(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,
    cache: KVCache,
    pos: jnp.ndarray,
):
    """Full-model chunk forward: tokens [B,T] at offset pos -> (logits
    [B,T,V] fp32, new_cache). One call == prefill; T=1 call == decode step."""
    x = embed(cfg, params, tokens)
    x, cache = forward_layers(cfg, params["layers"], x, cache, pos)
    return unembed(cfg, params, x), cache
