"""LoRA adapter loading + merge-at-load.

Serves a PEFT-format adapter directory (`adapter_config.json` +
`adapter_model.safetensors`) on top of a converted base checkpoint by
merging the low-rank deltas into the stacked weights ONCE at load:

    W' = W + (lora_alpha / r) * B @ A          (per layer, per module)

Merging (rather than keeping A/B live at runtime) is the TPU-friendly
serving shape here: decode is HBM-bound on the DENSE weight bytes either
way, a merged checkpoint runs every existing program (quantization,
pipeline sharding, speculation) unchanged, and there is no per-step
low-rank matmul overhead. Multi-adapter hot-swap batching is a possible
later extension; the reference has no adapter story at all (full
fine-tuned checkpoints only, /root/reference/Worker1.py:60).

PEFT tensor naming (peft >= 0.5 `save_pretrained`):
    base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight  [r, in]
    base_model.model.model.layers.{i}.self_attn.q_proj.lora_B.weight  [out, r]
Our stacked leaves store W.T relative to HF ([in, out]), so the merged
delta is (scale * B @ A).T.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..utils.logging import get_logger

log = get_logger("lora")

# PEFT target_modules name -> our stacked leaf
_MODULE_TO_LEAF = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "w_gate",
    "up_proj": "w_up",
    "down_proj": "w_down",
}


def load_lora_adapter(path: str) -> tuple[dict, dict]:
    """Read a PEFT adapter dir -> (adapter_config, {tensor_name: np.ndarray})."""
    from .convert import load_safetensors_file

    cfg_path = os.path.join(path, "adapter_config.json")
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(
            f"{path} has no adapter_config.json (expected a PEFT-format "
            f"adapter directory)"
        )
    with open(cfg_path) as f:
        acfg = json.load(f)
    tensor_path = os.path.join(path, "adapter_model.safetensors")
    if not os.path.exists(tensor_path):
        raise FileNotFoundError(f"{path} has no adapter_model.safetensors")
    return acfg, load_safetensors_file(tensor_path)


def merge_lora(cfg: ModelConfig, params: dict, adapter_path: str) -> dict:
    """Merge a PEFT LoRA adapter into converted stacked params.

    Runs BEFORE quantization/sharding (the merged dense weights then flow
    through every existing path). Raises on adapters that target modules
    this layout doesn't carry, on rank/shape mismatches, and on already-
    quantized params (merge order matters: quantizing first would merge
    into nothing).
    """
    from ..ops.quant import Q4Tensor, QTensor

    if cfg.arch != "llama":
        raise ValueError(
            f"LoRA merging is wired for the llama family; got {cfg.arch!r}"
        )
    acfg, tensors = load_lora_adapter(adapter_path)
    r = int(acfg["r"])
    # PEFT variants that change the merge MATH (not just naming) must be
    # rejected, not approximated — a silently-wrong merged model is the
    # worst failure mode a weights loader can have
    if acfg.get("use_dora"):
        raise ValueError(
            "DoRA adapters (use_dora=true) are not supported: the "
            "magnitude normalization changes the merge math"
        )
    if acfg.get("alpha_pattern"):
        raise ValueError(
            "per-module alpha_pattern adapters are not supported"
        )
    if acfg.get("layers_to_transform") is not None:
        raise ValueError(
            "layers_to_transform adapters (partial-layer) are not supported"
        )
    if acfg.get("modules_to_save"):
        raise ValueError(
            f"adapter carries fully fine-tuned modules_to_save="
            f"{acfg['modules_to_save']} — merging only the LoRA deltas "
            f"would silently drop them"
        )
    if acfg.get("bias", "none") != "none":
        raise ValueError(
            f"bias={acfg['bias']!r} adapters are not supported (trained "
            f"bias tensors would be dropped)"
        )
    if acfg.get("use_rslora"):
        # rank-stabilized LoRA: scale = alpha / sqrt(r)
        scale = float(acfg.get("lora_alpha", r)) / (r ** 0.5)
    else:
        scale = float(acfg.get("lora_alpha", r)) / r
    L = cfg.n_layers

    layers = dict(params["layers"])
    prefixes = (
        "base_model.model.model.layers.{}.self_attn.{}",
        "base_model.model.model.layers.{}.mlp.{}",
    )
    merged_modules = set()
    for module, leaf in _MODULE_TO_LEAF.items():
        # detect the module by ANY layer's tensor (a layers_to_transform
        # adapter that slipped past the config check still gets the
        # accurate partial-layer error below, not "unsupported target")
        a_name = b_name = None
        for pref in prefixes:
            if any(
                pref.format(i, module) + ".lora_A.weight" in tensors
                for i in range(L)
            ):
                a_name = pref + ".lora_A.weight"
                b_name = pref + ".lora_B.weight"
                break
        if a_name is None:
            continue
        if leaf not in layers:
            raise ValueError(
                f"adapter targets {module} but params have no {leaf!r} leaf"
            )
        w = layers[leaf]
        if isinstance(w, (QTensor, Q4Tensor)):
            raise ValueError(
                "params are already quantized — merge the LoRA adapter "
                "BEFORE quantization (create_engine does this when both "
                "are requested)"
            )
        deltas = []
        for i in range(L):
            a = tensors.get(a_name.format(i, module))
            b = tensors.get(b_name.format(i, module))
            if a is None or b is None:
                raise ValueError(
                    f"adapter is missing {module} lora_A/lora_B for layer "
                    f"{i} (partial-layer adapters are not supported)"
                )
            if a.shape[0] != r or b.shape[1] != r:
                raise ValueError(
                    f"layer {i} {module}: rank mismatch (adapter_config r="
                    f"{r}, tensors {a.shape} / {b.shape})"
                )
            # W' = W + scale * (B @ A); stacked leaves hold W.T [in, out]
            delta = (
                scale
                * b.astype(np.float32) @ a.astype(np.float32)
            ).T
            deltas.append(delta)
        stacked = jnp.asarray(np.stack(deltas, axis=0), w.dtype)
        if stacked.shape != w.shape:
            raise ValueError(
                f"{leaf}: adapter delta shape {stacked.shape} != weight "
                f"shape {w.shape}"
            )
        layers[leaf] = (w.astype(jnp.float32) + stacked.astype(jnp.float32)).astype(w.dtype)
        merged_modules.add(module)
    if not merged_modules:
        raise ValueError(
            f"adapter at {adapter_path} targets none of the supported "
            f"modules {sorted(_MODULE_TO_LEAF)}"
        )
    # ANY tensor not consumed by the merge is an error — fine-tuned heads,
    # bias terms, magnitude vectors, unsupported targets alike
    unknown = {
        n for n in tensors
        if not any(
            f".{m}.lora_A." in n or f".{m}.lora_B." in n
            for m in merged_modules
        )
    }
    if unknown:
        raise ValueError(
            f"adapter has tensors the merge would silently drop, e.g. "
            f"{sorted(unknown)[:3]}"
        )
    log.info(
        "lora_merged", adapter=adapter_path, r=r, scale=scale,
        modules=sorted(merged_modules),
    )
    out = dict(params)
    out["layers"] = layers
    return out
