"""LoRA adapter loading + merge-at-load.

Serves a PEFT-format adapter directory (`adapter_config.json` +
`adapter_model.safetensors`) on top of a converted base checkpoint by
merging the low-rank deltas into the stacked weights ONCE at load:

    W' = W + (lora_alpha / r) * B @ A          (per layer, per module)

Merge-at-load is the SINGLE-ADAPTER fast path: decode is HBM-bound on
the DENSE weight bytes either way, a merged checkpoint runs every
existing program (quantization, pipeline sharding, speculation)
unchanged, and there is no per-step low-rank matmul overhead. Use it
when one deployment serves one fine-tune.

Multi-adapter serving keeps A/B live instead: load_lora_stacked() below
reads the same PEFT directory into per-layer stacked A/B tensors
(rank-padded, scale folded into B) that engine/adapters.AdapterPool
writes into a paged slot of the resident base model's lora_* leaves —
many adapters share one base without merging, selected per-row inside
the batched launches (models/llama.decoder_layer's lora_pages gather).
The two paths are numerically the token-identical under greedy decode
(the fp32 delta math is shared); bit-level identity holds for rows with
adapter page 0, which skip the delta entirely. The reference has no
adapter story at all (full fine-tuned checkpoints only,
/root/reference/Worker1.py:60).

PEFT tensor naming (peft >= 0.5 `save_pretrained`):
    base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight  [r, in]
    base_model.model.model.layers.{i}.self_attn.q_proj.lora_B.weight  [out, r]
Our stacked leaves store W.T relative to HF ([in, out]), so the merged
delta is (scale * B @ A).T.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..utils.logging import get_logger

log = get_logger("lora")

# PEFT target_modules name -> our stacked leaf
_MODULE_TO_LEAF = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "w_gate",
    "up_proj": "w_up",
    "down_proj": "w_down",
}


def load_lora_adapter(path: str) -> tuple[dict, dict]:
    """Read a PEFT adapter dir -> (adapter_config, {tensor_name: np.ndarray})."""
    from .convert import load_safetensors_file

    cfg_path = os.path.join(path, "adapter_config.json")
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(
            f"{path} has no adapter_config.json (expected a PEFT-format "
            f"adapter directory)"
        )
    with open(cfg_path) as f:
        acfg = json.load(f)
    tensor_path = os.path.join(path, "adapter_model.safetensors")
    if not os.path.exists(tensor_path):
        raise FileNotFoundError(f"{path} has no adapter_model.safetensors")
    return acfg, load_safetensors_file(tensor_path)


def _check_adapter_cfg(acfg: dict) -> tuple[int, float]:
    """(rank, merge scale) after rejecting every PEFT variant that
    changes the delta MATH (not just naming) — a silently-wrong adapter
    is the worst failure mode a weights loader can have. Shared by the
    merge-at-load and runtime-stacked loaders so both paths accept and
    reject the exact same adapter population."""
    r = int(acfg["r"])
    if acfg.get("use_dora"):
        raise ValueError(
            "DoRA adapters (use_dora=true) are not supported: the "
            "magnitude normalization changes the merge math"
        )
    if acfg.get("alpha_pattern"):
        raise ValueError(
            "per-module alpha_pattern adapters are not supported"
        )
    if acfg.get("layers_to_transform") is not None:
        raise ValueError(
            "layers_to_transform adapters (partial-layer) are not supported"
        )
    if acfg.get("modules_to_save"):
        raise ValueError(
            f"adapter carries fully fine-tuned modules_to_save="
            f"{acfg['modules_to_save']} — merging only the LoRA deltas "
            f"would silently drop them"
        )
    if acfg.get("bias", "none") != "none":
        raise ValueError(
            f"bias={acfg['bias']!r} adapters are not supported (trained "
            f"bias tensors would be dropped)"
        )
    if acfg.get("use_rslora"):
        # rank-stabilized LoRA: scale = alpha / sqrt(r)
        scale = float(acfg.get("lora_alpha", r)) / (r ** 0.5)
    else:
        scale = float(acfg.get("lora_alpha", r)) / r
    return r, scale


def merge_lora(cfg: ModelConfig, params: dict, adapter_path: str) -> dict:
    """Merge a PEFT LoRA adapter into converted stacked params — the
    single-adapter fast path (see the module docstring; runtime
    multi-adapter serving goes through load_lora_stacked instead).

    Runs BEFORE quantization/sharding (the merged dense weights then flow
    through every existing path). Raises on adapters that target modules
    this layout doesn't carry, on rank/shape mismatches, and on already-
    quantized params (merge order matters: quantizing first would merge
    into nothing).
    """
    from ..ops.quant import Q4Tensor, QTensor

    if cfg.arch != "llama":
        raise ValueError(
            f"LoRA merging is wired for the llama family; got {cfg.arch!r}"
        )
    acfg, tensors = load_lora_adapter(adapter_path)
    r, scale = _check_adapter_cfg(acfg)
    L = cfg.n_layers

    layers = dict(params["layers"])
    prefixes = (
        "base_model.model.model.layers.{}.self_attn.{}",
        "base_model.model.model.layers.{}.mlp.{}",
    )
    merged_modules = set()
    for module, leaf in _MODULE_TO_LEAF.items():
        # detect the module by ANY layer's tensor (a layers_to_transform
        # adapter that slipped past the config check still gets the
        # accurate partial-layer error below, not "unsupported target")
        a_name = b_name = None
        for pref in prefixes:
            if any(
                pref.format(i, module) + ".lora_A.weight" in tensors
                for i in range(L)
            ):
                a_name = pref + ".lora_A.weight"
                b_name = pref + ".lora_B.weight"
                break
        if a_name is None:
            continue
        if leaf not in layers:
            raise ValueError(
                f"adapter targets {module} but params have no {leaf!r} leaf"
            )
        w = layers[leaf]
        if isinstance(w, (QTensor, Q4Tensor)):
            raise ValueError(
                "params are already quantized — merge the LoRA adapter "
                "BEFORE quantization (create_engine does this when both "
                "are requested)"
            )
        deltas = []
        for i in range(L):
            a = tensors.get(a_name.format(i, module))
            b = tensors.get(b_name.format(i, module))
            if a is None or b is None:
                raise ValueError(
                    f"adapter is missing {module} lora_A/lora_B for layer "
                    f"{i} (partial-layer adapters are not supported)"
                )
            if a.shape[0] != r or b.shape[1] != r:
                raise ValueError(
                    f"layer {i} {module}: rank mismatch (adapter_config r="
                    f"{r}, tensors {a.shape} / {b.shape})"
                )
            # W' = W + scale * (B @ A); stacked leaves hold W.T [in, out]
            delta = (
                scale
                * b.astype(np.float32) @ a.astype(np.float32)
            ).T
            deltas.append(delta)
        stacked = jnp.asarray(np.stack(deltas, axis=0), w.dtype)
        if stacked.shape != w.shape:
            raise ValueError(
                f"{leaf}: adapter delta shape {stacked.shape} != weight "
                f"shape {w.shape}"
            )
        layers[leaf] = (w.astype(jnp.float32) + stacked.astype(jnp.float32)).astype(w.dtype)
        merged_modules.add(module)
    if not merged_modules:
        raise ValueError(
            f"adapter at {adapter_path} targets none of the supported "
            f"modules {sorted(_MODULE_TO_LEAF)}"
        )
    # ANY tensor not consumed by the merge is an error — fine-tuned heads,
    # bias terms, magnitude vectors, unsupported targets alike
    unknown = {
        n for n in tensors
        if not any(
            f".{m}.lora_A." in n or f".{m}.lora_B." in n
            for m in merged_modules
        )
    }
    if unknown:
        raise ValueError(
            f"adapter has tensors the merge would silently drop, e.g. "
            f"{sorted(unknown)[:3]}"
        )
    log.info(
        "lora_merged", adapter=adapter_path, r=r, scale=scale,
        modules=sorted(merged_modules),
    )
    out = dict(params)
    out["layers"] = layers
    return out


def load_lora_stacked(cfg: ModelConfig, adapter_path: str,
                      max_rank: int) -> dict:
    """Read a PEFT adapter into RUNTIME stacked host tensors:
    {leaf: (a, b)} with a = A^T stacked [L, in, max_rank] and
    b = scale * B^T stacked [L, max_rank, out] (np.float32; the pool
    writes them in the model dtype). Rank-padding with zeros makes every
    adapter the pool's uniform rank so one compiled program serves any
    mix — padded rank columns contribute exactly 0 to the delta. The
    merge scale folds into b, so the traced delta is just
    (x @ a) @ b == scale * x @ A^T @ B^T, matching merge_lora's
    W' = W + scale * (B @ A) transposed into the stacked W.T layout.

    Accepts/rejects the exact same adapter population as merge_lora
    (shared _check_adapter_cfg + the same unknown-tensor sweep), plus a
    pool-specific rank bound: adapters above max_rank cannot ride the
    uniform batched delta and are rejected at load.
    """
    if cfg.arch != "llama":
        raise ValueError(
            f"LoRA adapters are wired for the llama family; got {cfg.arch!r}"
        )
    acfg, tensors = load_lora_adapter(adapter_path)
    r, scale = _check_adapter_cfg(acfg)
    if r > max_rank:
        raise ValueError(
            f"adapter rank {r} exceeds the adapter pool rank {max_rank} "
            f"(EngineConfig.adapter_rank) — raise the pool rank or use "
            f"merge-at-load (--lora) for this adapter"
        )
    L = cfg.n_layers
    prefixes = (
        "base_model.model.model.layers.{}.self_attn.{}",
        "base_model.model.model.layers.{}.mlp.{}",
    )
    out: dict = {}
    loaded_modules = set()
    for module, leaf in _MODULE_TO_LEAF.items():
        a_name = b_name = None
        for pref in prefixes:
            if any(
                pref.format(i, module) + ".lora_A.weight" in tensors
                for i in range(L)
            ):
                a_name = pref + ".lora_A.weight"
                b_name = pref + ".lora_B.weight"
                break
        if a_name is None:
            continue
        a_stack, b_stack = [], []
        for i in range(L):
            a = tensors.get(a_name.format(i, module))
            b = tensors.get(b_name.format(i, module))
            if a is None or b is None:
                raise ValueError(
                    f"adapter is missing {module} lora_A/lora_B for layer "
                    f"{i} (partial-layer adapters are not supported)"
                )
            if a.shape[0] != r or b.shape[1] != r:
                raise ValueError(
                    f"layer {i} {module}: rank mismatch (adapter_config r="
                    f"{r}, tensors {a.shape} / {b.shape})"
                )
            # stacked leaves hold W.T [in, out]: A [r, in] -> a = A.T
            # [in, r]; B [out, r] -> b = scale * B.T [r, out]
            a_p = np.zeros((a.shape[1], max_rank), np.float32)
            a_p[:, :r] = a.astype(np.float32).T
            b_p = np.zeros((max_rank, b.shape[0]), np.float32)
            b_p[:r, :] = scale * b.astype(np.float32).T
            a_stack.append(a_p)
            b_stack.append(b_p)
        out[leaf] = (np.stack(a_stack, axis=0), np.stack(b_stack, axis=0))
        loaded_modules.add(module)
    if not loaded_modules:
        raise ValueError(
            f"adapter at {adapter_path} targets none of the supported "
            f"modules {sorted(_MODULE_TO_LEAF)}"
        )
    unknown = {
        n for n in tensors
        if not any(
            f".{m}.lora_A." in n or f".{m}.lora_B." in n
            for m in loaded_modules
        )
    }
    if unknown:
        raise ValueError(
            f"adapter has tensors the runtime loader would silently drop, "
            f"e.g. {sorted(unknown)[:3]}"
        )
    log.info(
        "lora_stacked_loaded", adapter=adapter_path, r=r, scale=scale,
        pool_rank=max_rank, modules=sorted(loaded_modules),
    )
    return out
