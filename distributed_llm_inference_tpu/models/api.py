"""Arch dispatch: one functional interface over the model families.

The engine and pipeline runtime call these; cfg.arch picks the family
(llama: RMSNorm/RoPE/GQA/SwiGLU — gpt2: LayerNorm/learned-pos/MHA/gelu).
Both families share the stacked-layer pytree + KV-cache layout, so the
pipeline partitioner and cache plumbing are family-agnostic.
"""

from __future__ import annotations

from ..config import ModelConfig
from . import gpt2, llama

_FAMILIES = {"llama": llama, "gpt2": gpt2}


def family(cfg: ModelConfig):
    return _FAMILIES[cfg.arch]


def init_params(cfg, key):
    return family(cfg).init_params(cfg, key)


def init_kv_cache(cfg, batch, max_seq=None, n_layers=None):
    return family(cfg).init_kv_cache(cfg, batch, max_seq=max_seq, n_layers=n_layers)


def embed(cfg, params, tokens, pos=0):
    return family(cfg).embed(cfg, params, tokens, pos)


def forward_layers(cfg, layers, x, cache, pos, update_gate=None, tp_axis=None,
                   attn_hook=None, valid_start=None, ep_axis=None,
                   attn_seq_len=None, lora_pages=None):
    # Both families expose the same seams now: attn_hook (the shared
    # attention/cache strategy hook — parallel/context.py, the paged
    # pool), attn_seq_len (paged logical window). valid_start (ragged
    # left-padding), ep_axis (MoE) and lora_pages (paged adapter delta)
    # stay llama-only — gpt2's forward_layers rejects them loudly
    # (learned absolute positions are not shift-invariant; no MoE
    # blocks; no lora leaves).
    if lora_pages is not None and cfg.arch != "llama":
        raise ValueError(
            f"lora_pages (runtime adapters) requires the llama family; "
            f"got {cfg.arch!r}"
        )
    if (attn_hook is not None or valid_start is not None
            or ep_axis is not None or attn_seq_len is not None
            or lora_pages is not None):
        # gpt2.forward_layers has no lora_pages parameter; only thread
        # it when set (guaranteed llama by the check above)
        extra = {} if lora_pages is None else {"lora_pages": lora_pages}
        return family(cfg).forward_layers(
            cfg, layers, x, cache, pos, update_gate, tp_axis, attn_hook,
            valid_start, ep_axis, attn_seq_len=attn_seq_len, **extra,
        )
    return family(cfg).forward_layers(cfg, layers, x, cache, pos, update_gate,
                                      tp_axis)


def unembed(cfg, params, x):
    return family(cfg).unembed(cfg, params, x)


def forward(cfg, params, tokens, cache, pos):
    return family(cfg).forward(cfg, params, tokens, cache, pos)
