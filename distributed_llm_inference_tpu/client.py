"""Client library + interactive CLI (reference L5, /root/reference/Test.py).

Same flow as DistributedLLMClient: health check, worker sweep, generate
with perf-stat printing (Test.py:83-88), an interactive chat REPL with
`workers`/`health`/`quit` commands (Test.py:105-144), and a 3-option menu
(Test.py:147-188). stdlib urllib only — no requests dependency.
"""

from __future__ import annotations

import argparse
import json
import time
import urllib.error
import urllib.request
from typing import Any, Optional

# bounded-retry policy shared with the router tier (utils/retry.py):
# 429/503 retryable, Retry-After wins over jittered exponential backoff
from .utils.retry import RETRY_STATUSES, retry_delay
from .utils.tracing import SpanContext


class DistributedLLMClient:
    def __init__(self, base_url: str = "http://127.0.0.1:5000", timeout: float = 200.0,
                 max_retries: int = 3, retry_backoff_s: float = 0.5):
        # 200 s default mirrors Test.py:71's request timeout; a TPU backend
        # answers in milliseconds-to-seconds, but slow cold compiles exist.
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # bounded retry on 429/503 with jittered exponential backoff,
        # honoring the server's Retry-After (the drain path sends one);
        # 0 retries restores the old fail-fast behavior
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        # trace id of the most recent POST — the client ROOTS each
        # request's trace (W3C traceparent), so the whole fleet hop chain
        # is fetchable afterwards at GET /debug/traces/{last_trace_id}
        self.last_trace_id: Optional[str] = None

    def _trace_headers(self) -> dict:
        ctx = SpanContext.new_root()
        self.last_trace_id = ctx.trace_id
        return {"Content-Type": "application/json",
                "traceparent": ctx.header()}

    def _get(self, path: str, timeout: Optional[float] = None) -> dict:
        with urllib.request.urlopen(
            f"{self.base_url}{path}", timeout=timeout or self.timeout
        ) as r:
            return json.loads(r.read())

    def _retry_delay(self, attempt: int, retry_after) -> float:
        """Server-directed delay when Retry-After parses, else jittered
        exponential backoff (utils/retry.py — the one copy of the policy
        this client shares with the router's upstream calls)."""
        return retry_delay(attempt, retry_after, base_s=self.retry_backoff_s)

    def _post(self, path: str, payload: dict, timeout: Optional[float] = None) -> dict:
        req = urllib.request.Request(
            f"{self.base_url}{path}",
            data=json.dumps(payload).encode(),
            headers=self._trace_headers(),
            method="POST",
        )
        for attempt in range(self.max_retries + 1):
            try:
                with urllib.request.urlopen(req, timeout=timeout or self.timeout) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                try:
                    body = json.loads(e.read())
                except Exception:
                    body = {"error": str(e), "status": "failed"}
                if e.code in RETRY_STATUSES and attempt < self.max_retries:
                    time.sleep(self._retry_delay(
                        attempt, e.headers.get("Retry-After")
                    ))
                    continue
                return body
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                # connection refused / timeout: error envelope, not a traceback
                # (keeps the interactive REPL alive across server restarts).
                # NOT retried: a timed-out POST may have generated server-side.
                return {"error": f"connection failed: {e}", "status": "failed"}
        return {"error": "retries exhausted", "status": "failed"}

    # -- reference-parity surface (Test.py:18-103) --------------------------
    def check_health(self) -> dict:
        """Orchestrator liveness (Test.py:18-33)."""
        try:
            return self._get("/health", timeout=5)
        except Exception as e:
            return {"status": "offline", "error": str(e)}

    def check_workers(self) -> dict:
        """Per-stage health sweep (Test.py:35-52)."""
        try:
            return self._get("/workers", timeout=5)
        except Exception as e:
            return {"error": str(e)}

    def generate(
        self,
        prompt: str,
        max_tokens: int = 20,
        temperature: float = 0.7,
        verbose: bool = True,
        **kw: Any,
    ) -> dict:
        """Generate + print perf stats (Test.py:54-103)."""
        result = self._post(
            "/generate",
            {"prompt": prompt, "max_tokens": max_tokens, "temperature": temperature, **kw},
        )
        if verbose:
            if result.get("status") == "success":
                print(f"\n🤖 Response: {result.get('response', '')}")
                print(
                    f"   ⏱  {result.get('time_taken')} | "
                    f"{result.get('tokens_generated')} tokens | "
                    f"{result.get('tokens_per_sec')} tok/s | "
                    f"TTFT {result.get('ttft_s')}s"
                )
                # disaggregated serving detail (router envelopes): which
                # replica ran the token loop, and whether its prefix
                # arrived over the KV fabric instead of a local prefill
                extras = []
                if result.get("replica"):
                    extras.append(f"replica {result['replica']}")
                if result.get("kv_fabric_blocks"):
                    extras.append(
                        f"{result['kv_fabric_blocks']} KV blocks via fabric"
                    )
                if result.get("prefix_cached_tokens"):
                    extras.append(
                        f"{result['prefix_cached_tokens']} prefix tokens cached"
                    )
                if extras:
                    print(f"   🔀 {' | '.join(extras)}")
            else:
                print(f"\n❌ {result.get('error', 'unknown error')}")
        return result

    def generate_stream(self, prompt: str, max_tokens: int = 20, **kw: Any):
        """Stream a generation: print deltas as they arrive (NDJSON lines
        from a --continuous server), return the final envelope.

        Retry discipline: only a PRE-STREAM rejection (HTTP 429/503 — the
        stream never opened, zero output reached us) is retried. Once the
        200 stream opens, NOTHING is retried: partial generation output
        may already be on the user's screen, and replaying the request
        would bill and print it twice. Mid-stream failures arrive as a
        normal done-event and are returned as-is."""
        req = urllib.request.Request(
            f"{self.base_url}/generate",
            data=json.dumps(
                {"prompt": prompt, "max_tokens": max_tokens, "stream": True, **kw}
            ).encode(),
            headers=self._trace_headers(),
            method="POST",
        )
        final: dict = {}
        for attempt in range(self.max_retries + 1):
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    print("\n🤖 ", end="", flush=True)
                    for line in r:
                        ev = json.loads(line)
                        if ev.get("done"):
                            final = ev
                            break
                        print(ev.get("delta", ""), end="", flush=True)
                # failures arrive as a normal done-event over HTTP 200 (queue
                # full, deadline) — and a dropped connection leaves final empty
                if final.get("status") == "success":
                    print(
                        f"\n   ⏱  {final.get('time_taken')} | "
                        f"{final.get('tokens_generated')} tokens | "
                        f"{final.get('tokens_per_sec')} tok/s | "
                        f"TTFT {final.get('ttft_s')}s"
                    )
                else:
                    print(f"\n❌ {final.get('error', 'stream ended without a result')}")
            except urllib.error.HTTPError as e:
                try:
                    final = json.loads(e.read())
                except Exception:
                    final = {"error": str(e), "status": "failed"}
                if e.code in RETRY_STATUSES and attempt < self.max_retries:
                    time.sleep(self._retry_delay(
                        attempt, e.headers.get("Retry-After")
                    ))
                    continue
                print(f"\n❌ {final.get('error', 'unknown error')}")
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                # never retried: the stream may have started (partial output)
                final = {"error": f"connection failed: {e}", "status": "failed"}
                print(f"\n❌ {final['error']}")
            return final
        return final

    # -- interactive REPL (Test.py:105-144) ---------------------------------
    def interactive_chat(self):
        print("\n💬 Interactive chat — 'workers', 'health', or 'quit'")
        while True:
            try:
                line = input("\nYou: ").strip()
            except (EOFError, KeyboardInterrupt):
                break
            if not line:
                continue
            if line.lower() in ("quit", "exit"):
                break
            if line.lower() == "workers":
                print(json.dumps(self.check_workers(), indent=2, default=str))
                continue
            if line.lower() == "health":
                print(json.dumps(self.check_health(), indent=2))
                continue
            self.generate(line, max_tokens=15)


def main(argv: Optional[list] = None):
    ap = argparse.ArgumentParser(description="distributed_llm_inference_tpu client")
    ap.add_argument("--url", default="http://127.0.0.1:5000")
    ap.add_argument("--prompt", default=None, help="one-shot prompt (skips menu)")
    ap.add_argument("--max-tokens", type=int, default=20)
    ap.add_argument(
        "--stream", action="store_true",
        help="stream tokens as they decode (server must run --continuous)",
    )
    ap.add_argument(
        "--json", action="store_true", dest="constrain_json",
        help="grammar-constrain the output to valid JSON (server-side "
             "token masking, not prompting)",
    )
    ap.add_argument(
        "--regex", default=None, metavar="PATTERN", dest="constrain_regex",
        help="grammar-constrain the output to fullmatch PATTERN",
    )
    args = ap.parse_args(argv)

    kw = {}
    if args.constrain_regex is not None:
        kw["constraint"] = {"regex": args.constrain_regex}
    elif args.constrain_json:
        kw["constraint"] = {"json_object": True}

    client = DistributedLLMClient(args.url)
    if args.prompt is not None:
        if args.stream:
            client.generate_stream(args.prompt, max_tokens=args.max_tokens, **kw)
        else:
            client.generate(args.prompt, max_tokens=args.max_tokens, **kw)
        return

    # 3-option menu (Test.py:147-188)
    print("1) single prompt  2) interactive chat  3) quick test")
    try:
        choice = input("choice: ").strip()
    except (EOFError, KeyboardInterrupt):
        return
    if choice == "1":
        prompt = input("prompt: ").strip()
        client.generate(prompt, max_tokens=args.max_tokens)
    elif choice == "2":
        client.interactive_chat()
    else:
        print("health:", json.dumps(client.check_health()))
        print("workers:", json.dumps(client.check_workers(), default=str))
        client.generate("Hello", max_tokens=15)


if __name__ == "__main__":
    main()
