"""Package AST index + intra-package call graph + traced reachability.

The host-sync rule must know which functions execute INSIDE a jit trace:
linting file-by-file would either miss `sample_token` (ops/sampling.py,
called from every decode loop) or drown the host-side engine code in
false positives. So we parse every module in the package once, resolve
intra-package references (imports, module aliases, `self.` methods, the
models/api family dispatch), and walk the graph from the jit roots.

Jit roots — the functions whose BODIES are traced:
  * defs decorated with `jax.jit` / `functools.partial(jax.jit, ...)`;
  * functions passed by name to a `jax.jit(...)` call;
  * functions passed by name to `shard_map` / `jax.shard_map` /
    `self._shard(...)` (the parallel/ backends build their traced bodies
    as closures handed to a shard_map partial, then jit the result).

Edges — deliberately reference-based, not call-based: ANY Load of a name
that resolves to a package function adds an edge (`jax.lax.while_loop(
cond, body, init)` passes `body` without calling it; a reference is the
honest "may be traced" signal). Dynamic dispatch through
`family(cfg).embed(...)` (models/api.py) fans out to the same attribute
in every package module the dispatching module imports.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Iterator, Optional

# modules whose names never resolve into the package
_EXTERNAL = {
    "jax", "jnp", "np", "numpy", "functools", "threading", "collections",
    "math", "json", "time", "os", "re", "ast",
}


@dataclass
class FuncInfo:
    """One function (or method, or nested closure) in the package."""

    module: str  # dotted module name relative to the package root
    qualname: str  # "decode" / "PipelineBackend._build_prefill.body"
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    params: tuple = ()
    is_jit_root: bool = False
    jit_site: Optional[ast.Call] = None  # the jit Call/decorator, if any

    @property
    def key(self) -> tuple:
        return (self.module, self.qualname)


@dataclass
class ModuleInfo:
    name: str  # dotted, relative to the package ("engine.generate")
    path: str
    tree: ast.Module
    lines: list
    functions: dict = field(default_factory=dict)  # qualname -> FuncInfo
    # alias -> ("module", dotted) | ("obj", dotted_module, name)
    #        | ("external", name)
    imports: dict = field(default_factory=dict)


@dataclass
class PackageIndex:
    root: str  # filesystem path of the package dir
    modules: dict = field(default_factory=dict)  # dotted name -> ModuleInfo

    def functions(self) -> Iterator[FuncInfo]:
        for mod in self.modules.values():
            yield from mod.functions.values()

    def get(self, module: str, qualname: str) -> Optional[FuncInfo]:
        mod = self.modules.get(module)
        return mod.functions.get(qualname) if mod else None

    def rel_path(self, module: str) -> str:
        return self.modules[module].path


def _module_name(root: str, path: str) -> str:
    rel = os.path.relpath(path, root)
    parts = rel[:-3].split(os.sep)  # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _collect_functions(mod: ModuleInfo) -> None:
    """Register every def (top-level, method, nested) under a qualname."""

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}" if prefix else child.name
                a = child.args
                params = tuple(
                    p.arg
                    for p in (a.posonlyargs + a.args + a.kwonlyargs)
                )
                mod.functions[q] = FuncInfo(
                    module=mod.name, qualname=q, node=child, params=params
                )
                visit(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(mod.tree, "")


def _resolve_relative(current: str, level: int, target: str) -> str:
    """Dotted module for a `from ...X import Y` seen inside `current`."""
    parts = current.split(".")[:-1] if current else []  # current's package
    if level > 1:
        parts = parts[: len(parts) - (level - 1)]
    if target:
        parts = parts + target.split(".")
    return ".".join(parts)


def _collect_imports(index_modules: set, mod: ModuleInfo) -> None:
    """Map aliases to package modules / objects (function-level imports
    included — engine/paged.py imports models.api inside a traced body)."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                mod.imports[name] = ("external", alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = _resolve_relative(mod.name, node.level, node.module or "")
            else:
                base = node.module or ""
            for alias in node.names:
                name = alias.asname or alias.name
                as_module = f"{base}.{alias.name}" if base else alias.name
                if as_module in index_modules:
                    mod.imports[name] = ("module", as_module)
                elif base in index_modules:
                    mod.imports[name] = ("obj", base, alias.name)
                else:
                    mod.imports[name] = ("external", alias.name)


def build_index(root: str) -> PackageIndex:
    """Parse every .py under `root` (a package directory)."""
    index = PackageIndex(root=root)
    paths = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                paths.append(os.path.join(dirpath, f))
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        name = _module_name(root, path)
        mod = ModuleInfo(
            name=name,
            path=os.path.relpath(path, os.path.dirname(root.rstrip(os.sep))),
            tree=ast.parse(source, filename=path),
            lines=source.splitlines(),
        )
        _collect_functions(mod)
        index.modules[name] = mod
    names = set(index.modules)
    for mod in index.modules.values():
        _collect_imports(names, mod)
    return index


def dotted(node: ast.AST) -> Optional[str]:
    """`jax.lax.ppermute` -> "jax.lax.ppermute"; None for non-chains."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST, mod: ModuleInfo) -> bool:
    """True for `jax.jit` / `jit` (imported from jax) expressions."""
    d = dotted(node)
    return d in ("jax.jit", "jit")


def _jit_roots_from_decorators(mod: ModuleInfo) -> Iterator[tuple]:
    for fn in mod.functions.values():
        node = fn.node
        for dec in getattr(node, "decorator_list", ()):
            if _is_jit_expr(dec, mod):
                yield fn, None
            elif isinstance(dec, ast.Call):
                if _is_jit_expr(dec.func, mod):
                    yield fn, dec
                elif dotted(dec.func) in ("functools.partial", "partial"):
                    if dec.args and _is_jit_expr(dec.args[0], mod):
                        yield fn, dec


_TRACING_WRAPPERS = ("shard_map", "jax.shard_map", "self._shard")


def _jit_roots_from_calls(mod: ModuleInfo) -> Iterator[tuple]:
    """`jax.jit(fn, ...)` / `shard_map(body, ...)` with a Name argument
    that resolves to a function defined in this module."""
    by_name = {}
    for fn in mod.functions.values():
        by_name.setdefault(fn.qualname.rsplit(".", 1)[-1], []).append(fn)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        is_jit = _is_jit_expr(node.func, mod)
        if not is_jit and d not in _TRACING_WRAPPERS:
            continue
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name) and arg.id in by_name:
                for fn in by_name[arg.id]:
                    yield fn, (node if is_jit else None)


def _local_scope(fn: FuncInfo, mod: ModuleInfo) -> dict:
    """Names defined as nested functions directly inside `fn`."""
    prefix = fn.qualname + "."
    out = {}
    for q, f in mod.functions.items():
        if q.startswith(prefix) and "." not in q[len(prefix):]:
            out[q[len(prefix):]] = f
    return out


def _class_scope(fn: FuncInfo, mod: ModuleInfo) -> dict:
    """Sibling methods, for `self.method` edges."""
    if "." not in fn.qualname:
        return {}
    cls = fn.qualname.split(".")[0]
    prefix = cls + "."
    out = {}
    for q, f in mod.functions.items():
        if q.startswith(prefix) and "." not in q[len(prefix):]:
            out[q[len(prefix):]] = f
    return out


def _walk_own_body(fn: FuncInfo) -> Iterator[ast.AST]:
    """Walk `fn`'s body but NOT nested function bodies (they are their own
    graph nodes; the defining statement itself is yielded so a reference
    to the nested name still resolves)."""

    def walk(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            yield child
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield from walk(child)

    for stmt in fn.node.body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield from walk(stmt)


def _edges_for(fn: FuncInfo, mod: ModuleInfo, index: PackageIndex) -> set:
    """All package functions `fn` references (see module docstring)."""
    out = set()
    local = _local_scope(fn, mod)
    methods = _class_scope(fn, mod)
    local_fns = {f.qualname.rsplit(".", 1)[-1]: f
                 for q, f in mod.functions.items() if "." not in q}

    def resolve_name(name: str) -> Optional[FuncInfo]:
        if name in local:
            return local[name]
        if name in local_fns:
            return local_fns[name]
        imp = mod.imports.get(name)
        if imp and imp[0] == "obj":
            return index.get(imp[1], imp[2])
        return None

    for node in _walk_own_body(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            target = resolve_name(node.id)
            if target is not None:
                out.add(target.key)
        elif isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            if isinstance(node.value, ast.Name):
                base = node.value.id
                if base == "self" and node.attr in methods:
                    out.add(methods[node.attr].key)
                    continue
                imp = mod.imports.get(base)
                if imp and imp[0] == "module":
                    target = index.get(imp[1], node.attr)
                    if target is not None:
                        out.add(target.key)
            elif isinstance(node.value, ast.Call):
                # dynamic family dispatch: `family(cfg).embed(...)` — when
                # the inner call resolves to a package function, fan the
                # attribute out to every package module this module
                # imports (models/api.py imports exactly the families)
                inner = None
                if isinstance(node.value.func, ast.Name):
                    inner = resolve_name(node.value.func.id)
                if inner is not None:
                    for imp in mod.imports.values():
                        if imp[0] == "module":
                            target = index.get(imp[1], node.attr)
                            if target is not None:
                                out.add(target.key)
    return out


def jit_roots(index: PackageIndex) -> dict:
    """{(module, qualname): jit_site_or_None} for every traced root."""
    roots = {}
    for mod in index.modules.values():
        for fn, site in _jit_roots_from_decorators(mod):
            fn.is_jit_root = True
            fn.jit_site = site
            roots.setdefault(fn.key, site)
        for fn, site in _jit_roots_from_calls(mod):
            fn.is_jit_root = True
            if site is not None and fn.jit_site is None:
                fn.jit_site = site
            roots.setdefault(fn.key, site)
    return roots


def call_graph(index: PackageIndex) -> dict:
    """{func_key: set(func_key)} over the whole package."""
    graph = {}
    for mod in index.modules.values():
        for fn in mod.functions.values():
            graph[fn.key] = _edges_for(fn, mod, index)
    return graph


def traced_reachable(index: PackageIndex, extra_roots=()) -> set:
    """Keys of every function reachable from a jit root (the functions
    whose bodies execute inside a trace)."""
    graph = call_graph(index)
    seen = set()
    stack = list(jit_roots(index)) + list(extra_roots)
    while stack:
        key = stack.pop()
        if key in seen or key not in graph:
            continue
        seen.add(key)
        stack.extend(graph[key] - seen)
    return seen
