"""Package AST index + intra-package call graph + traced reachability.

The host-sync rule must know which functions execute INSIDE a jit trace:
linting file-by-file would either miss `sample_token` (ops/sampling.py,
called from every decode loop) or drown the host-side engine code in
false positives. So we parse every module in the package once, resolve
intra-package references (imports, module aliases, `self.` methods, the
models/api family dispatch), and walk the graph from the jit roots.

Jit roots — the functions whose BODIES are traced:
  * defs decorated with `jax.jit` / `functools.partial(jax.jit, ...)`;
  * functions passed by name to a `jax.jit(...)` call;
  * functions passed by name to `shard_map` / `jax.shard_map` /
    `self._shard(...)` (the parallel/ backends build their traced bodies
    as closures handed to a shard_map partial, then jit the result).

Edges — deliberately reference-based, not call-based: ANY Load of a name
that resolves to a package function adds an edge (`jax.lax.while_loop(
cond, body, init)` passes `body` without calling it; a reference is the
honest "may be traced" signal). Dynamic dispatch through
`family(cfg).embed(...)` (models/api.py) fans out to the same attribute
in every package module the dispatching module imports.

Thread-aware half (the host control plane): the same index also knows
the HOST roots — functions the runtime enters from outside any trace:

  * thread-spawn targets: `threading.Thread(target=f)`, `Timer(t, f)`,
    `executor.submit(f, ...)` — resolved like any reference (Name,
    `self.method`, nested def);
  * daemon/loop entry points the stdlib dispatches to dynamically:
    `do_GET`/`do_POST`/... HTTP handler methods, module-level `main`,
    `signal.signal(sig, f)` / `atexit.register(f)` targets.

`host_reachable()` is the closure from those roots; `decode_unreachable()
= host_reachable() - traced_reachable()` plus any function annotated
`# jaxlint: decode-unreachable -- reason` on (or directly above) its
def line — the DERIVED replacement for the hand-pinned decode-
UNREACHABLE fixture list tests/test_analysis.py used to grow per PR.
The thread-reach rule (analysis/rules/thread_reach.py) enforces that no
thread entry point or annotated function is ever traced-reachable.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterator, Optional

# modules whose names never resolve into the package
_EXTERNAL = {
    "jax", "jnp", "np", "numpy", "functools", "threading", "collections",
    "math", "json", "time", "os", "re", "ast",
}


@dataclass
class FuncInfo:
    """One function (or method, or nested closure) in the package."""

    module: str  # dotted module name relative to the package root
    qualname: str  # "decode" / "PipelineBackend._build_prefill.body"
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    params: tuple = ()
    is_jit_root: bool = False
    jit_site: Optional[ast.Call] = None  # the jit Call/decorator, if any

    @property
    def key(self) -> tuple:
        return (self.module, self.qualname)


@dataclass
class ModuleInfo:
    name: str  # dotted, relative to the package ("engine.generate")
    path: str
    tree: ast.Module
    lines: list
    functions: dict = field(default_factory=dict)  # qualname -> FuncInfo
    # alias -> ("module", dotted) | ("obj", dotted_module, name)
    #        | ("external", name)
    imports: dict = field(default_factory=dict)


@dataclass
class PackageIndex:
    root: str  # filesystem path of the package dir
    modules: dict = field(default_factory=dict)  # dotted name -> ModuleInfo

    def functions(self) -> Iterator[FuncInfo]:
        for mod in self.modules.values():
            yield from mod.functions.values()

    def get(self, module: str, qualname: str) -> Optional[FuncInfo]:
        mod = self.modules.get(module)
        return mod.functions.get(qualname) if mod else None

    def rel_path(self, module: str) -> str:
        return self.modules[module].path


def _module_name(root: str, path: str) -> str:
    rel = os.path.relpath(path, root)
    parts = rel[:-3].split(os.sep)  # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _collect_functions(mod: ModuleInfo) -> None:
    """Register every def (top-level, method, nested) under a qualname."""

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}" if prefix else child.name
                a = child.args
                params = tuple(
                    p.arg
                    for p in (a.posonlyargs + a.args + a.kwonlyargs)
                )
                mod.functions[q] = FuncInfo(
                    module=mod.name, qualname=q, node=child, params=params
                )
                visit(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(mod.tree, "")


def _resolve_relative(current: str, level: int, target: str) -> str:
    """Dotted module for a `from ...X import Y` seen inside `current`."""
    parts = current.split(".")[:-1] if current else []  # current's package
    if level > 1:
        parts = parts[: len(parts) - (level - 1)]
    if target:
        parts = parts + target.split(".")
    return ".".join(parts)


def _collect_imports(index_modules: set, mod: ModuleInfo) -> None:
    """Map aliases to package modules / objects (function-level imports
    included — engine/paged.py imports models.api inside a traced body)."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                mod.imports[name] = ("external", alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = _resolve_relative(mod.name, node.level, node.module or "")
            else:
                base = node.module or ""
            for alias in node.names:
                name = alias.asname or alias.name
                as_module = f"{base}.{alias.name}" if base else alias.name
                if as_module in index_modules:
                    mod.imports[name] = ("module", as_module)
                elif base in index_modules:
                    mod.imports[name] = ("obj", base, alias.name)
                else:
                    mod.imports[name] = ("external", alias.name)


def build_index(root: str) -> PackageIndex:
    """Parse every .py under `root` (a package directory)."""
    index = PackageIndex(root=root)
    paths = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                paths.append(os.path.join(dirpath, f))
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        name = _module_name(root, path)
        mod = ModuleInfo(
            name=name,
            path=os.path.relpath(path, os.path.dirname(root.rstrip(os.sep))),
            tree=ast.parse(source, filename=path),
            lines=source.splitlines(),
        )
        _collect_functions(mod)
        index.modules[name] = mod
    names = set(index.modules)
    for mod in index.modules.values():
        _collect_imports(names, mod)
    return index


def dotted(node: ast.AST) -> Optional[str]:
    """`jax.lax.ppermute` -> "jax.lax.ppermute"; None for non-chains."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST, mod: ModuleInfo) -> bool:
    """True for `jax.jit` / `jit` (imported from jax) expressions."""
    d = dotted(node)
    return d in ("jax.jit", "jit")


def _jit_roots_from_decorators(mod: ModuleInfo) -> Iterator[tuple]:
    for fn in mod.functions.values():
        node = fn.node
        for dec in getattr(node, "decorator_list", ()):
            if _is_jit_expr(dec, mod):
                yield fn, None
            elif isinstance(dec, ast.Call):
                if _is_jit_expr(dec.func, mod):
                    yield fn, dec
                elif dotted(dec.func) in ("functools.partial", "partial"):
                    if dec.args and _is_jit_expr(dec.args[0], mod):
                        yield fn, dec


_TRACING_WRAPPERS = ("shard_map", "jax.shard_map", "self._shard")


def _jit_roots_from_calls(mod: ModuleInfo) -> Iterator[tuple]:
    """`jax.jit(fn, ...)` / `shard_map(body, ...)` with a Name argument
    that resolves to a function defined in this module."""
    by_name = {}
    for fn in mod.functions.values():
        by_name.setdefault(fn.qualname.rsplit(".", 1)[-1], []).append(fn)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        is_jit = _is_jit_expr(node.func, mod)
        if not is_jit and d not in _TRACING_WRAPPERS:
            continue
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name) and arg.id in by_name:
                for fn in by_name[arg.id]:
                    yield fn, (node if is_jit else None)


def _local_scope(fn: FuncInfo, mod: ModuleInfo) -> dict:
    """Names defined as nested functions directly inside `fn`."""
    prefix = fn.qualname + "."
    out = {}
    for q, f in mod.functions.items():
        if q.startswith(prefix) and "." not in q[len(prefix):]:
            out[q[len(prefix):]] = f
    return out


def _class_scope(fn: FuncInfo, mod: ModuleInfo) -> dict:
    """Sibling methods, for `self.method` edges."""
    if "." not in fn.qualname:
        return {}
    cls = fn.qualname.split(".")[0]
    prefix = cls + "."
    out = {}
    for q, f in mod.functions.items():
        if q.startswith(prefix) and "." not in q[len(prefix):]:
            out[q[len(prefix):]] = f
    return out


def _walk_own_body(fn: FuncInfo) -> Iterator[ast.AST]:
    """Walk `fn`'s body but NOT nested function bodies (they are their own
    graph nodes; the defining statement itself is yielded so a reference
    to the nested name still resolves)."""

    def walk(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            yield child
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield from walk(child)

    for stmt in fn.node.body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield from walk(stmt)


def _edges_for(fn: FuncInfo, mod: ModuleInfo, index: PackageIndex) -> set:
    """All package functions `fn` references (see module docstring)."""
    out = set()
    local = _local_scope(fn, mod)
    methods = _class_scope(fn, mod)
    local_fns = {f.qualname.rsplit(".", 1)[-1]: f
                 for q, f in mod.functions.items() if "." not in q}

    def resolve_name(name: str) -> Optional[FuncInfo]:
        if name in local:
            return local[name]
        if name in local_fns:
            return local_fns[name]
        imp = mod.imports.get(name)
        if imp and imp[0] == "obj":
            return index.get(imp[1], imp[2])
        return None

    for node in _walk_own_body(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            target = resolve_name(node.id)
            if target is not None:
                out.add(target.key)
        elif isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            if isinstance(node.value, ast.Name):
                base = node.value.id
                if base == "self" and node.attr in methods:
                    out.add(methods[node.attr].key)
                    continue
                imp = mod.imports.get(base)
                if imp and imp[0] == "module":
                    target = index.get(imp[1], node.attr)
                    if target is not None:
                        out.add(target.key)
            elif isinstance(node.value, ast.Call):
                # dynamic family dispatch: `family(cfg).embed(...)` — when
                # the inner call resolves to a package function, fan the
                # attribute out to every package module this module
                # imports (models/api.py imports exactly the families)
                inner = None
                if isinstance(node.value.func, ast.Name):
                    inner = resolve_name(node.value.func.id)
                if inner is not None:
                    for imp in mod.imports.values():
                        if imp[0] == "module":
                            target = index.get(imp[1], node.attr)
                            if target is not None:
                                out.add(target.key)
    return out


def jit_roots(index: PackageIndex) -> dict:
    """{(module, qualname): jit_site_or_None} for every traced root."""
    roots = {}
    for mod in index.modules.values():
        for fn, site in _jit_roots_from_decorators(mod):
            fn.is_jit_root = True
            fn.jit_site = site
            roots.setdefault(fn.key, site)
        for fn, site in _jit_roots_from_calls(mod):
            fn.is_jit_root = True
            if site is not None and fn.jit_site is None:
                fn.jit_site = site
            roots.setdefault(fn.key, site)
    return roots


def call_graph(index: PackageIndex) -> dict:
    """{func_key: set(func_key)} over the whole package."""
    graph = {}
    for mod in index.modules.values():
        for fn in mod.functions.values():
            graph[fn.key] = _edges_for(fn, mod, index)
    return graph


def traced_reachable(index: PackageIndex, extra_roots=()) -> set:
    """Keys of every function reachable from a jit root (the functions
    whose bodies execute inside a trace)."""
    graph = call_graph(index)
    seen = set()
    stack = list(jit_roots(index)) + list(extra_roots)
    while stack:
        key = stack.pop()
        if key in seen or key not in graph:
            continue
        seen.add(key)
        stack.extend(graph[key] - seen)
    return seen


# -- thread-aware host-plane reachability ------------------------------------

# callables that take a function and run it on another thread / later:
# the first positional arg (Timer: second) or target= is a THREAD root
_SPAWN_CALLS = {"threading.Thread", "Thread"}
_TIMER_CALLS = {"threading.Timer", "Timer"}
# dynamic registration sinks whose target runs on the host event plane
_REGISTER_CALLS = {"signal.signal", "atexit.register"}

# stdlib-dispatched entry points: BaseHTTPRequestHandler methods and CLI
# mains are never referenced by name inside the package, but the host
# runtime enters them — they root the host plane like a thread target
_HANDLER_RE_ATTRS = ("do_", )
# stdlib hook overrides the server machinery calls by name
_HANDLER_OVERRIDES = {"log_message", "handle_error", "finish_request"}

_ANNOTATION_RE = re.compile(
    r"#\s*jaxlint:\s*decode-unreachable\s*(?:--+|—|–|:)?\s*(.*)"
)


def resolve_target(node: ast.AST, fn: FuncInfo, mod: ModuleInfo,
                   index: PackageIndex) -> list:
    """Package FuncInfos a spawn-target expression may name: a bare Name
    (local def, module function, imported function), `self.method`
    (every class's method of that name in the module — instance typing
    is out of scope for an AST pass, and a wrong extra root only widens
    the host plane), or `module.attr`."""
    out = []
    if isinstance(node, ast.Name):
        local = _local_scope(fn, mod)
        if node.id in local:
            return [local[node.id]]
        for q, f in mod.functions.items():
            if "." not in q and q == node.id:
                return [f]
        imp = mod.imports.get(node.id)
        if imp and imp[0] == "obj":
            t = index.get(imp[1], imp[2])
            if t is not None:
                return [t]
        return out
    if isinstance(node, ast.Attribute):
        if isinstance(node.value, ast.Name):
            if node.value.id == "self":
                # every method of this name in the module: the spawning
                # method's own class first, but Thread(target=obj._run)
                # style spawns resolve by name across classes too
                for q, f in mod.functions.items():
                    if q.endswith("." + node.attr):
                        out.append(f)
                return out
            imp = mod.imports.get(node.value.id)
            if imp and imp[0] == "module":
                t = index.get(imp[1], node.attr)
                if t is not None:
                    return [t]
        # obj.attr where obj is a local variable: name-match across the
        # module (same honesty-over-precision trade as self.*)
        for q, f in mod.functions.items():
            if q.endswith("." + node.attr) or q == node.attr:
                out.append(f)
    return out


def _spawn_target_exprs(call: ast.Call):
    """The expressions a spawn/submit/register call runs later."""
    d = dotted(call.func)
    if d in _SPAWN_CALLS:
        for kw in call.keywords:
            if kw.arg == "target":
                yield kw.value
        return
    if d in _TIMER_CALLS:
        if len(call.args) >= 2:
            yield call.args[1]
        for kw in call.keywords:
            if kw.arg == "function":
                yield kw.value
        return
    if d in _REGISTER_CALLS:
        if d == "signal.signal" and len(call.args) >= 2:
            yield call.args[1]
        elif d == "atexit.register" and call.args:
            yield call.args[0]
        return
    if isinstance(call.func, ast.Attribute) and call.func.attr == "submit":
        # executor.submit(fn, ...) — any receiver; a non-executor .submit
        # with a function arg still runs host-side work, so over-approx
        if call.args:
            yield call.args[0]


def _scope_modules(mod: ModuleInfo, index: PackageIndex) -> list:
    """This module plus every package module it imports (module aliases
    AND the source modules of `from X import name` object imports) —
    the fan-out scope for name-based method resolution."""
    names = {mod.name}
    for imp in mod.imports.values():
        if imp[0] == "module":
            names.add(imp[1])
        elif imp[0] == "obj":
            names.add(imp[1])
    return [index.modules[n] for n in names if n in index.modules]


def _class_inits(mod_scope: list, cls_name: str) -> list:
    """`Cls(...)` instantiation edges: the constructor bodies the host
    plane enters (`__init__`, dataclass `__post_init__`)."""
    out = []
    for m in mod_scope:
        for suffix in ("__init__", "__post_init__"):
            f = m.functions.get(f"{cls_name}.{suffix}")
            if f is not None:
                out.append(f)
    return out


def _leaf_map(mods) -> dict:
    """{leaf name: [func keys]} over the given modules' functions."""
    out: dict = {}
    for m in mods:
        for q, f in m.functions.items():
            out.setdefault(q.rsplit(".", 1)[-1], []).append(f.key)
    return out


def _walk_with_lambdas(fn: FuncInfo) -> Iterator[ast.AST]:
    """Like _walk_own_body but DESCENDS into lambdas: lambdas are not
    separate graph nodes (only defs are registered), so their bodies —
    `key=lambda c: self.victim_key(...)` — belong to the enclosing
    function for host-plane purposes."""

    def walk(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            yield child
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from walk(child)

    for stmt in fn.node.body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield from walk(stmt)


def _host_edges_for(fn: FuncInfo, mod: ModuleInfo, index: PackageIndex,
                    scoped_leaves: dict, global_leaves: dict) -> set:
    """Reference edges PLUS the dynamic-dispatch approximations host
    code actually uses: `Cls(...)` -> `Cls.__init__`, and `obj.method` /
    `obj.prop` resolved BY NAME — first across this module and
    everything it imports, falling back to the whole package when the
    scoped lookup finds nothing (`serve_chain(shadow, ...)` calls a
    ShadowStore method without importing engine/shadow). The fan-out
    over-approximates (instance typing is beyond an AST pass); that is
    safe here because decode_unreachable() subtracts the traced set —
    an extra host edge can only widen the proven-host-only set toward
    the truth, never contaminate the traced closure."""
    out = set(_edges_for(fn, mod, index))
    scope = _scope_modules(mod, index)
    local_classes = {
        q.split(".")[0] for q in mod.functions if "." in q
    }
    for node in _walk_with_lambdas(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                cls = None
                if f.id in local_classes:
                    cls = f.id
                else:
                    imp = mod.imports.get(f.id)
                    if imp and imp[0] == "obj":
                        cls = imp[2]
                if cls:
                    for t in _class_inits(scope, cls):
                        out.add(t.key)
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            base = node.value
            if isinstance(base, ast.Name) and base.id in mod.imports \
                    and mod.imports[base.id][0] == "module":
                continue  # module.attr: precise resolution above
            keys = scoped_leaves.get(node.attr)
            if keys is None:
                keys = global_leaves.get(node.attr, ())
            out.update(keys)
    return out


def thread_roots(index: PackageIndex) -> dict:
    """{func_key: (module, lineno)} for every function handed to a
    thread/timer/executor spawn or a signal/atexit registration — the
    entry points of the host control plane's own threads."""
    roots: dict = {}
    for mod in index.modules.values():
        for fn in mod.functions.values():
            for node in _walk_own_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                for expr in _spawn_target_exprs(node):
                    for target in resolve_target(expr, fn, mod, index):
                        roots.setdefault(
                            target.key, (mod.path, node.lineno)
                        )
    return roots


def host_roots(index: PackageIndex) -> dict:
    """Thread roots plus the stdlib-dispatched entry points (HTTP
    `do_*` handler methods, module-level `main`) — everything the host
    runtime enters from outside any trace."""
    roots = dict(thread_roots(index))
    for mod in index.modules.values():
        for fn in mod.functions.values():
            leaf = fn.qualname.rsplit(".", 1)[-1]
            if leaf == "main" and "." not in fn.qualname:
                roots.setdefault(fn.key, (mod.path, fn.node.lineno))
            elif "." in fn.qualname and (
                any(leaf.startswith(p) for p in _HANDLER_RE_ATTRS)
                or leaf in _HANDLER_OVERRIDES
            ):
                roots.setdefault(fn.key, (mod.path, fn.node.lineno))
    return roots


def host_call_graph(index: PackageIndex) -> dict:
    """{func_key: set(func_key)} with the host-plane edge enrichments
    (constructor + name-based method/property dispatch)."""
    global_leaves = _leaf_map(index.modules.values())
    graph = {}
    for mod in index.modules.values():
        scoped_leaves = _leaf_map(_scope_modules(mod, index))
        for fn in mod.functions.values():
            graph[fn.key] = _host_edges_for(
                fn, mod, index, scoped_leaves, global_leaves
            )
    return graph


def host_reachable(index: PackageIndex) -> set:
    """Closure over the ENRICHED graph from the host roots: the host
    control plane. May OVERLAP traced_reachable — host code builds and
    launches jitted programs, so builder references leak in; callers
    wanting the proven-host-only set use decode_unreachable()."""
    graph = host_call_graph(index)
    seen = set()
    stack = list(host_roots(index))
    while stack:
        key = stack.pop()
        if key in seen or key not in graph:
            continue
        seen.add(key)
        stack.extend(graph[key] - seen)
    return seen


def annotated_decode_unreachable(index: PackageIndex) -> dict:
    """{func_key: reason} for every `# jaxlint: decode-unreachable`
    annotation sitting on (or directly above) a def line. reason may be
    "" — the thread-reach rule reports reasonless annotations."""
    out: dict = {}
    for mod in index.modules.values():
        by_line = {}
        for i, text in enumerate(mod.lines, start=1):
            m = _ANNOTATION_RE.search(text)
            if m is None:
                continue
            target = i + 1 if text.lstrip().startswith("#") else i
            by_line[target] = m.group(1).strip()
        if not by_line:
            continue
        for fn in mod.functions.values():
            # decorators push the def line down; accept the annotation on
            # the def line itself or on the line the decorator list starts
            lines = [fn.node.lineno]
            decs = getattr(fn.node, "decorator_list", ())
            if decs:
                lines.append(decs[0].lineno - 1)
            for ln in lines:
                if ln in by_line:
                    out[fn.key] = by_line[ln]
                    break
    return out


def decode_unreachable(index: PackageIndex,
                       traced: Optional[set] = None) -> set:
    """The DERIVED decode-unreachable set: host-plane functions that are
    provably outside every trace (host-reachable minus traced-reachable)
    plus the annotated escape hatch. This is what replaced the manual
    pin fixtures in tests/test_analysis.py — the thread-reach rule
    guarantees the annotated half really is disjoint from the traced
    set, so consumers can treat the union as proven."""
    if traced is None:
        traced = traced_reachable(index)
    derived = host_reachable(index) - traced
    derived.update(annotated_decode_unreachable(index))
    return derived
