"""Rule engine: run the AST rules, apply per-line suppressions, report.

Suppression syntax (per line, reason MANDATORY — an unexplained
suppression is itself a violation):

    x.item()  # jaxlint: disable=host-sync -- eager branch, guarded above

A standalone `# jaxlint: disable=...` comment suppresses the NEXT line
(for lines too long to carry the comment). `disable=all` silences every
rule on that line. The separator before the reason may be `--`, an
em/en dash, or a colon.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

from .callgraph import PackageIndex, build_index

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([\w,\-]+)\s*(?:--+|—|–|:)?\s*(.*)"
)


@dataclass(frozen=True)
class Diagnostic:
    """One finding, printable as `path:line: [rule] message`."""

    path: str  # package-relative file path
    line: int  # 1-indexed
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass(frozen=True)
class Suppression:
    rules: frozenset  # rule ids, or {"all"}
    reason: str
    line: int  # line the suppression APPLIES to

    def covers(self, rule: str) -> bool:
        return "all" in self.rules or rule in self.rules


def parse_suppressions(lines) -> tuple:
    """(by_line: {lineno: Suppression}, bad: [Diagnostic-args]) — a
    suppression with no reason is reported, not honored."""
    by_line = {}
    bad = []
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = frozenset(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = m.group(2).strip()
        # a standalone comment line suppresses the next line
        target = i + 1 if text.lstrip().startswith("#") else i
        if not reason:
            bad.append((i, "suppression without a reason — write "
                           "`# jaxlint: disable=RULE -- why it is safe`"))
            continue
        by_line[target] = Suppression(rules=rules, reason=reason, line=target)
    return by_line, bad


def run_lint(root: str, rules=None, index: Optional[PackageIndex] = None):
    """Run the rule set over the package at `root`.

    Returns (diagnostics, suppressed_count). `rules`: iterable of rule
    ids (default: all registered rules).
    """
    from .rules import ALL_RULES

    if index is None:
        index = build_index(root)
    selected = list(ALL_RULES) if rules is None else list(rules)
    unknown = [r for r in selected if r not in ALL_RULES]
    if unknown:
        raise ValueError(f"unknown rule(s): {unknown}; have {sorted(ALL_RULES)}")

    raw: list = []
    for rule_id in selected:
        raw.extend(ALL_RULES[rule_id](index))

    # suppression filtering, per file
    supp_by_file = {}
    diagnostics = []
    suppressed = 0
    for mod in index.modules.values():
        by_line, bad = parse_suppressions(mod.lines)
        supp_by_file[mod.path] = by_line
        for line, msg in bad:
            diagnostics.append(
                Diagnostic(path=mod.path, line=line, rule="bad-suppression",
                           message=msg)
            )
    for d in sorted(raw, key=lambda d: (d.path, d.line, d.rule)):
        supp = supp_by_file.get(d.path, {}).get(d.line)
        if supp is not None and supp.covers(d.rule):
            suppressed += 1
            continue
        diagnostics.append(d)
    return diagnostics, suppressed


def format_diagnostics(diagnostics, suppressed: int = 0) -> str:
    out = [d.format() for d in diagnostics]
    tail = f"{len(diagnostics)} violation(s)"
    if suppressed:
        tail += f", {suppressed} suppressed"
    out.append(tail)
    return "\n".join(out)
